"""Protocol server: TCP acceptor pool + request dispatcher.

The ranch listener (100 acceptors, max 1024 conns, port 8087 —
/root/reference/src/antidote_pb_sup.erl:47-56) becomes a
``ThreadingTCPServer``; the decode→process→encode loop with error replies
mirrors ``antidote_pb_protocol:loop/handle``
(/root/reference/src/antidote_pb_protocol.erl:51-88), and the dispatch
table mirrors ``antidote_pb_process:process/1``
(/root/reference/src/antidote_pb_process.erl:49-135).

The node's transaction manager is a single commit stream, so requests are
serialized through one lock — concurrency buys pipelining of socket IO,
matching the single-writer-per-partition design (SURVEY §2.10 row 2).
"""

from __future__ import annotations

import itertools
import logging
import queue
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from antidote_tpu import faults as _faults
from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.overload import (
    AdmissionGate,
    BusyError,
    ColdMiss,
    DeadlineExceeded,
    ForwardFailed,
    InsufficientRightsError,
    NotOwnerError,
    ReadOnlyError,
    ReplicaLagging,
    TenantBusyError,
    check_deadline,
    deadline_from_ms,
)
from antidote_tpu.tenancy import TenantLanes, TenantRegistry
from antidote_tpu.proto import apb
from antidote_tpu.proto.proxy import ProxyExhausted, ProxyPlane
from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    encode,
    encode_value,
    freeze,
    read_frame_buffered,
    write_frame_body,
    write_message,
)
from antidote_tpu.txn.manager import AbortError, Transaction

DEFAULT_PORT = 8087
log = logging.getLogger(__name__)

_STOP = object()


class _StaticWork:
    """One client's static read/update — or an interactive COMMIT — parked
    at the batch gate / locked-plane merge point."""

    __slots__ = ("kind", "objects", "updates", "clock", "event", "result",
                 "error", "deadline", "t_submit", "wants_bytes",
                 "reply_bytes", "txid", "tenant")

    def __init__(self, kind, objects=None, updates=None, clock=None,
                 deadline=None, wants_bytes=False, txid=None, tenant=None):
        self.kind = kind
        self.objects = objects
        self.updates = updates
        self.clock = clock
        #: tenant lane this work rides (ISSUE 19): derived from the
        #: bucket namespace / request tag at decode; None = default
        self.tenant = tenant
        #: interactive commit works (kind == "commit") carry the txid;
        #: the locked worker resolves it to the registered Transaction
        #: at the merge point
        self.txid = txid
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        #: absolute monotonic deadline (None = none): checked when the
        #: batch dispatcher DEQUEUES the work — a request that outlived
        #: its caller while parked is aborted, not executed
        self.deadline: Optional[float] = deadline
        #: submit timestamp (stage_parked histogram)
        self.t_submit = 0.0
        #: native-dialect reads ask the writeback stage to serialize the
        #: reply frame for them (batched reply serialization: one tight
        #: encode loop instead of per-connection wakeup-then-frame)
        self.wants_bytes = wants_bytes
        self.reply_bytes: Optional[bytes] = None


class RawReply:
    """A fully-framed response produced by the writeback stage — the
    handler sends the bytes as-is."""

    __slots__ = ("buf",)

    def __init__(self, buf: bytes):
        self.buf = buf


class _EpochReadBatch:
    """A launched (but unmaterialized) merged epoch-read batch in flight
    between the dispatcher's launch stage and the writeback stage: device
    handles plus the per-work result spans."""

    __slots__ = ("pending", "works", "spans", "vc_list")

    def __init__(self, pending, works, spans, vc_list):
        self.pending = pending
        self.works = works
        self.spans = spans
        self.vc_list = vc_list


def _decode_objects(objs):
    return [(freeze(k), t, b) for k, t, b in (freeze(o) for o in objs)]


def _decode_updates(ups):
    return [(freeze(k), t, b, freeze(op)) for k, t, b, op in
            (freeze(u) for u in ups)]


def _vc(x) -> Optional[np.ndarray]:
    # sync-ok: converts a wire-decoded int list, never a jax array
    return None if x is None else np.asarray(x, np.int32)


class ProtocolServer:
    def __init__(self, node: AntidoteNode, host: str = "127.0.0.1",
                 port: int = 0, interdc=None, max_connections: int = 1024,
                 batch_static: bool = True, max_in_flight: int = 256,
                 max_in_flight_per_client: int = 64, queue_max: int = 4096,
                 default_deadline_ms: Optional[float] = None,
                 epoch_tick_ms: float = 100.0,
                 snapshot_cache_size: Optional[int] = None,
                 group_commit_window_us: float = 0.0,
                 follower=None, native_frontend: bool = False,
                 native_mirror_cap: int = 1 << 18,
                 server_proxy: bool = True, tenants=None):
        self.node = node
        #: multi-tenant QoS (ISSUE 19): weights + caps for every tenant
        #: this node serves.  An untenanted node gets a registry holding
        #: only the default lane — every tenant code path then
        #: degenerates to the old single-queue behavior.
        self.tenants: TenantRegistry = tenants or TenantRegistry()
        #: DCReplica for the descriptor/connect requests (optional)
        self.interdc = interdc
        #: FollowerReplica when this server fronts a read replica
        #: (ISSUE 9): writes/txns answer typed not_owner redirects, and
        #: session reads pass the follower's applied-clock gate (park
        #: briefly, then typed lagging redirect) before dispatch
        self.follower = follower
        if follower is not None and interdc is None:
            self.interdc = follower
        if follower is not None and not batch_static:
            # the inline (batch_static=False) read path calls
            # node.read_objects under only the dispatch lock, but a
            # follower's pump thread mutates the live head buffers via
            # apply_effects — the commit-lock read discipline lives in
            # the batch workers, so the combination would race (the
            # "buffer donated" crash class); refuse it loudly
            raise ValueError(
                "a follower server requires batch_static=True (the "
                "inline read path bypasses the replica's commit-lock "
                "read discipline)")
        #: symmetric serving fabric (ISSUE 17): on a follower, out-of-arc
        #: session reads proxy one hop to the arc owner and writes/txns
        #: forward to the owner write plane instead of bouncing typed
        #: redirects; ``server_proxy=False`` is the operator escape hatch
        #: back to the PR 9 refuse-and-redirect behavior
        self.proxy: Optional[ProxyPlane] = None
        self._server_proxy = bool(server_proxy)
        self._lock = threading.Lock()
        self._txns: Dict[int, Transaction] = {}
        #: metric sink for the overload planes: the node's own registry
        #: when it has one; a ClusterNode facade exposes its member's
        #: (one registry per process either way)
        self.metrics = getattr(node, "metrics", None)
        if self.metrics is None:
            inner = getattr(getattr(node, "member", None), "node", None)
            self.metrics = getattr(inner, "metrics", None)
        if self.metrics is None:
            from antidote_tpu.obs import NodeMetrics

            self.metrics = NodeMetrics()
        if follower is not None and self._server_proxy:
            self.proxy = ProxyPlane(follower, self.metrics)
        #: overload admission (PR 4): global + per-client (peer host)
        #: in-flight caps.  Past a cap, the request is answered with a
        #: typed busy error carrying a retry-after hint — never parked
        #: forever (the riak_core vnode overload answer, {error,
        #: overload}).  Per-HOST, not per-socket: each connection's
        #: handler thread is serial, so per-socket in-flight never
        #: exceeds 1 — bounding a client machine's whole connection
        #: fleet is what actually prevents monopolization
        self.admission = AdmissionGate(
            max_in_flight, max_in_flight_per_client,
            gauge=self.metrics.in_flight, tenants=self.tenants,
        )
        #: default per-request deadline (ms) when the client sends none;
        #: None = requests without a deadline_ms field never expire
        self.default_deadline_ms = default_deadline_ms
        self._conn_ids = itertools.count(1)
        #: cross-connection batch gate (r4 VERDICT item 3): static
        #: reads/updates from concurrent connections coalesce into single
        #: device launches instead of one launch per socket — the wire
        #: analogue of SURVEY §2.10 "batch thousands of reads per launch"
        #: (the reference scales the same path with 20 read servers per
        #: partition, /root/reference/include/antidote.hrl:28)
        self.batch_static = batch_static
        self._closing = False
        #: BOUNDED: a full gate answers busy instead of buffering without
        #: limit (admission usually sheds first; this cap is the backstop
        #: against a stalled dispatcher).  Per-tenant bounded LANES with
        #: deficit-round-robin dequeue (ISSUE 19): a backlogged tenant
        #: fills its OWN lane and sheds typed tenant_busy there, instead
        #: of occupying the shared budget everyone else's requests ride.
        self._static_q = TenantLanes(self.tenants, queue_max,
                                     name="static batch gate")
        self._batch_max = 1024
        #: per-handler-thread scratch (stage_decode timing)
        self._tls = threading.local()
        # --- staged serving pipeline (ISSUE 5) -------------------------
        #: serving-epoch publication cadence for the dedicated ticker
        self.epoch_tick_ms = epoch_tick_ms
        txm = getattr(node, "txm", None)
        if txm is not None:
            # the group-commit merge point caps any single tenant's
            # share of one merged batch (weight-proportional rounds)
            txm.tenants = self.tenants
        #: lock-split epoch reads need the single-node txn manager (the
        #: cluster facade routes through 2PC) and the batch dispatcher;
        #: epoch_tick_ms <= 0 disables the whole epoch plane (operator
        #: escape hatch back to the locked serving path)
        self._epoch_reads = bool(batch_static and txm is not None
                                 and epoch_tick_ms > 0)
        if self._epoch_reads:
            txm.enable_serving_epochs()
            self._epoch_reads = txm.serving_epochs  # clocksi-only
            if snapshot_cache_size is not None:
                txm.store.snapshot_cache_cap = int(snapshot_cache_size)
            if txm.store.metrics is None:
                txm.store.metrics = self.metrics
        #: mesh serving plane (ISSUE 10): the LAUNCH stage routes mesh
        #: tables through per-shard [P, M'] gathers, which pad per
        #: shard — scale the merge chunk so each DEVICE still sees a
        #: full batch (chunk/P objects land on each device slice)
        mesh = getattr(getattr(txm, "store", None), "mesh", None) \
            if txm is not None else None
        self._epoch_chunk = self.EPOCH_LAUNCH_CHUNK * (
            mesh.n_devices if mesh is not None else 1)
        #: launched-but-unmaterialized epoch read batches between the
        #: dispatcher and the writeback worker.  BOUNDED: a lagging
        #: writeback stage backpressures the dispatcher (which then
        #: backpressures the bounded batch gate) instead of queueing
        #: device handles without limit.
        self._writeback_q: "queue.Queue" = queue.Queue(maxsize=16)
        #: the LOCKED plane's feed: update groups, interactive COMMITs
        #: (the cross-connection group-commit merge point, ISSUE 6) and
        #: reads the epoch cannot serve, processed by a dedicated worker
        #: so a commit group (or an XLA compile hiding inside one) never
        #: parks the dispatcher's read-launch stage.  BOUNDED: past the
        #: cap the work sheds with a typed busy error, same as the gate
        #: — per-tenant lanes + DRR here too (the merge point is where a
        #: write storm actually queues)
        self._locked_q = TenantLanes(self.tenants, queue_max,
                                     name="locked plane")
        #: optional gather window at the merge point: after the locked
        #: worker's first dequeue it keeps draining up to this long, so
        #: moderate-load commit groups widen before taking the commit
        #: lock once.  0 (default) = natural batching only (whatever
        #: queued during the previous group's execution).
        self._group_window_s = max(0.0, float(group_commit_window_us)) / 1e6
        self._ticker_stop = threading.Event()
        if batch_static:
            self._batcher = threading.Thread(
                target=self._static_loop, daemon=True,
                name="antidote-proto-batch",
            )
            self._batcher.start()
            self._writeback = threading.Thread(
                target=self._writeback_loop, daemon=True,
                name="antidote-proto-writeback",
            )
            self._writeback.start()
            self._locked_worker = threading.Thread(
                target=self._locked_loop, daemon=True,
                name="antidote-proto-locked",
            )
            self._locked_worker.start()
        #: the ticker runs whenever a txn manager exists — even with the
        #: epoch plane disabled (gr protocol / epoch_tick_ms <= 0) it
        #: still drives the LOCKED path's per-table epoch ladder, which
        #: used to piggyback on static-batch traffic
        self._ticker_runs = bool(batch_static and txm is not None)
        if self._ticker_runs:
            self._ticker = threading.Thread(
                target=self._epoch_ticker, daemon=True,
                name="antidote-epoch-ticker",
            )
            self._ticker.start()
        #: connection cap (the reference's ranch listener caps at 1024,
        #: /root/reference/src/antidote_pb_sup.erl:47-56).  The accept
        #: loop blocks on the semaphore when the cap is reached, so
        #: excess connections queue in the kernel listen backlog instead
        #: of exhausting server threads — ranch's backpressure shape.
        self.max_connections = max_connections
        self._conn_slots = threading.BoundedSemaphore(max_connections)
        handler = self._make_handler()
        conn_slots = self._conn_slots

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            closing = False
            # while the accept loop parks on the cap, excess connections
            # must queue in the kernel listen backlog (ranch's shape) —
            # the socketserver default of 5 would drop their SYNs
            request_queue_size = max_connections

            def shutdown(self):
                self.closing = True
                super().shutdown()

            def process_request(self, request, client_address):
                # hold the accept loop until a slot frees: backpressure,
                # not thread-per-connection without bound.  Poll so a
                # shutdown() issued while the cap is saturated can still
                # unpark the serve_forever loop instead of deadlocking.
                while not conn_slots.acquire(timeout=0.1):
                    if self.closing:
                        self.shutdown_request(request)
                        return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    conn_slots.release()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    conn_slots.release()

        # --- native serving front-end (ISSUE 16) -----------------------
        #: a C++ epoll thread owning accept / framing / hot-read decode /
        #: admission / whole-batch cache hits on the ADVERTISED port;
        #: Python sees only drained misses, writes, txns and apb frames.
        #: The socketserver plane stays bound (ephemeral port) as the
        #: fallback path — and remains the only plane when the native
        #: module can't load (NativeFrontend.create → None).
        self.native = None
        self._native_drain = None
        if native_frontend:
            from antidote_tpu.proto.native_frontend import NativeFrontend

            self.native = NativeFrontend.create(
                host, port, max_connections, max_in_flight,
                max_in_flight_per_client, mirror_cap=native_mirror_cap)
        self._server = Server(
            (host, port if self.native is None else 0), handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"antidote-proto:{self.port}",
        )
        self._thread.start()
        if self.native is not None:
            self.port = self.native.port
            # fast-serve needs the epoch plane on an OWNER, and every
            # armed frontend.* fault rule must keep firing — rules are
            # applied Python-side per drained frame, so a natively-served
            # hit would bypass them; with any armed, everything crosses
            if (self._epoch_reads and self.follower is None
                    and not _faults.armed_prefix("frontend.")):
                self.node.txm.store.native_mirror = self.native
            else:
                self.native.set_fast_serve(False)
            self._native_drain = threading.Thread(
                target=self._native_drain_loop, daemon=True,
                name="antidote-native-drain",
            )
            self._native_drain.start()

    # ------------------------------------------------------------------
    def _make_handler(server_self):
        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # txns this connection started and has not finished: a
                # dropped connection must not pin open transactions (they
                # hold the certification-GC floor — manager._open_snaps —
                # forever; the reference's coordinator FSMs die with the
                # client process and roll back the same way)
                conn_txns = set()
                try:
                    self._serve(conn_txns)
                finally:
                    for txid in conn_txns:
                        server_self._abort_orphan(txid)

            def _serve(self, conn_txns):
                # admission key = peer host: one client machine's whole
                # connection fleet shares one per-client budget
                try:
                    client_id = self.request.getpeername()[0]
                except OSError:
                    client_id = f"conn{next(server_self._conn_ids)}"
                metrics = server_self.metrics
                # buffered framing: header + body in ~one syscall each
                rfile = self.request.makefile("rb")
                while True:
                    try:
                        frame = read_frame_buffered(rfile)
                    except (ConnectionError, OSError, ValueError):
                        return
                    # frontend.recv fault site — the Python-plane twin
                    # of the native drain worker's (chaos parity: the
                    # same plan wrecks frames on either accept path)
                    frame = server_self._frame_fault(frame)
                    if frame is None:
                        return
                    # ADMISSION (PR 4): acquire an in-flight slot before
                    # any decode/dispatch work.  Past the global or
                    # per-client cap the request is answered with a
                    # typed busy error + retry-after hint — the client
                    # backs off, the server never queues unboundedly.
                    t0 = time.monotonic()
                    try:
                        server_self.admission.enter(client_id)
                    except BusyError as e:
                        metrics.shed.inc(plane="server")
                        if not self._reply_error(frame, "busy", e):
                            return
                        continue
                    # decode-stage clock: runs until the work parks at
                    # the batch gate (observed in _submit)
                    server_self._tls.t0 = t0
                    try:
                        if not self._handle_admitted(frame, conn_txns):
                            return
                    finally:
                        server_self.admission.exit(client_id)
                        metrics.server_request_seconds.observe(
                            time.monotonic() - t0)

            def _reply_error(self, frame, kind: str, e) -> bool:
                """Typed error reply in the FRAME'S dialect; False when
                the connection died mid-write."""
                retry_ms = int(getattr(e, "retry_after_ms", 0))
                try:
                    if frame and frame[0] in apb.APB_REQUEST_CODES:
                        write_frame_body(self.request, apb.overload_error(
                            kind, str(e), retry_ms))
                    else:
                        resp = {"error": kind, "detail": str(e)}
                        if retry_ms:
                            resp["retry_after_ms"] = retry_ms
                        write_message(self.request,
                                      MessageCode.ERROR_RESP, resp)
                    return True
                except (ConnectionError, OSError):
                    return False

            def _handle_admitted(self, frame, conn_txns) -> bool:
                """One admitted request end-to-end; False = drop conn."""
                buf = server_self._frame_reply(frame, conn_txns)
                try:
                    # py-socket-ok: socketserver fallback plane — with
                    # the native front-end on, client replies leave
                    # through frontend_send instead
                    self.request.sendall(buf)
                except (ConnectionError, OSError):
                    return False
                return True

        return Handler

    # ------------------------------------------------------------------
    # shared serving core (socket handlers + native drain workers)
    # ------------------------------------------------------------------
    def _frame_reply(self, frame: bytes, conn_txns) -> bytes:
        """One request frame → one fully-framed reply, both dialects —
        the serving core behind the socket Handler AND the native drain
        workers (admission is the caller's job; the error mapping here
        mirrors antidote_pb_protocol:handle's error replies)."""
        # dialect dispatch on the code byte: antidote_pb request codes
        # (apb.APB_REQUEST_CODES) are disjoint from the native msgpack
        # codes, so existing antidotec_pb clients connect to the same
        # port — and ride the SAME follower discipline (ISSUE 11)
        if frame and frame[0] in apb.APB_REQUEST_CODES:
            resp_body = apb.handle_request(
                self, frame[0], frame[1:], conn_txns, lock=self._lock,
            )
            return struct.pack(">I", len(resp_body)) + resp_body
        code = body = None
        try:
            code, body = decode(frame)
            resp_code, resp = self._process(code, body)
            if code == MessageCode.START_TRANSACTION:
                conn_txns.add(resp["txid"])
            elif code in (MessageCode.COMMIT_TRANSACTION,
                          MessageCode.ABORT_TRANSACTION):
                conn_txns.discard(body.get("txid"))
        except AbortError as e:
            if code == MessageCode.UPDATE_OBJECTS:
                conn_txns.discard(body.get("txid"))
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "aborted", "detail": str(e)
            }
        except InsufficientRightsError as e:
            # escrow refusal (ISSUE 18): the counter_b decrement/transfer
            # exceeded this DC's locally-held rights — nothing executed;
            # the hint tracks the background transfer loop's expected
            # grant arrival (a COMMIT refusal closed the txn server-side,
            # so the descriptor must not linger in conn_txns)
            if code in (MessageCode.UPDATE_OBJECTS,
                        MessageCode.COMMIT_TRANSACTION):
                conn_txns.discard(body.get("txid"))
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "insufficient_rights", "detail": str(e),
                "retry_after_ms": int(e.retry_after_ms),
            }
        except TenantBusyError as e:
            # tenant-scoped quota/lane refusal (ISSUE 19): typed
            # distinctly from global busy — the client learns its OWN
            # quota (not the node) is the bottleneck, so failover to a
            # sibling node won't help but backing off will
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "tenant_busy", "detail": str(e),
                "retry_after_ms": int(e.retry_after_ms),
                "tenant": e.tenant,
            }
        except BusyError as e:
            # downstream cap (commit backlog / batch gate): same typed
            # shape as the admission shed
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "busy", "detail": str(e),
                "retry_after_ms": int(e.retry_after_ms),
            }
        except DeadlineExceeded as e:
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "deadline", "detail": str(e)
            }
        except ReplicaLagging as e:
            # follower session gate: the read was NOT served — the
            # client retries after the hint or fails over (the redirect
            # names the owner)
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "lagging", "detail": str(e),
                "retry_after_ms": int(e.retry_after_ms),
                "redirect": e.redirect,
            }
            self._attach_hint(resp)
        except ColdMiss as e:
            # cold-tier fault-in refused (rate cap / I/O fault / CRC
            # failure): the key's device row stays cold this round —
            # the client retries after the hint; the value was NEVER
            # served wrong
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "cold_miss", "detail": str(e),
                "retry_after_ms": int(e.retry_after_ms),
                "permanent": bool(e.permanent),
            }
        except NotOwnerError as e:
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "not_owner", "detail": str(e),
                "redirect": e.redirect,
            }
            self._attach_hint(resp)
        except ForwardFailed as e:
            # a server-side forwarded write lost the owner connection
            # AFTER the request left the socket: at-most-once forbids a
            # blind resend, so the typed reply tells the CLIENT the op
            # may have executed (re-read at the session token to learn
            # the outcome)
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "forward_failed", "detail": str(e),
                "maybe_executed": True,
            }
            self._attach_hint(resp)
        except ReadOnlyError as e:
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": "read_only", "detail": str(e)
            }
        except Exception as e:  # error reply, keep the conn
            log.exception("request failed")
            resp_code, resp = MessageCode.ERROR_RESP, {
                "error": type(e).__name__, "detail": str(e)
            }
        if isinstance(resp, RawReply):
            # the writeback stage already framed the reply
            return resp.buf
        return encode(resp_code, resp)

    def _frame_fault(self, frame: bytes) -> Optional[bytes]:
        """Apply an armed ``frontend.recv`` fault rule to one inbound
        frame (chaos: the native accept path and the Python plane share
        this site).  None = drop the connection."""
        d = _faults.hit("frontend.recv")
        if d is None:
            return frame
        if d.action == "drop":
            return None
        if d.action == "truncate":
            keep = int(d.arg) if d.arg else max(1, len(frame) // 2)
            return frame[:keep]
        if d.action == "delay":
            time.sleep(float(d.arg or 0.01))
        return frame

    def _attach_hint(self, resp: dict) -> None:
        """Ring-hint header (ISSUE 17): follower replies that imply the
        client mis-routed (proxied reads, typed redirects) carry the
        current fleet+owner so capable clients refresh their ring in
        place and converge back to zero-hop."""
        if self.proxy is not None:
            hint = self.proxy.ring_hint()
            if hint is not None:
                resp["ring_hint"] = hint

    def _abort_orphan(self, txid: int) -> None:
        """Roll back a transaction whose client connection died."""
        with self._lock:
            txn = self._txns.pop(txid, None)
            if txn is not None and txn.active:
                self.node.abort_transaction(txn)
        if (txn is None and self.proxy is not None
                and txid in self.proxy.forwarded_txns):
            # a FORWARDED interactive txn's edge client died: this node
            # holds no Transaction object — relay the abort to the owner
            self.proxy.abort_forwarded(txid)

    # ------------------------------------------------------------------
    # native front-end drain plane (ISSUE 16)
    # ------------------------------------------------------------------
    def _native_drain_loop(self):
        """Fans batch-drain crossings out to per-connection workers.

        The C++ loop serves whole-batch cache hits itself; everything it
        can't (misses, writes, interactive txns, apb frames, admission
        sheds) crosses here in packed batches — ONE GIL acquisition per
        drain, then per-conn queues so one slow device batch never
        head-of-line-blocks another connection's frames.  Reply order
        per connection is preserved: the native loop only fast-serves a
        conn with no frame still pending in Python."""
        nf = self.native
        workers: Dict[int, "queue.SimpleQueue"] = {}
        while not self._closing:
            batch = nf.take_batch(200)
            now = time.monotonic()
            for conn_id, kind, aux, payload in batch:
                if kind == nf.K_CONN_DROP:
                    q = workers.pop(conn_id, None)
                    if q is not None:
                        q.put(None)
                    continue
                q = workers.get(conn_id)
                if q is None:
                    # admitted frames hold admission slots until
                    # frontend_send releases them, and the native loop
                    # stops reading sockets when its crossing queue
                    # fills — so this queue's depth is
                    # bounded-by: admission caps + native QUEUE_CAP
                    q = queue.SimpleQueue()
                    workers[conn_id] = q
                    threading.Thread(
                        target=self._native_conn_worker, daemon=True,
                        args=(conn_id, q),
                        name=f"antidote-native-conn-{conn_id}",
                    ).start()
                q.put((kind, aux, payload, now))
        for q in workers.values():
            q.put(None)

    def _native_conn_worker(self, conn_id: int, q: "queue.SimpleQueue"):
        """One drained connection's serving thread — the moral twin of a
        socketserver Handler: same fault site, same serving core, same
        orphan-txn rollback when the conn drops."""
        nf = self.native
        conn_txns = set()
        try:
            while True:
                item = q.get()
                if item is None or self._closing:
                    return
                kind, aux, frame, t0 = item
                admitted = 1 if kind == nf.K_FRAME else 0
                frame = self._frame_fault(frame)
                if frame is None:
                    # chaos drop: account the slot, then drop the conn —
                    # the Python plane's silent-close twin
                    nf.send(conn_id, b"", admitted)
                    nf.close_conn(conn_id)
                    continue
                if kind == nf.K_SHED:
                    # the native loop refused admission; serialize the
                    # typed busy reply in the frame's dialect here
                    # (Python owns the apb encoder)
                    self.metrics.shed.inc(plane="server")
                    nf.send(conn_id, self._busy_reply_bytes(frame, aux), 0)
                    continue
                self._tls.t0 = t0
                try:
                    buf = self._frame_reply(frame, conn_txns)
                except Exception as e:  # never wedge the admission slot
                    log.exception("native drain request failed")
                    buf = encode(MessageCode.ERROR_RESP, {
                        "error": type(e).__name__, "detail": str(e)})
                nf.send(conn_id, buf, admitted)
                self.metrics.server_request_seconds.observe(
                    time.monotonic() - t0)
        finally:
            for txid in conn_txns:
                self._abort_orphan(txid)

    def _busy_reply_bytes(self, frame: bytes, hint_ms: int) -> bytes:
        """Framed admission-shed reply in the frame's dialect (the
        native loop sheds apb frames to Python — kind 2 — because the
        apb error encoder lives here)."""
        if frame and frame[0] in apb.APB_REQUEST_CODES:
            body = apb.overload_error(
                "busy", "server admission refused", int(hint_ms))
            return struct.pack(">I", len(body)) + body
        return encode(MessageCode.ERROR_RESP, {
            "error": "busy", "detail": "server admission refused",
            "retry_after_ms": int(hint_ms),
        })

    def _native_advance(self) -> None:
        """Push the freshly-published serving epoch to the C++ mirror —
        called by the epoch ticker right after every publish.  The
        mirror's re-stamping is sound because every effect applied since
        the last advance invalidated its keys eagerly (under the commit
        lock, BEFORE the publish made them visible)."""
        nf = self.native
        txm = self.node.txm
        if nf is None or getattr(txm.store, "native_mirror", None) is not nf:
            return
        ep = txm.store.serving_epoch
        if ep is None:
            nf.set_clockless_ok(False)
            return
        nf.advance(int(ep.id), [int(x) for x in ep.vc],
                   int(ep.vc[txm.my_dc]) >= txm.epoch_lag_counter)

    # ------------------------------------------------------------------
    # static batch gate
    # ------------------------------------------------------------------
    def static_read(self, objects, clock, deadline=None, wants_bytes=False,
                    tenant=None):
        """Batched static read: (values, snapshot_vc) — or a
        :class:`RawReply` when ``wants_bytes`` and the writeback stage
        serialized the native reply frame itself."""
        tenant = self.tenants.resolve(tenant, (o[2] for o in objects))
        if not self.batch_static:
            with self._lock:
                check_deadline(deadline, "dispatch")
                return self.node.read_objects(objects, clock=_vc(clock))
        clock_vc = _vc(clock)
        fast = self._try_cache_read(objects, clock_vc, wants_bytes)
        if fast is not None:
            return fast
        w = _StaticWork("read", objects=objects, clock=clock_vc,
                        deadline=deadline, wants_bytes=wants_bytes,
                        tenant=tenant)
        out = self._submit(w)
        if w.reply_bytes is not None:
            return RawReply(w.reply_bytes)
        return out

    def _try_cache_read(self, objects, clock, wants_bytes):
        """Hot-key fast path, ON the handler thread: when every object of
        an epoch-eligible read resolves from the snapshot cache (or is
        bottom at the epoch), the reply is served right here — no gate,
        no dispatcher hop, no device work.  Returns the reply or None.

        No epoch pin: this path touches only host-side structures (cache
        entries, directory, the epoch's used-rows snapshot) — never the
        frozen device buffers the pin protects."""
        if not self._epoch_reads:
            return None
        txm = self.node.txm
        store = txm.store
        ep = store.serving_epoch
        if ep is None:
            return None
        if int(ep.vc[txm.my_dc]) < txm.epoch_lag_counter:
            return None
        if clock is not None and not (clock <= ep.vc).all():
            return None
        vals = store.epoch_cache_read(objects, ep)
        if vals is None:
            return None
        vc_list = [int(x) for x in ep.vc]
        if wants_bytes:
            return RawReply(encode(MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals],
                "commit_clock": vc_list,
            }))
        return vals, vc_list

    def static_update(self, updates, clock, deadline=None, tenant=None):
        """Batched static update: commit VC (raises AbortError on cert).
        Parks DIRECTLY at the locked worker's merge point — the
        dispatcher stage only ever forwarded updates, and the extra
        queue hop + thread wakeup per write was measurable on the
        2-core write-plane floor (ISSUE 6)."""
        tenant = self.tenants.resolve(tenant, (u[2] for u in updates))
        if not self.batch_static:
            with self._lock:
                check_deadline(deadline, "dispatch")
                return self.node.update_objects(updates, clock=_vc(clock))
        return self._submit(_StaticWork("update", updates=updates,
                                        clock=_vc(clock),
                                        deadline=deadline, tenant=tenant),
                            self._locked_q)

    def _submit(self, work: _StaticWork, q: Optional[TenantLanes] = None):
        """Park a work on a pipeline queue (default: the batch gate;
        interactive commits go straight to the locked-plane merge point
        — one hop fewer) and wait for its stage to reply.  Tenant
        discipline (ISSUE 19): the work enters its tenant's in-flight
        account (typed ``tenant_busy`` past a configured cap) and its
        tenant's bounded LANE — never the shared budget."""
        if self._closing:
            raise ConnectionError("server shutting down")
        if q is None:
            q = self._static_q
        tenant = self.tenants.label(work.tenant)
        m = self.metrics
        try:
            self.admission.tenant_enter(tenant)
        except TenantBusyError:
            m.shed.inc(plane="tenant")
            # tenant-label-ok: `tenant` is clamped by TenantRegistry.label
            m.tenant_shed.inc(tenant=tenant, plane="admission")
            raise
        now = time.monotonic()
        work.t_submit = now
        t0 = getattr(self._tls, "t0", None)
        if t0 is not None:
            m.stage_decode_seconds.observe(now - t0)
            self._tls.t0 = None
        try:
            try:
                # bounded gate: shed with a typed busy error instead of
                # parking behind an unbounded backlog
                q.put_nowait(work, tenant)
            except TenantBusyError:
                m.shed.inc(plane="tenant")
                # tenant-label-ok: clamped by TenantRegistry.label above
                m.tenant_shed.inc(
                    tenant=tenant,
                    plane=("batch_gate" if q is self._static_q
                           else "locked"))
                raise
            except (BusyError, queue.Full):
                m.shed.inc(plane="server_queue")
                raise BusyError(
                    f"static batch gate full ({q.maxsize} requests "
                    f"parked)",
                    retry_after_ms=100,
                ) from None
            if q is self._static_q:
                m.commit_gate_depth.set(q.qsize())
            if not work.event.wait(timeout=300):
                raise TimeoutError("static batch dispatcher stalled")
        finally:
            self.admission.tenant_exit(tenant)
            # tenant-label-ok: clamped by TenantRegistry.label above
            m.tenant_in_flight.set(
                self.admission.tenant_in_flight(tenant), tenant=tenant)
        # tenant-label-ok: clamped by TenantRegistry.label above
        m.tenant_request_seconds.observe(time.monotonic() - now,
                                         tenant=tenant)
        if work.error is not None:
            raise work.error
        return work.result

    def _drain_batch(self, q, window_s: float = 0.0):
        """Block for one work, drain whatever else queued (up to
        ``_batch_max``); with ``window_s`` keep gathering late arrivals
        up to that long (the --group-commit-window-us merge window).
        Returns (works, stop_seen)."""
        batch = [q.get()]
        deadline = (time.monotonic() + window_s) if window_s > 0 else None
        while len(batch) < self._batch_max:
            try:
                batch.append(q.get_nowait())
            except queue.Empty:
                if deadline is None:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    batch.append(q.get(timeout=left))
                except queue.Empty:
                    break
        stop = any(w is _STOP for w in batch)
        return [w for w in batch if w is not _STOP], stop

    def _shed_expired(self, works, where: str, observe_parked=False):
        """Deadline discipline shared by both planes: work that outlived
        its caller while parked is aborted AT DEQUEUE — executing it
        would burn a device launch on a reply nobody is waiting for."""
        live: List[_StaticWork] = []
        now = time.monotonic()
        m = self.metrics
        for w in works:
            if observe_parked and w.t_submit:
                m.stage_parked_seconds.observe(now - w.t_submit)
            if w.deadline is not None and now > w.deadline:
                m.shed.inc(plane="deadline")
                w.error = DeadlineExceeded(
                    f"request deadline passed while parked at the "
                    f"{where}; not executed")
                w.event.set()
            else:
                live.append(w)
        return live

    @staticmethod
    def _fail_queue_remainder(q) -> None:
        """Shutdown drain: fail anything that raced the stop sentinel
        into the queue — a handler parked behind it must not wait out
        its submit timeout."""
        while True:
            try:
                w = q.get_nowait()
            except queue.Empty:
                return
            if w is not _STOP:
                w.error = ConnectionError("server shutting down")
                w.event.set()

    def _static_loop(self):
        """The DISPATCHER stage of the serving pipeline: drain whatever
        queued while the previous group executed, LAUNCH merged epoch
        reads lock-free (device handles go to the writeback stage — this
        thread never blocks on the device), and forward everything else
        to the locked-plane worker.  Natural batching — no gather delay:
        at low load a lone request runs immediately; under load the
        batch grows to whatever queued during the previous launch, and
        batch N+1 is being decoded by handler threads while batch N
        executes on device and batch N-1's replies are serialized by the
        writeback worker."""
        q = self._static_q
        m = self.metrics
        while True:
            works, stop = self._drain_batch(q)
            m.commit_gate_depth.set(q.qsize())
            works = self._shed_expired(works, "batch gate",
                                       observe_parked=True)
            try:
                reads = [w for w in works if w.kind == "read"]
                rest = [w for w in works if w.kind != "read"]
                if reads and self._epoch_reads:
                    # lock-split: reads pinned at/below the published
                    # serving epoch never park behind a commit group
                    t0 = time.monotonic()
                    reads = self._launch_epoch_reads(reads)
                    m.stage_launch_seconds.observe(time.monotonic() - t0)
                # updates and unservable reads go to the locked-plane
                # worker: a commit group (or the compile hiding inside
                # one) never parks the dispatcher's launch stage.
                # path=locked counts only reads actually enqueued — a
                # queue-full shed is not a served read (a rerouted
                # work's already-launched objects still show under
                # gather: a real, if wasted, launch)
                for w in rest + reads:
                    try:
                        self._locked_q.put_nowait(
                            w, self.tenants.label(w.tenant))
                    except TenantBusyError as e:
                        m.shed.inc(plane="tenant")
                        # tenant-label-ok: clamped via TenantRegistry.label
                        m.tenant_shed.inc(tenant=e.tenant, plane="locked")
                        w.error = e
                        w.event.set()
                        continue
                    except (BusyError, queue.Full):
                        m.shed.inc(plane="server_queue")
                        w.error = BusyError(
                            f"static batch gate full (locked plane: "
                            f"{self._locked_q.maxsize} parked)",
                            retry_after_ms=100)
                        w.event.set()
                        continue
                    if w.kind == "read":
                        m.serving_reads.inc(len(w.objects), path="locked")
            except BaseException as e:  # never strand a parked connection
                for w in works:
                    if not w.event.is_set():
                        w.error = e
                        w.event.set()
            if stop:
                self._locked_q.put(_STOP)
                self._fail_queue_remainder(q)
                return

    def _locked_loop(self):
        """The LOCKED plane's worker — and the write plane's MERGE POINT
        (ISSUE 6): static update groups and interactive COMMITs arriving
        on different connections drain into ONE merged batch that takes
        the commit lock once, certifies once, appends once and scatters
        once, with per-source acks fanned back out.  Also serves the
        reads the epoch path cannot (clocks ahead of the epoch,
        composite maps, promoted keys, no epoch yet).  Runs under
        ``self._lock`` — serialized against nothing but itself and
        inline (batch_static off) dispatch; the epoch read plane never
        waits for it."""
        q = self._locked_q
        while True:
            works, stop = self._drain_batch(q, self._group_window_s)
            # re-checked at THIS dequeue too (the overload contract at
            # the merge point): a work can expire while parked behind a
            # slow commit group — this plane's whole job is absorbing
            # those.  Write works park here directly (no dispatcher
            # hop), so this dequeue also owns their parked-stage clock;
            # rerouted reads were already observed at the batch gate.
            writes = self._shed_expired(
                [w for w in works if w.kind != "read"], "locked plane",
                observe_parked=True)
            reads = self._shed_expired(
                [w for w in works if w.kind == "read"], "locked plane")
            try:
                ups = [w for w in writes if w.kind == "update"]
                commits = [w for w in writes if w.kind == "commit"]
                with self._lock:
                    # writes first: the merged read then serves at a
                    # snapshot covering them (fresh + cache friendly)
                    if ups or commits:
                        self._run_commit_merge(ups, commits)
                    if reads:
                        self._run_read_group(reads)
            except BaseException as e:  # never strand a parked connection
                for w in works:
                    if not w.event.is_set():
                        w.error = e
                        w.event.set()
            if stop:
                self._fail_queue_remainder(q)
                return

    # ------------------------------------------------------------------
    # lock-split epoch reads (dispatcher launch stage)
    # ------------------------------------------------------------------
    def _launch_epoch_reads(
            self, works: List[_StaticWork]) -> List[_StaticWork]:
        """Launch epoch-eligible read works as merged lock-free gathers
        against the frozen serving epoch (async dispatch only — never a
        device sync) and hand the device handles to the writeback stage.
        Returns the works that must take the locked path: clocks ahead
        of the epoch, objects the epoch cannot serve (composite maps,
        promoted keys, unfrozen tables), or no epoch at all."""
        leftover: List[_StaticWork] = []
        for chunk in self._chunk_epoch_works(works):
            leftover.extend(self._launch_epoch_chunk(chunk))
        return leftover

    def _launch_epoch_chunk(
            self, works: List[_StaticWork]) -> List[_StaticWork]:
        """One bounded launch chunk: pin the epoch, classify, launch ONE
        merged gather, enqueue for writeback.  Returns locked-path works."""
        txm = self.node.txm
        store = txm.store
        ep = store.pin_serving_epoch()
        if ep is None:
            return works
        # a clockless read must still see every locally-ACKED commit.
        # Commit groups publish BEFORE replying, so acked == covered —
        # except across a deferred/failed publish, which raises the lag
        # floor; an epoch below the floor cannot serve clockless reads.
        # (Deliberately NOT commit_counter: a commit minted mid-flight
        # has not acked yet, and gating on it would park reads behind
        # every in-flight commit — the convoy this plane removes.)
        if int(ep.vc[txm.my_dc]) < txm.epoch_lag_counter:
            store.unpin_serving_epoch(ep)
            return works
        merged: List[_StaticWork] = []
        locked: List[_StaticWork] = []
        for w in works:
            if w.clock is None or (w.clock <= ep.vc).all():
                merged.append(w)
            else:
                locked.append(w)
        if not merged:
            store.unpin_serving_epoch(ep)
            return works
        objs: list = []
        spans = []
        for w in merged:
            spans.append((len(objs), len(objs) + len(w.objects)))
            objs.extend(w.objects)
        try:
            pending, fallback = store.epoch_read_launch(objs, ep)
        except BaseException:
            store.unpin_serving_epoch(ep)
            log.exception("epoch read launch failed; locked fallback")
            return works
        keep, kspans = merged, spans
        if fallback:
            fb = set(fallback)
            keep, kspans = [], []
            for w, (lo, hi) in zip(merged, spans):
                if fb.isdisjoint(range(lo, hi)):
                    keep.append(w)
                    kspans.append((lo, hi))
                else:
                    # a work with ANY unservable object reroutes whole —
                    # its launched siblings' results are simply dropped
                    locked.append(w)
        if not keep:
            store.unpin_serving_epoch(ep)
            return locked
        vc_list = [int(x) for x in ep.vc]
        # bounded handoff: a lagging writeback stage backpressures this
        # dispatcher (and through the bounded gate, the clients)
        self._writeback_q.put(_EpochReadBatch(pending, keep, kspans,
                                              vc_list))
        return locked

    #: merged epoch-read launches are chunked at this many objects: one
    #: padded-batch XLA bucket serves every chunk, so a saturated gate
    #: can never mint a brand-new (bigger) bucket shape — and its
    #: multi-second compile — in the middle of serving traffic
    EPOCH_LAUNCH_CHUNK = 512

    def _chunk_epoch_works(self, works: List[_StaticWork]):
        """Split eligible works into launch chunks of ≤ the epoch chunk
        size — EPOCH_LAUNCH_CHUNK, scaled by the mesh device count for
        mesh-routed launches — total objects (a single oversized work
        still gets its own chunk; the bucket ladder handles it)."""
        chunk: List[_StaticWork] = []
        n = 0
        for w in works:
            if chunk and n + len(w.objects) > self._epoch_chunk:
                yield chunk
                chunk, n = [], 0
            chunk.append(w)
            n += len(w.objects)
        if chunk:
            yield chunk

    def _writeback_loop(self):
        """The WRITEBACK stage: the only pipeline stage allowed to block
        on the device.  Materializes launched epoch-read batches, decodes
        values (back-filling the hot-key snapshot cache), serializes the
        native reply frames in one tight loop, and wakes the parked
        handler threads."""
        q = self._writeback_q
        m = self.metrics
        while True:
            batch = q.get()
            if batch is _STOP:
                return
            store = self.node.txm.store
            t0 = time.monotonic()
            try:
                # sync-ok: the writeback stage owns the device sync
                vals = store.epoch_read_finish(batch.pending)
                for w, (lo, hi) in zip(batch.works, batch.spans):
                    w.result = (vals[lo:hi], batch.vc_list)
                    if w.wants_bytes:
                        w.reply_bytes = encode(
                            MessageCode.READ_OBJECTS_RESP, {
                                "values": [encode_value(v)
                                           for v in vals[lo:hi]],
                                "commit_clock": batch.vc_list,
                            })
                    w.event.set()
            except BaseException as e:
                log.exception("epoch read writeback failed")
                for w in batch.works:
                    if not w.event.is_set():
                        w.error = e
                        w.event.set()
            finally:
                store.unpin_serving_epoch(batch.pending.ep)
                m.stage_writeback_seconds.observe(time.monotonic() - t0)

    # ------------------------------------------------------------------
    # serving-epoch ticker (dedicated publication thread)
    # ------------------------------------------------------------------
    #: per-table cadence of the LOCKED path's epoch ladder
    #: (TypedTable.publish_epoch full-head copies)
    TABLE_EPOCH_S = 2.0
    #: at most this many full-head table publishes per tick — the
    #: per-tick publication cost cap (a tick can no longer stall the
    #: pipeline for one whole-store copy sweep)
    TABLE_EPOCHS_PER_TICK = 1

    def _epoch_ticker(self):
        """Publishes serving epochs on a fixed cadence so an
        interactive-txn-only (or remote-ingress-only) workload still gets
        fresh epochs — commit groups publish inline before their acks,
        the ticker covers everything else (including deferred-publish
        retries).  Runs OFF the dispatcher thread: a publication tick can
        never stall a parked read batch (reads don't take the lock the
        publish holds)."""
        txm = self.node.txm
        # with the epoch plane off, the ticker still drives the table
        # ladder — at a relaxed cadence (the ladder's own per-table
        # cadence is TABLE_EPOCH_S anyway)
        tick = (max(float(self.epoch_tick_ms), 1.0) / 1e3
                if self._epoch_reads else 0.5)
        while not self._ticker_stop.wait(tick):
            try:
                if self._epoch_reads:
                    txm.publish_serving_epoch()
                    self._native_advance()
                self._publish_table_epochs_capped()
            except Exception:
                log.exception("epoch ticker publish failed")

    def _publish_table_epochs_capped(self) -> int:
        """The locked path's per-table epoch ladder (read-while-write
        double buffer for clock-pinned reads), budgeted: at most
        ``TABLE_EPOCHS_PER_TICK`` full-head copies per tick, each table
        at most every ``TABLE_EPOCH_S``.  A table publishes only when new
        commits landed AND some read actually took the slow path since
        its last publish — (a) alone copies heads for workloads that
        never fold, (b) alone is satisfied forever by one old historical
        read.  Returns the number of tables published."""
        txm = self.node.txm
        store = txm.store
        budget = self.TABLE_EPOCHS_PER_TICK
        published = 0
        now = time.monotonic()
        with txm.commit_lock:
            # least-recently-published first: with more continuously-
            # eligible tables than budget slots per cadence window, a
            # fixed scan order would starve the tables at the tail of
            # the dict forever
            tables = sorted(store.tables.values(),
                            key=lambda t: getattr(t, "_pub_at", 0.0))
            for t in tables:
                if budget == 0:
                    break
                if (t.slow_serves != getattr(t, "_pub_slow_serves", -1)
                        and store.mutation_epoch != getattr(t, "_pub_mut",
                                                            -1)
                        and now - getattr(t, "_pub_at", 0.0)
                        >= self.TABLE_EPOCH_S):
                    t._pub_slow_serves = t.slow_serves
                    t._pub_mut = store.mutation_epoch
                    t._pub_at = now
                    t.publish_epoch()
                    budget -= 1
                    published += 1
        return published

    def _run_read_group(self, works: List[_StaticWork]) -> None:
        # requests whose causal clock is already covered locally merge
        # into ONE snapshot read; a clock AHEAD of local replication (or
        # bogus) must WAIT inside start_transaction — running it solo
        # keeps one slow client from head-of-line-blocking the batch.
        # FOLLOWER MODE: locked-path reads gather from the LIVE head
        # buffers, which the replica's pump thread mutates via
        # apply_effects (a read-modify-REASSIGN with buffer donation) —
        # on an owner the locked worker itself serializes reads against
        # commits, but a follower's applies arrive on another thread, so
        # the read must hold the same commit lock the ingress drain
        # holds (the geo-peer read discipline).  The epoch plane stays
        # lock-free either way (frozen buffers + the pin protocol).
        import contextlib

        read_lock = (self.node.txm.commit_lock
                     if self.follower is not None
                     else contextlib.nullcontext())
        covered = self._covered_vc()
        merged, solo = [], []
        for w in works:
            if w.clock is None or (covered is not None
                                   and (w.clock <= covered).all()):
                merged.append(w)
            else:
                solo.append(w)
        if merged:
            clock = None
            for w in merged:
                if w.clock is not None:
                    clock = (w.clock if clock is None
                             else np.maximum(clock, w.clock))
            objs: list = []
            offs = [0]
            for w in merged:
                objs.extend(w.objects)
                offs.append(len(objs))
            try:
                with read_lock:
                    vals, vc = self.node.read_objects(objs, clock=clock)
                for i, w in enumerate(merged):
                    w.result = (vals[offs[i]:offs[i + 1]], vc)
                    w.event.set()
            except Exception:
                solo = merged + solo  # isolate the offender
        for w in solo:
            if w.event.is_set():
                continue
            try:
                with read_lock:
                    w.result = self.node.read_objects(w.objects,
                                                      clock=w.clock)
            except Exception as e:
                w.error = e
            w.event.set()

    def _covered_vc(self):
        """Freshest locally-covered clock (entry-wise), or None when the
        node doesn't expose one (then every clocked read runs solo)."""
        txm = getattr(self.node, "txm", None)
        if txm is not None:
            vc = txm.store.dc_max_vc().copy()
            vc[txm.my_dc] = max(int(vc[txm.my_dc]), txm.commit_counter)
            return vc
        member = getattr(self.node, "member", None)
        if member is not None:
            # sync-ok: cluster members return host clocks, not jax arrays
            return np.asarray(member.stable_vc())
        return None

    def _run_commit_merge(self, ups: List[_StaticWork],
                          commits: List[_StaticWork]) -> None:
        """The write plane's merge point (ISSUE 6): static update groups
        AND interactive COMMITs from different connections fuse into ONE
        ``commit_transactions_group`` call — one commit-lock take, one
        certification pass, one WAL append, one device scatter — with
        per-source results fanned back out (a member's failure-atomic
        rollback rolls back only its own sub-group)."""
        txm = getattr(self.node, "txm", None)
        if txm is None:
            # cluster coordinator (2PC): sequential legacy path (commit
            # works are never routed here without a txm)
            for w in ups:
                try:
                    w.result = self.node.update_objects(w.updates,
                                                        clock=w.clock)
                except Exception as e:
                    w.error = e
                w.event.set()
            for w in commits:
                w.error = RuntimeError("commit merge requires a local txm")
                w.event.set()
            return
        # resolve interactive commit works to their registered txns
        # (self._lock is held by the locked worker)
        inter: List = []
        for w in commits:
            txn = self._txns.get(w.txid)
            if txn is None or not txn.active:
                w.error = KeyError(
                    f"unknown or finished transaction {w.txid}")
                w.event.set()
                continue
            inter.append((w, txn))
        pending = list(ups)
        first = True
        # Static group members share a snapshot, so two read-bearing
        # writes to one hot key first-committer-abort each other — a
        # conflict the pre-batch serial path could never produce (each
        # request's snapshot followed the previous commit).  Losers
        # retry as a FOLLOW-UP GROUP at a fresh snapshot (≥1 winner per
        # round → ≤N rounds, still one device append per round) —
        # equivalent to some serial interleaving, so no spurious abort
        # escapes to a client.  (Blind commutative updates bypass
        # certification entirely and never enter this loop's retries.)
        # Interactive commits ride the FIRST round only: their abort is
        # the client's to observe, never auto-retried.
        while pending or (first and inter):
            staged = []
            for w in pending:
                # re-check per-work deadlines at every retry round: a
                # conflict-retry loop under load must not keep executing
                # work whose caller has already timed out
                if (w.deadline is not None
                        and time.monotonic() > w.deadline):
                    self.metrics.shed.inc(plane="deadline")
                    w.error = DeadlineExceeded(
                        "request deadline passed before commit; "
                        "not executed")
                    w.event.set()
                    continue
                try:
                    txn = txm.start_transaction(w.clock)
                    try:
                        txm.update_objects(w.updates, txn)
                    except Exception:
                        txm.abort_transaction(txn)
                        raise
                    staged.append((w, txn))
                except Exception as e:
                    w.error = e
                    w.event.set()
            batch = staged + (inter if first else [])
            first = False
            if not batch:
                return
            try:
                outs = txm.commit_transactions_group(
                    [t for _, t in batch])
            except Exception as e:
                for w, txn in batch:
                    # a backlog-shed group comes back with its txns
                    # still OPEN — server-created static txns must be
                    # aborted here (their clients only see the error
                    # reply); an interactive holder's txn stays open on
                    # BusyError so the SAME commit is retryable, and on
                    # any other failure the _process wrapper unregisters
                    # the (now closed) txn
                    if w.kind == "update" and txn.active:
                        txm.abort_transaction(txn)
                    w.error = e
                    w.event.set()
                return
            retry = []
            for (w, txn), r in zip(batch, outs):
                if isinstance(r, AbortError) and w.kind == "update":
                    retry.append(w)
                elif isinstance(r, Exception):
                    w.error = r
                    w.event.set()
                else:
                    w.result = r
                    w.event.set()
            pending = retry

    # ------------------------------------------------------------------
    # symmetric serving fabric (ISSUE 17): follower entrypoints
    # ------------------------------------------------------------------
    def _follower_entry(self, code: MessageCode, body, deadline):
        """Write/txn traffic arriving at a follower.  Returns the
        ``(resp_code, resp)`` pair when the fabric handled (forwarded or
        refused) the request, None to continue the normal serving path.

        DC-mesh mutations stay refused outright: CONNECT_TO_DCS would
        subscribe the FOLLOWER to a peer DC's stream — it would then
        apply foreign-origin txns the owner never replicated, i.e.
        guaranteed divergence + an endless heal loop — and forwarding
        them would silently mutate the owner's mesh behind the
        operator's back."""
        fol = self.follower
        plane = self.proxy
        if code in (MessageCode.CONNECT_TO_DCS, MessageCode.CREATE_DC):
            self.metrics.session_redirects.inc(kind="not_owner",
                                               dialect="native")
            raise NotOwnerError(fol.owner_client_addr)
        if code == MessageCode.STATIC_UPDATE_OBJECTS:
            if plane is None or body.get("proxied"):
                # one hop max: a FORWARDED write landing back on a
                # follower means the fleet disagrees about who owns the
                # write plane — refuse typed rather than loop
                self.metrics.session_redirects.inc(kind="not_owner",
                                                   dialect="native")
                raise NotOwnerError(fol.owner_client_addr)
            vc = plane.forward_update(
                _decode_updates(body["updates"]), body.get("clock"),
                deadline, tenant=body.get("tenant"),
            )
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in vc]
            }
        if code in (MessageCode.START_TRANSACTION,
                    MessageCode.READ_OBJECTS,
                    MessageCode.UPDATE_OBJECTS,
                    MessageCode.COMMIT_TRANSACTION,
                    MessageCode.ABORT_TRANSACTION):
            if plane is None or body.get("proxied"):
                if code in (MessageCode.START_TRANSACTION,
                            MessageCode.UPDATE_OBJECTS,
                            MessageCode.COMMIT_TRANSACTION):
                    self.metrics.session_redirects.inc(kind="not_owner",
                                                       dialect="native")
                    raise NotOwnerError(fol.owner_client_addr)
                # READ/ABORT keep their pre-fabric unknown-txn answers
                return None
            return self._forward_txn_op(plane, code, body)
        return None

    def _forward_txn_op(self, plane: ProxyPlane, code: MessageCode, body):
        """Relay one interactive-txn op over the sticky owner channel.
        The owner's reply bodies are the native wire shapes already —
        relay them verbatim (the txid is the OWNER's: the follower holds
        no Transaction object, only forwarded-txn bookkeeping so a dead
        edge connection still aborts its orphans)."""
        if code == MessageCode.START_TRANSACTION:
            resp = plane.txn_call(code, body)
            plane.forwarded_txns.add(resp["txid"])
            return MessageCode.START_TRANSACTION_RESP, resp
        if code == MessageCode.READ_OBJECTS:
            return MessageCode.READ_OBJECTS_RESP, plane.txn_call(code, body)
        if code == MessageCode.UPDATE_OBJECTS:
            try:
                resp = plane.txn_call(code, body)
            except AbortError:
                # the owner aborted + unregistered the txn
                plane.forwarded_txns.discard(body.get("txid"))
                raise
            return MessageCode.OPERATION_RESP, resp
        if code == MessageCode.COMMIT_TRANSACTION:
            try:
                resp = plane.txn_call(code, body)
            except BusyError:
                raise  # txn stays OPEN at the owner — retryable
            except BaseException:
                plane.forwarded_txns.discard(body.get("txid"))
                raise
            plane.forwarded_txns.discard(body.get("txid"))
            return MessageCode.COMMIT_RESP, resp
        # ABORT_TRANSACTION
        resp = plane.txn_call(code, body)
        plane.forwarded_txns.discard(body.get("txid"))
        return MessageCode.OPERATION_RESP, resp

    def _follower_read(self, objs, clock, deadline, dialect: str = "native",
                       proxied: bool = False, tenant=None):
        """Session read at a follower entrypoint.  Returns
        ``(out, via_proxy)``: in-arc keys serve locally (token-gated,
        with a server-side proxy failover when the gate refuses);
        out-of-arc keys proxy one hop to the arc owner.  A PROXIED
        request never re-proxies (the forwarding node owns failover) and
        typed lagging surfaces only when every avenue is exhausted."""
        fol = self.follower
        plane = self.proxy
        wants_bytes = dialect == "native"

        def _local():
            fol.gate_read(objs, _vc(clock), deadline, dialect=dialect)
            return self.static_read(objs, clock, deadline=deadline,
                                    wants_bytes=wants_bytes,
                                    tenant=tenant), False

        if plane is None or proxied:
            return _local()
        target = plane.route(objs)
        if target is None:
            # in-arc: serve locally; a gate refusal (lagging/bootstrap)
            # fails over server-side to a live peer instead of bouncing
            # a typed redirect to a client that routed CORRECTLY
            try:
                return _local()
            except ReplicaLagging as gate_err:
                try:
                    return plane.proxy_read(objs, clock, deadline,
                                            tenant=tenant), True
                except ProxyExhausted:
                    raise gate_err from None
        try:
            return plane.proxy_read(objs, clock, deadline,
                                    first=target, tenant=tenant), True
        except ProxyExhausted:
            # every remote hop failed: terminal local attempt — the
            # gate's typed refusal is the honest last resort
            return _local()

    # ------------------------------------------------------------------
    def _process(self, code: MessageCode, body: Any):
        # per-request deadline: client-supplied relative ``deadline_ms``
        # (native dialect only), else the configured server default.
        # Work that outlives it while queued is aborted at dequeue.
        deadline = deadline_from_ms(
            body.get("deadline_ms") if isinstance(body, dict) else None,
            self.default_deadline_ms,
        )
        # follower replicas: PR 9 refused every write/txn with a typed
        # not_owner redirect; with the serving fabric (ISSUE 17) the
        # follower instead FORWARDS them to the owner write plane and
        # answers like any node — typed errors surface only when
        # forwarding is exhausted (or with --no-server-proxy)
        fol = self.follower
        if fol is not None:
            handled = self._follower_entry(code, body, deadline)
            if handled is not None:
                return handled
        # static ops route through the gate helpers OUTSIDE the lock (the
        # gate's dispatcher takes it; with batching off they lock inline)
        # — the ONLY static dispatch path, so it cannot drift from a
        # duplicate
        if code == MessageCode.STATIC_READ_OBJECTS:
            objs = _decode_objects(body["objects"])
            if fol is not None:
                out, via_proxy = self._follower_read(
                    objs, body.get("clock"), deadline,
                    proxied=bool(body.get("proxied")),
                    tenant=body.get("tenant"),
                )
                if via_proxy:
                    vals, vc = out
                    resp = {
                        "values": [encode_value(v) for v in vals],
                        "commit_clock": [int(x) for x in vc],
                    }
                    # teach the mis-routed client the ring so it
                    # converges back to zero-hop
                    self._attach_hint(resp)
                    return MessageCode.READ_OBJECTS_RESP, resp
            else:
                out = self.static_read(
                    objs, body.get("clock"),
                    deadline=deadline, wants_bytes=True,
                    tenant=body.get("tenant"),
                )
            if isinstance(out, RawReply):
                # batched reply serialization: the writeback stage framed
                # the response; the handler sends the bytes as-is
                return MessageCode.READ_OBJECTS_RESP, out
            vals, vc = out
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals],
                "commit_clock": [int(x) for x in vc],
            }
        if code == MessageCode.STATIC_UPDATE_OBJECTS:
            vc = self.static_update(
                _decode_updates(body["updates"]), body.get("clock"),
                deadline=deadline, tenant=body.get("tenant"),
            )
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in vc]
            }
        if (code == MessageCode.COMMIT_TRANSACTION and self.batch_static
                and getattr(self.node, "txm", None) is not None):
            # interactive commits join the cross-connection merge point
            # (ISSUE 6): instead of serializing through the dispatch
            # lock one at a time, the commit parks at the locked
            # worker and fuses with whatever static updates and OTHER
            # connections' commits drained in the same batch
            txid = body["txid"]
            # an interactive commit's tenant comes from its buffered
            # writeset's buckets (the txn was started tag-free)
            with self._lock:
                txn = self._txns.get(txid)
            tenant = self.tenants.resolve(
                body.get("tenant"),
                (e.bucket for e, _ in getattr(txn, "writeset", ()) or ()))
            w = _StaticWork("commit", deadline=deadline, txid=txid,
                            tenant=tenant)
            try:
                vc = self._submit(w, self._locked_q)
            except BusyError:
                # the txn stays OPEN and registered: the busy reply's
                # retry-after hint is honest — the SAME commit can be
                # resubmitted (manager backlog-shed semantics)
                raise
            except BaseException:
                # unregister AND abort-if-still-open: a work shed at
                # the merge-point dequeue (deadline, queue overflow,
                # shutdown) never reached the commit group, so the txn
                # is still ACTIVE — popping it without aborting would
                # orphan an open txn nothing can reach, pinning the
                # certification-GC floor forever
                with self._lock:
                    txn = self._txns.pop(txid, None)
                if txn is not None and txn.active:
                    self.node.abort_transaction(txn)
                raise
            with self._lock:
                self._txns.pop(txid, None)
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in vc]
            }
        if code == MessageCode.REPLICA_ADMIN:
            # replica registry op (console replica add/remove/status),
            # OUTSIDE the dispatch lock: pure registry bookkeeping on
            # the replica plane, never a data-path call
            if self.interdc is None or not hasattr(self.interdc,
                                                   "replica_admin"):
                raise RuntimeError("no replica plane attached (start "
                                   "with --interdc or --follower-of)")
            return MessageCode.OPERATION_RESP, {
                "replicas": self.interdc.replica_admin(body or {})
            }
        if code == MessageCode.CHECKPOINT_NOW:
            # admin op, OUTSIDE the dispatch lock: the checkpointer has
            # its own serialization, and streaming a multi-second image
            # while holding the dispatch lock would park the locked
            # plane behind an operator command
            return MessageCode.OPERATION_RESP, {
                "checkpoint": self.node.checkpoint_now()
            }
        with self._lock:
            # deadline re-checked at dequeue (= after the lock convoy):
            # a request that outlived its caller is not executed
            try:
                check_deadline(deadline, "dispatch")
            except DeadlineExceeded:
                self.metrics.shed.inc(plane="deadline")
                raise
            return self._dispatch(code, body)

    def _dispatch(self, code: MessageCode, body: Any):
        node = self.node
        if code == MessageCode.START_TRANSACTION:
            txn = node.start_transaction(
                clock=_vc(body.get("clock")), props=body.get("props"),
            )
            self._txns[txn.txid] = txn
            return MessageCode.START_TRANSACTION_RESP, {"txid": txn.txid}
        if code == MessageCode.READ_OBJECTS:
            txn = self._txn(body["txid"])
            vals = node.read_objects(_decode_objects(body["objects"]), txn)
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals]
            }
        if code == MessageCode.UPDATE_OBJECTS:
            txn = self._txn(body["txid"])
            try:
                node.update_objects(_decode_updates(body["updates"]), txn)
            except AbortError:
                self._txns.pop(body["txid"], None)
                raise
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.COMMIT_TRANSACTION:
            # keep the txn registered until the outcome is known: a
            # commit-backlog BusyError leaves it OPEN (the shed happens
            # before the group touches it), so the busy reply's retry
            # hint is honest — the SAME commit can be resubmitted
            txn = self._txn(body["txid"])
            try:
                commit_vc = node.commit_transaction(txn)
            except BusyError:
                raise
            except BaseException:
                self._txns.pop(body["txid"], None)  # txn is dead
                raise
            self._txns.pop(body["txid"], None)
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in commit_vc]
            }
        if code == MessageCode.ABORT_TRANSACTION:
            txn = self._txns.pop(body["txid"])
            node.abort_transaction(txn)
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.GET_CONNECTION_DESCRIPTOR:
            return MessageCode.OPERATION_RESP, {
                "descriptor": self._get_descriptor(),
            }
        if code == MessageCode.CONNECT_TO_DCS:
            self._connect_to_dcs(body.get("descriptors", []))
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.CREATE_DC:
            self._create_dc(body.get("nodes", []))
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.NODE_STATUS:
            status = node.status(
                include_ready=bool(body.get("include_ready"))
            )
            # the server's own admission plane rides along (the node
            # object can't see it)
            status.setdefault("overload", {}).update({
                "in_flight": self.admission.in_flight(),
                "max_in_flight": self.admission.max_in_flight,
                "max_in_flight_per_client": self.admission.max_per_client,
                "batch_gate_depth": self._static_q.qsize(),
                "batch_gate_max": self._static_q.maxsize,
            })
            status["pipeline"] = self._pipeline_status()
            status["tenants"] = self._tenant_status()
            if self.interdc is not None and hasattr(self.interdc,
                                                    "replica_status"):
                # follower liveness (owner: every follower with its
                # typed ok/lagging/down state; follower: its own
                # state/bootstrap/divergence view)
                status["replicas"] = self.interdc.replica_status()
            return MessageCode.OPERATION_RESP, {"status": status}
        raise ValueError(f"unhandled message code {code!r}")

    def _txn(self, txid: int) -> Transaction:
        txn = self._txns.get(txid)
        if txn is None:
            raise KeyError(f"unknown or finished transaction {txid}")
        return txn

    # ------------------------------------------------------------------
    # DC management (antidote_pb_process:process create_dc /
    # get_connection_descriptor / connect_to_dcs clauses,
    # /root/reference/src/antidote_pb_process.erl:103-135) — shared by
    # both wire dialects
    # ------------------------------------------------------------------
    def _get_descriptor(self) -> dict:
        if self.interdc is None:
            raise RuntimeError("no inter-DC replica attached")
        return self.interdc.descriptor().to_wire()

    def _connect_to_dcs(self, descriptors) -> None:
        if self.interdc is None:
            raise RuntimeError("no inter-DC replica attached")
        for d in descriptors:
            self.interdc.observe_descriptor(d)

    def _create_dc(self, nodes) -> None:
        """The reference assembles a riak cluster from ``nodes`` here;
        this build's DC is assembled at boot (console serve /
        cluster.boot ctl_wire), so a single-node list is acknowledged
        (the DC exists) and a multi-node list is refused with the
        operator path, matching create_dc's error reply shape."""
        if len(nodes) > 1:
            raise RuntimeError(
                "create_dc_failed: multi-member DCs assemble via "
                "cluster.boot + ctl_wire, not the client protocol"
            )

    # ------------------------------------------------------------------
    def _tenant_status(self) -> dict:
        """Per-tenant QoS block for node status (ISSUE 19): configured
        weight/caps plus live in-flight, lane depths and typed-shed
        odometers — the block that makes noisy-neighbor interference
        observable before anyone's p99 says so."""
        gate = self._static_q.status()
        locked = self._locked_q.status()
        out = {"multi": self.tenants.multi, "tenants": {}}
        for name in self.tenants.names:
            spec = self.tenants.spec(name)
            out["tenants"][name] = {
                "weight": spec.weight,
                "max_in_flight": spec.max_in_flight,
                "in_flight": self.admission.tenant_in_flight(name),
                "batch_gate": gate.get(name, {}),
                "locked": locked.get(name, {}),
            }
        return out

    # ------------------------------------------------------------------
    def _pipeline_status(self) -> dict:
        """Stage-timing + serving-plane block for node status — the
        server-side breakdown the wire bench freezes into its artifact
        (decode / parked / launch / writeback µs per stage)."""
        m = self.metrics

        def us(h):
            s = h.summary()
            return {
                "count": s["count"],
                "sum_ms": round(s["count"] * s["mean"] * 1e3, 3),
                "mean_us": round(s["mean"] * 1e6, 1),
                "p50_us": round(s["p50"] * 1e6, 1),
                "p99_us": round(s["p99"] * 1e6, 1),
            }

        out = {
            "epoch_reads": self._epoch_reads,
            "stages": {
                "decode": us(m.stage_decode_seconds),
                "parked": us(m.stage_parked_seconds),
                "launch": us(m.stage_launch_seconds),
                "writeback": us(m.stage_writeback_seconds),
            },
            "reads": {
                path[0]: int(v)
                for path, v in sorted(m.serving_reads.snapshot().items())
            },
            "snapshot_cache": {
                ev[0]: int(v)
                for ev, v in sorted(m.snapshot_cache.snapshot().items())
            },
            "epoch_publish": {
                mode[0]: int(v)
                for mode, v in sorted(m.epoch_publish.snapshot().items())
            },
            "serving_epoch_id": int(m.serving_epoch_id.value()),
            "writeback_depth": self._writeback_q.qsize(),
            "locked_depth": self._locked_q.qsize(),
            "group_commit_window_us": round(self._group_window_s * 1e6, 1),
        }
        if self.native is not None:
            out["native"] = self.native.stats()
        if self.proxy is not None:
            out["proxy"] = self.proxy.stats()
        txm = getattr(self.node, "txm", None)
        if txm is not None:
            out["snapshot_cache"]["size"] = len(txm.store.snapshot_cache)
            out["snapshot_cache"]["cap"] = txm.store.snapshot_cache_cap
            if txm.store.mesh is not None:
                out["mesh"] = txm.store.mesh.status()
            out["materializer"] = txm.store.materializer_status()
        return out

    # ------------------------------------------------------------------
    def is_alive(self) -> bool:
        """Supervision probe (supervise.Supervisor child health)."""
        return self._thread.is_alive()

    def close(self) -> None:
        self._closing = True
        self._ticker_stop.set()
        if self.proxy is not None:
            self.proxy.close()
        self._server.shutdown()
        self._server.server_close()
        if self.native is not None:
            # unwire the mirror FIRST: kv.py must stop pushing into a
            # handle about to be quarantined
            txm = getattr(self.node, "txm", None)
            if txm is not None and getattr(txm.store, "native_mirror",
                                           None) is self.native:
                txm.store.native_mirror = None
            self.native.close()
            if self._native_drain is not None:
                self._native_drain.join(timeout=5)
        if self.batch_static:
            # the gate is bounded now: a full queue + wedged dispatcher
            # must not turn close() into a forever-blocking put
            stop_by = time.monotonic() + 5.0
            while True:
                try:
                    self._static_q.put_nowait(_STOP)
                    break
                except queue.Full:
                    if time.monotonic() >= stop_by:
                        break  # dispatcher wedged; it is a daemon thread
                    time.sleep(0.05)
            self._batcher.join(timeout=5)
            # stop the writeback stage AFTER the dispatcher: in-flight
            # launched batches still get materialized and replied.
            # Fresh grace window — the gate put loop + batcher join may
            # have consumed the earlier one entirely, and giving up on
            # the first Full would drop in-flight replies.
            stop_by = time.monotonic() + 5.0
            while True:
                try:
                    self._writeback_q.put_nowait(_STOP)
                    break
                except queue.Full:
                    if time.monotonic() >= stop_by:
                        break
                    time.sleep(0.05)
            self._writeback.join(timeout=5)
            # the dispatcher's stop path forwarded _STOP to the locked
            # worker; it drains whatever raced in behind the sentinel
            self._locked_worker.join(timeout=5)
        if self._ticker_runs:
            self._ticker.join(timeout=5)
        self._thread.join(timeout=5)
