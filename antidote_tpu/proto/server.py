"""Protocol server: TCP acceptor pool + request dispatcher.

The ranch listener (100 acceptors, max 1024 conns, port 8087 —
/root/reference/src/antidote_pb_sup.erl:47-56) becomes a
``ThreadingTCPServer``; the decode→process→encode loop with error replies
mirrors ``antidote_pb_protocol:loop/handle``
(/root/reference/src/antidote_pb_protocol.erl:51-88), and the dispatch
table mirrors ``antidote_pb_process:process/1``
(/root/reference/src/antidote_pb_process.erl:49-135).

The node's transaction manager is a single commit stream, so requests are
serialized through one lock — concurrency buys pipelining of socket IO,
matching the single-writer-per-partition design (SURVEY §2.10 row 2).
"""

from __future__ import annotations

import itertools
import logging
import queue
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.overload import (
    AdmissionGate,
    BusyError,
    DeadlineExceeded,
    ReadOnlyError,
    check_deadline,
    deadline_from_ms,
)
from antidote_tpu.proto import apb
from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    encode_value,
    freeze,
    read_frame,
    write_frame_body,
    write_message,
)
from antidote_tpu.txn.manager import AbortError, Transaction

DEFAULT_PORT = 8087
log = logging.getLogger(__name__)

_STOP = object()


class _StaticWork:
    """One client's static read/update parked at the batch gate."""

    __slots__ = ("kind", "objects", "updates", "clock", "event", "result",
                 "error", "deadline")

    def __init__(self, kind, objects=None, updates=None, clock=None,
                 deadline=None):
        self.kind = kind
        self.objects = objects
        self.updates = updates
        self.clock = clock
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        #: absolute monotonic deadline (None = none): checked when the
        #: batch dispatcher DEQUEUES the work — a request that outlived
        #: its caller while parked is aborted, not executed
        self.deadline: Optional[float] = deadline


def _decode_objects(objs):
    return [(freeze(k), t, b) for k, t, b in (freeze(o) for o in objs)]


def _decode_updates(ups):
    return [(freeze(k), t, b, freeze(op)) for k, t, b, op in
            (freeze(u) for u in ups)]


def _vc(x) -> Optional[np.ndarray]:
    return None if x is None else np.asarray(x, np.int32)


class ProtocolServer:
    def __init__(self, node: AntidoteNode, host: str = "127.0.0.1",
                 port: int = 0, interdc=None, max_connections: int = 1024,
                 batch_static: bool = True, max_in_flight: int = 256,
                 max_in_flight_per_client: int = 64, queue_max: int = 4096,
                 default_deadline_ms: Optional[float] = None):
        self.node = node
        #: DCReplica for the descriptor/connect requests (optional)
        self.interdc = interdc
        self._lock = threading.Lock()
        self._txns: Dict[int, Transaction] = {}
        #: metric sink for the overload planes: the node's own registry
        #: when it has one; a ClusterNode facade exposes its member's
        #: (one registry per process either way)
        self.metrics = getattr(node, "metrics", None)
        if self.metrics is None:
            inner = getattr(getattr(node, "member", None), "node", None)
            self.metrics = getattr(inner, "metrics", None)
        if self.metrics is None:
            from antidote_tpu.obs import NodeMetrics

            self.metrics = NodeMetrics()
        #: overload admission (PR 4): global + per-client (peer host)
        #: in-flight caps.  Past a cap, the request is answered with a
        #: typed busy error carrying a retry-after hint — never parked
        #: forever (the riak_core vnode overload answer, {error,
        #: overload}).  Per-HOST, not per-socket: each connection's
        #: handler thread is serial, so per-socket in-flight never
        #: exceeds 1 — bounding a client machine's whole connection
        #: fleet is what actually prevents monopolization
        self.admission = AdmissionGate(
            max_in_flight, max_in_flight_per_client,
            gauge=self.metrics.in_flight,
        )
        #: default per-request deadline (ms) when the client sends none;
        #: None = requests without a deadline_ms field never expire
        self.default_deadline_ms = default_deadline_ms
        self._conn_ids = itertools.count(1)
        #: cross-connection batch gate (r4 VERDICT item 3): static
        #: reads/updates from concurrent connections coalesce into single
        #: device launches instead of one launch per socket — the wire
        #: analogue of SURVEY §2.10 "batch thousands of reads per launch"
        #: (the reference scales the same path with 20 read servers per
        #: partition, /root/reference/include/antidote.hrl:28)
        self.batch_static = batch_static
        self._closing = False
        #: BOUNDED: a full gate answers busy instead of buffering without
        #: limit (admission usually sheds first; this cap is the backstop
        #: against a stalled dispatcher)
        self._static_q: "queue.Queue" = queue.Queue(maxsize=queue_max)
        self._batch_max = 1024
        if batch_static:
            self._batcher = threading.Thread(
                target=self._static_loop, daemon=True,
                name="antidote-proto-batch",
            )
            self._batcher.start()
        #: connection cap (the reference's ranch listener caps at 1024,
        #: /root/reference/src/antidote_pb_sup.erl:47-56).  The accept
        #: loop blocks on the semaphore when the cap is reached, so
        #: excess connections queue in the kernel listen backlog instead
        #: of exhausting server threads — ranch's backpressure shape.
        self.max_connections = max_connections
        self._conn_slots = threading.BoundedSemaphore(max_connections)
        handler = self._make_handler()
        conn_slots = self._conn_slots

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            closing = False
            # while the accept loop parks on the cap, excess connections
            # must queue in the kernel listen backlog (ranch's shape) —
            # the socketserver default of 5 would drop their SYNs
            request_queue_size = max_connections

            def shutdown(self):
                self.closing = True
                super().shutdown()

            def process_request(self, request, client_address):
                # hold the accept loop until a slot frees: backpressure,
                # not thread-per-connection without bound.  Poll so a
                # shutdown() issued while the cap is saturated can still
                # unpark the serve_forever loop instead of deadlocking.
                while not conn_slots.acquire(timeout=0.1):
                    if self.closing:
                        self.shutdown_request(request)
                        return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    conn_slots.release()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    conn_slots.release()

        self._server = Server((host, port), handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"antidote-proto:{self.port}",
        )
        self._thread.start()

    # ------------------------------------------------------------------
    def _make_handler(server_self):
        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # txns this connection started and has not finished: a
                # dropped connection must not pin open transactions (they
                # hold the certification-GC floor — manager._open_snaps —
                # forever; the reference's coordinator FSMs die with the
                # client process and roll back the same way)
                conn_txns = set()
                try:
                    self._serve(conn_txns)
                finally:
                    for txid in conn_txns:
                        server_self._abort_orphan(txid)

            def _serve(self, conn_txns):
                # admission key = peer host: one client machine's whole
                # connection fleet shares one per-client budget
                try:
                    client_id = self.request.getpeername()[0]
                except OSError:
                    client_id = f"conn{next(server_self._conn_ids)}"
                metrics = server_self.metrics
                while True:
                    try:
                        frame = read_frame(self.request)
                    except (ConnectionError, OSError):
                        return
                    # ADMISSION (PR 4): acquire an in-flight slot before
                    # any decode/dispatch work.  Past the global or
                    # per-client cap the request is answered with a
                    # typed busy error + retry-after hint — the client
                    # backs off, the server never queues unboundedly.
                    t0 = time.monotonic()
                    try:
                        server_self.admission.enter(client_id)
                    except BusyError as e:
                        metrics.shed.inc(plane="server")
                        if not self._reply_error(frame, "busy", e):
                            return
                        continue
                    try:
                        if not self._handle_admitted(frame, conn_txns):
                            return
                    finally:
                        server_self.admission.exit(client_id)
                        metrics.server_request_seconds.observe(
                            time.monotonic() - t0)

            def _reply_error(self, frame, kind: str, e) -> bool:
                """Typed error reply in the FRAME'S dialect; False when
                the connection died mid-write."""
                retry_ms = int(getattr(e, "retry_after_ms", 0))
                try:
                    if frame and frame[0] in apb.APB_REQUEST_CODES:
                        write_frame_body(self.request, apb.overload_error(
                            kind, str(e), retry_ms))
                    else:
                        resp = {"error": kind, "detail": str(e)}
                        if retry_ms:
                            resp["retry_after_ms"] = retry_ms
                        write_message(self.request,
                                      MessageCode.ERROR_RESP, resp)
                    return True
                except (ConnectionError, OSError):
                    return False

            def _handle_admitted(self, frame, conn_txns) -> bool:
                """One admitted request end-to-end; False = drop conn."""
                # dialect dispatch on the code byte: antidote_pb
                # request codes (apb.APB_REQUEST_CODES) are disjoint
                # from the native msgpack codes, so existing
                # antidotec_pb clients connect to the same port
                if frame and frame[0] in apb.APB_REQUEST_CODES:
                    resp_body = apb.handle_request(
                        server_self, frame[0], frame[1:], conn_txns,
                        lock=server_self._lock,
                    )
                    try:
                        write_frame_body(self.request, resp_body)
                    except (ConnectionError, OSError):
                        return False
                    return True
                try:
                    code, body = decode(frame)
                    resp_code, resp = server_self._process(code, body)
                    if code == MessageCode.START_TRANSACTION:
                        conn_txns.add(resp["txid"])
                    elif code in (MessageCode.COMMIT_TRANSACTION,
                                  MessageCode.ABORT_TRANSACTION):
                        conn_txns.discard(body.get("txid"))
                except AbortError as e:
                    if code == MessageCode.UPDATE_OBJECTS:
                        conn_txns.discard(body.get("txid"))
                    resp_code, resp = MessageCode.ERROR_RESP, {
                        "error": "aborted", "detail": str(e)
                    }
                except BusyError as e:
                    # downstream cap (commit backlog / batch gate):
                    # same typed shape as the admission shed
                    resp_code, resp = MessageCode.ERROR_RESP, {
                        "error": "busy", "detail": str(e),
                        "retry_after_ms": int(e.retry_after_ms),
                    }
                except DeadlineExceeded as e:
                    resp_code, resp = MessageCode.ERROR_RESP, {
                        "error": "deadline", "detail": str(e)
                    }
                except ReadOnlyError as e:
                    resp_code, resp = MessageCode.ERROR_RESP, {
                        "error": "read_only", "detail": str(e)
                    }
                except Exception as e:  # error reply, keep the conn
                    log.exception("request failed")
                    resp_code, resp = MessageCode.ERROR_RESP, {
                        "error": type(e).__name__, "detail": str(e)
                    }
                try:
                    write_message(self.request, resp_code, resp)
                except (ConnectionError, OSError):
                    return False
                return True

        return Handler

    def _abort_orphan(self, txid: int) -> None:
        """Roll back a transaction whose client connection died."""
        with self._lock:
            txn = self._txns.pop(txid, None)
            if txn is not None and txn.active:
                self.node.abort_transaction(txn)

    # ------------------------------------------------------------------
    # static batch gate
    # ------------------------------------------------------------------
    def static_read(self, objects, clock, deadline=None):
        """Batched static read: (values, snapshot_vc)."""
        if not self.batch_static:
            with self._lock:
                check_deadline(deadline, "dispatch")
                return self.node.read_objects(objects, clock=_vc(clock))
        return self._submit(_StaticWork("read", objects=objects,
                                        clock=_vc(clock),
                                        deadline=deadline))

    def static_update(self, updates, clock, deadline=None):
        """Batched static update: commit VC (raises AbortError on cert)."""
        if not self.batch_static:
            with self._lock:
                check_deadline(deadline, "dispatch")
                return self.node.update_objects(updates, clock=_vc(clock))
        return self._submit(_StaticWork("update", updates=updates,
                                        clock=_vc(clock),
                                        deadline=deadline))

    def _submit(self, work: _StaticWork):
        if self._closing:
            raise ConnectionError("server shutting down")
        try:
            # bounded gate: shed with a typed busy error instead of
            # parking behind an unbounded backlog
            self._static_q.put_nowait(work)
        except queue.Full:
            self.metrics.shed.inc(plane="server_queue")
            raise BusyError(
                f"static batch gate full ({self._static_q.maxsize} "
                "requests parked)", retry_after_ms=100,
            ) from None
        self.metrics.commit_gate_depth.set(self._static_q.qsize())
        if not work.event.wait(timeout=300):
            raise TimeoutError("static batch dispatcher stalled")
        if work.error is not None:
            raise work.error
        return work.result

    def _static_loop(self):
        """The batch dispatcher: drain whatever has queued while the
        previous group executed, run updates as ONE group commit and reads
        as ONE merged snapshot read.  Natural batching — no gather delay:
        at low load a lone request runs immediately; under load the batch
        grows to whatever queued during the previous launch."""
        q = self._static_q
        while True:
            first = q.get()
            batch = [first]
            while len(batch) < self._batch_max:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            stop = any(w is _STOP for w in batch)
            works: List[_StaticWork] = [w for w in batch if w is not _STOP]
            self.metrics.commit_gate_depth.set(q.qsize())
            # deadline discipline: work that outlived its caller while
            # parked is aborted AT DEQUEUE — executing it would burn a
            # device launch on a reply nobody is waiting for
            live: List[_StaticWork] = []
            for w in works:
                if w.deadline is not None and time.monotonic() > w.deadline:
                    self.metrics.shed.inc(plane="deadline")
                    w.error = DeadlineExceeded(
                        "request deadline passed while parked at the "
                        "batch gate; not executed")
                    w.event.set()
                else:
                    live.append(w)
            works = live
            try:
                ups = [w for w in works if w.kind == "update"]
                reads = [w for w in works if w.kind == "read"]
                with self._lock:
                    # updates first: the merged read then serves at a
                    # snapshot covering them (fresh-path + cache friendly)
                    if ups:
                        self._run_update_group(ups)
                    if reads:
                        self._run_read_group(reads)
                    self._maybe_publish_epochs()
            except BaseException as e:  # never strand a parked connection
                for w in works:
                    if not w.event.is_set():
                        w.error = e
                        w.event.set()
            if stop:
                # fail anything that raced the shutdown into the queue —
                # a handler parked behind the sentinel must not wait out
                # its submit timeout
                while True:
                    try:
                        w = q.get_nowait()
                    except queue.Empty:
                        return
                    if w is not _STOP:
                        w.error = ConnectionError("server shutting down")
                        w.event.set()

    #: serving-epoch publication cadence (seconds): each tick freezes the
    #: tables' heads so reads pinned at/below that snapshot stay pure
    #: gathers while writes advance (the read-while-write double buffer —
    #: without a production publisher the epoch machinery would only ever
    #: run in benchmarks)
    EPOCH_PUBLISH_S = 2.0
    _last_epoch_pub = 0.0
    _epoch_pub_mutations = -1

    def _maybe_publish_epochs(self) -> None:
        txm = getattr(self.node, "txm", None)
        if txm is None:
            return  # cluster members publish at their own stores
        import time as _t

        now = _t.monotonic()
        if now - self._last_epoch_pub < self.EPOCH_PUBLISH_S:
            return
        store = txm.store
        # freeze a table when (a) new commits landed since its last
        # freeze AND (b) some read actually took the slow path since
        # then — (a) alone copies heads for workloads that never fold,
        # (b) alone is satisfied forever by one old historical read.
        # Checked PER TABLE so a slow read arriving after writes
        # quiesced still gets its epoch on the next tick (the global
        # early-return variant starved exactly that case).
        published = False
        for t in store.tables.values():
            if (t.slow_serves != getattr(t, "_pub_slow_serves", -1)
                    and store.mutation_epoch != getattr(t, "_pub_mut", -1)):
                t._pub_slow_serves = t.slow_serves
                t._pub_mut = store.mutation_epoch
                t.publish_epoch()
                published = True
        if published:
            self._last_epoch_pub = now

    def _run_read_group(self, works: List[_StaticWork]) -> None:
        # requests whose causal clock is already covered locally merge
        # into ONE snapshot read; a clock AHEAD of local replication (or
        # bogus) must WAIT inside start_transaction — running it solo
        # keeps one slow client from head-of-line-blocking the batch
        covered = self._covered_vc()
        merged, solo = [], []
        for w in works:
            if w.clock is None or (covered is not None
                                   and (w.clock <= covered).all()):
                merged.append(w)
            else:
                solo.append(w)
        if merged:
            clock = None
            for w in merged:
                if w.clock is not None:
                    clock = (w.clock if clock is None
                             else np.maximum(clock, w.clock))
            objs: list = []
            offs = [0]
            for w in merged:
                objs.extend(w.objects)
                offs.append(len(objs))
            try:
                vals, vc = self.node.read_objects(objs, clock=clock)
                for i, w in enumerate(merged):
                    w.result = (vals[offs[i]:offs[i + 1]], vc)
                    w.event.set()
            except Exception:
                solo = merged + solo  # isolate the offender
        for w in solo:
            if w.event.is_set():
                continue
            try:
                w.result = self.node.read_objects(w.objects, clock=w.clock)
            except Exception as e:
                w.error = e
            w.event.set()

    def _covered_vc(self):
        """Freshest locally-covered clock (entry-wise), or None when the
        node doesn't expose one (then every clocked read runs solo)."""
        txm = getattr(self.node, "txm", None)
        if txm is not None:
            vc = txm.store.dc_max_vc().copy()
            vc[txm.my_dc] = max(int(vc[txm.my_dc]), txm.commit_counter)
            return vc
        member = getattr(self.node, "member", None)
        if member is not None:
            return np.asarray(member.stable_vc())
        return None

    def _run_update_group(self, works: List[_StaticWork]) -> None:
        txm = getattr(self.node, "txm", None)
        if txm is None or len(works) == 1:
            # cluster coordinator (2PC) or a lone update: sequential path
            for w in works:
                try:
                    w.result = self.node.update_objects(w.updates,
                                                        clock=w.clock)
                except Exception as e:
                    w.error = e
                w.event.set()
            return
        pending = list(works)
        # Group members share a snapshot, so two blind writes to one hot
        # key first-committer-abort each other — a conflict the pre-batch
        # serial path could never produce (each request's snapshot
        # followed the previous commit).  Losers retry as a FOLLOW-UP
        # GROUP at a fresh snapshot (≥1 winner per round → ≤N rounds,
        # still one device append per round) — equivalent to some serial
        # interleaving, so no spurious abort escapes to a client.
        while pending:
            staged = []
            for w in pending:
                # re-check per-work deadlines at every retry round: a
                # conflict-retry loop under load must not keep executing
                # work whose caller has already timed out
                if (w.deadline is not None
                        and time.monotonic() > w.deadline):
                    self.metrics.shed.inc(plane="deadline")
                    w.error = DeadlineExceeded(
                        "request deadline passed before commit; "
                        "not executed")
                    w.event.set()
                    continue
                try:
                    txn = txm.start_transaction(w.clock)
                    try:
                        txm.update_objects(w.updates, txn)
                    except Exception:
                        txm.abort_transaction(txn)
                        raise
                    staged.append((w, txn))
                except Exception as e:
                    w.error = e
                    w.event.set()
            if not staged:
                return
            try:
                outs = txm.commit_transactions_group([t for _, t in staged])
            except Exception as e:
                # a backlog-shed group comes back with its txns still
                # OPEN (retryable for interactive holders) — but these
                # txns are server-created and the static clients only
                # ever see the error reply, so abort them here
                for w, txn in staged:
                    if txn.active:
                        txm.abort_transaction(txn)
                    w.error = e
                    w.event.set()
                return
            retry = []
            for (w, _), r in zip(staged, outs):
                if isinstance(r, AbortError):
                    retry.append(w)
                elif isinstance(r, Exception):
                    w.error = r
                    w.event.set()
                else:
                    w.result = r
                    w.event.set()
            pending = retry

    # ------------------------------------------------------------------
    def _process(self, code: MessageCode, body: Any):
        # per-request deadline: client-supplied relative ``deadline_ms``
        # (native dialect only), else the configured server default.
        # Work that outlives it while queued is aborted at dequeue.
        deadline = deadline_from_ms(
            body.get("deadline_ms") if isinstance(body, dict) else None,
            self.default_deadline_ms,
        )
        # static ops route through the gate helpers OUTSIDE the lock (the
        # gate's dispatcher takes it; with batching off they lock inline)
        # — the ONLY static dispatch path, so it cannot drift from a
        # duplicate
        if code == MessageCode.STATIC_READ_OBJECTS:
            vals, vc = self.static_read(
                _decode_objects(body["objects"]), body.get("clock"),
                deadline=deadline,
            )
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals],
                "commit_clock": [int(x) for x in vc],
            }
        if code == MessageCode.STATIC_UPDATE_OBJECTS:
            vc = self.static_update(
                _decode_updates(body["updates"]), body.get("clock"),
                deadline=deadline,
            )
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in vc]
            }
        with self._lock:
            # deadline re-checked at dequeue (= after the lock convoy):
            # a request that outlived its caller is not executed
            try:
                check_deadline(deadline, "dispatch")
            except DeadlineExceeded:
                self.metrics.shed.inc(plane="deadline")
                raise
            return self._dispatch(code, body)

    def _dispatch(self, code: MessageCode, body: Any):
        node = self.node
        if code == MessageCode.START_TRANSACTION:
            txn = node.start_transaction(
                clock=_vc(body.get("clock")), props=body.get("props"),
            )
            self._txns[txn.txid] = txn
            return MessageCode.START_TRANSACTION_RESP, {"txid": txn.txid}
        if code == MessageCode.READ_OBJECTS:
            txn = self._txn(body["txid"])
            vals = node.read_objects(_decode_objects(body["objects"]), txn)
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals]
            }
        if code == MessageCode.UPDATE_OBJECTS:
            txn = self._txn(body["txid"])
            try:
                node.update_objects(_decode_updates(body["updates"]), txn)
            except AbortError:
                self._txns.pop(body["txid"], None)
                raise
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.COMMIT_TRANSACTION:
            # keep the txn registered until the outcome is known: a
            # commit-backlog BusyError leaves it OPEN (the shed happens
            # before the group touches it), so the busy reply's retry
            # hint is honest — the SAME commit can be resubmitted
            txn = self._txn(body["txid"])
            try:
                commit_vc = node.commit_transaction(txn)
            except BusyError:
                raise
            except BaseException:
                self._txns.pop(body["txid"], None)  # txn is dead
                raise
            self._txns.pop(body["txid"], None)
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in commit_vc]
            }
        if code == MessageCode.ABORT_TRANSACTION:
            txn = self._txns.pop(body["txid"])
            node.abort_transaction(txn)
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.GET_CONNECTION_DESCRIPTOR:
            return MessageCode.OPERATION_RESP, {
                "descriptor": self._get_descriptor(),
            }
        if code == MessageCode.CONNECT_TO_DCS:
            self._connect_to_dcs(body.get("descriptors", []))
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.CREATE_DC:
            self._create_dc(body.get("nodes", []))
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.NODE_STATUS:
            status = node.status(
                include_ready=bool(body.get("include_ready"))
            )
            # the server's own admission plane rides along (the node
            # object can't see it)
            status.setdefault("overload", {}).update({
                "in_flight": self.admission.in_flight(),
                "max_in_flight": self.admission.max_in_flight,
                "max_in_flight_per_client": self.admission.max_per_client,
                "batch_gate_depth": self._static_q.qsize(),
                "batch_gate_max": self._static_q.maxsize,
            })
            return MessageCode.OPERATION_RESP, {"status": status}
        raise ValueError(f"unhandled message code {code!r}")

    def _txn(self, txid: int) -> Transaction:
        txn = self._txns.get(txid)
        if txn is None:
            raise KeyError(f"unknown or finished transaction {txid}")
        return txn

    # ------------------------------------------------------------------
    # DC management (antidote_pb_process:process create_dc /
    # get_connection_descriptor / connect_to_dcs clauses,
    # /root/reference/src/antidote_pb_process.erl:103-135) — shared by
    # both wire dialects
    # ------------------------------------------------------------------
    def _get_descriptor(self) -> dict:
        if self.interdc is None:
            raise RuntimeError("no inter-DC replica attached")
        return self.interdc.descriptor().to_wire()

    def _connect_to_dcs(self, descriptors) -> None:
        if self.interdc is None:
            raise RuntimeError("no inter-DC replica attached")
        for d in descriptors:
            self.interdc.observe_descriptor(d)

    def _create_dc(self, nodes) -> None:
        """The reference assembles a riak cluster from ``nodes`` here;
        this build's DC is assembled at boot (console serve /
        cluster.boot ctl_wire), so a single-node list is acknowledged
        (the DC exists) and a multi-node list is refused with the
        operator path, matching create_dc's error reply shape."""
        if len(nodes) > 1:
            raise RuntimeError(
                "create_dc_failed: multi-member DCs assemble via "
                "cluster.boot + ctl_wire, not the client protocol"
            )

    # ------------------------------------------------------------------
    def is_alive(self) -> bool:
        """Supervision probe (supervise.Supervisor child health)."""
        return self._thread.is_alive()

    def close(self) -> None:
        self._closing = True
        self._server.shutdown()
        self._server.server_close()
        if self.batch_static:
            # the gate is bounded now: a full queue + wedged dispatcher
            # must not turn close() into a forever-blocking put
            stop_by = time.monotonic() + 5.0
            while True:
                try:
                    self._static_q.put_nowait(_STOP)
                    break
                except queue.Full:
                    if time.monotonic() >= stop_by:
                        break  # dispatcher wedged; it is a daemon thread
                    time.sleep(0.05)
            self._batcher.join(timeout=5)
        self._thread.join(timeout=5)
