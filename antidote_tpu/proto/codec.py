"""Frame + message codec.

Framing mirrors ``antidote_pb_protocol``: a 4-byte big-endian length
prefix, then a 1-byte message code and the body
(/root/reference/src/antidote_pb_protocol.erl:42-64 — ``{packet, 4}``
plus the msg-code byte handled by antidote_pb_codec).  The body is msgpack
rather than protobuf; the request set mirrors the ``antidote_pb_process``
clauses (/root/reference/src/antidote_pb_process.erl:49-135).
"""

from __future__ import annotations

import enum
import socket
import struct
from typing import Any, Tuple

import msgpack


class MessageCode(enum.IntEnum):
    # requests (antidote_pb_process:process/1 clauses)
    START_TRANSACTION = 1
    READ_OBJECTS = 2
    UPDATE_OBJECTS = 3
    COMMIT_TRANSACTION = 4
    ABORT_TRANSACTION = 5
    STATIC_UPDATE_OBJECTS = 6
    STATIC_READ_OBJECTS = 7
    GET_CONNECTION_DESCRIPTOR = 8
    CONNECT_TO_DCS = 9
    CREATE_DC = 10
    NODE_STATUS = 11  # console/ops extension (no reference pb equivalent)
    CHECKPOINT_NOW = 12  # ops extension: synchronous checkpoint cycle
    REPLICA_ADMIN = 13  # ops extension: follower-replica registry
    # (add/remove/status against the owner's replica plane)
    # responses
    OPERATION_RESP = 64
    START_TRANSACTION_RESP = 65
    READ_OBJECTS_RESP = 66
    COMMIT_RESP = 67
    ERROR_RESP = 127


MAX_FRAME = 64 * 1024 * 1024


def freeze(x: Any) -> Any:
    """msgpack round-trips tuples as lists; keys and ops must come back
    hashable/structured, so freeze lists into tuples recursively."""
    if isinstance(x, list):
        return tuple(freeze(v) for v in x)
    return x


def encode_value(v: Any) -> Any:
    """Client-visible CRDT values may be dicts keyed by (field, type)
    tuples (map_rr/map_go); msgpack maps cannot carry tuple keys, so dicts
    ride as tagged pair lists."""
    if isinstance(v, dict):
        return {"__map__": [[list(k), encode_value(x)] for k, x in v.items()]}
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    return v


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and "__map__" in v:
        return {freeze(k): decode_value(x) for k, x in v["__map__"]}
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


def merge_clock(token, clock):
    """Entry-wise max of two session clocks (either may be None) — the
    SESSION TOKEN update rule: a client folds every commit clock and
    read snapshot it observes into its token, and sends the token as the
    causal ``clock`` of later requests, so read-your-writes and
    monotonic reads hold across any replica it fails over to.  Lives in
    the codec because the token IS the wire clock — one place owns its
    shape (a plain list of per-DC ints)."""
    if token is None:
        return None if clock is None else [int(x) for x in clock]
    if clock is None:
        return [int(x) for x in token]
    a, b = [int(x) for x in token], [int(x) for x in clock]
    if len(b) > len(a):
        a += [0] * (len(b) - len(a))
    if len(a) > len(b):
        b += [0] * (len(a) - len(b))
    return [max(x, y) for x, y in zip(a, b)]


def encode(code: MessageCode, body: Any) -> bytes:
    payload = msgpack.packb(body, use_bin_type=True)
    return struct.pack(">IB", len(payload) + 1, int(code)) + payload


def encode_with(packer: "msgpack.Packer", code: MessageCode,
                body: Any) -> bytes:
    """Framed encode through a caller-owned persistent Packer (hot-path
    clients skip per-call packer construction) — same frame layout as
    :func:`encode`, owned here so the wire contract lives in one file."""
    payload = packer.pack(body)
    return struct.pack(">IB", len(payload) + 1, int(code)) + payload


def decode(frame: bytes) -> Tuple[MessageCode, Any]:
    code = MessageCode(frame[0])
    body = msgpack.unpackb(frame[1:], raw=False, strict_map_key=False)
    return code, body


def read_frame(sock: socket.socket) -> bytes:
    """Read one length-prefixed frame (code byte + body) off a socket."""
    hdr = _read_exact(sock, 4)
    (n,) = struct.unpack(">I", hdr)
    if not 1 <= n <= MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    return _read_exact(sock, n)


def read_frame_buffered(rfile) -> bytes:
    """Read one frame off a buffered binary file (``sock.makefile('rb')``)
    — the serving hot path's framing: the buffer coalesces the header +
    body reads into ~one syscall per request instead of 2+ recv calls."""
    hdr = rfile.read(4)
    if len(hdr) < 4:
        raise ConnectionError("peer closed")
    (n,) = struct.unpack(">I", hdr)
    if not 1 <= n <= MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    body = rfile.read(n)
    if len(body) < n:
        raise ConnectionError("peer closed")
    return body


def write_message(sock: socket.socket, code: MessageCode, body: Any) -> None:
    sock.sendall(encode(code, body))


def write_frame_body(sock: socket.socket, body: bytes) -> None:
    """Frame pre-encoded (code byte + payload) bytes — the apb codec
    builds its own bodies."""
    sock.sendall(struct.pack(">I", len(body)) + body)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)
