"""Client wire protocol (SURVEY §2.1).

The reference speaks length-prefixed protobuf over TCP port 8087
(antidote_pb_protocol / antidote_pb_process / antidote_pb_sup,
/root/reference/src/antidote_pb_protocol.erl:42-88).  Here the same
semantic surface rides 4-byte-length frames carrying a 1-byte message code
plus a msgpack body.
"""

from antidote_tpu.proto.client import AntidoteClient
from antidote_tpu.proto.codec import MessageCode, decode, encode
from antidote_tpu.proto.server import ProtocolServer, DEFAULT_PORT

__all__ = [
    "AntidoteClient",
    "MessageCode",
    "ProtocolServer",
    "DEFAULT_PORT",
    "decode",
    "encode",
]
