// Native serving front-end (ISSUE 16): accept + framing + decode +
// admission + whole-batch snapshot-cache hits off the GIL.
//
// Extends the epoll io-thread pattern of interdc/cpp/pump.cc (the
// libzmq io-thread role) into the ranch-listener role of the reference
// (antidote_pb_sup.erl:47-56 — 100 acceptors / 1024 conns / {packet,4}
// framing): ONE epoll thread owns the listen socket, every client
// connection's read buffer, 4-byte big-endian length framing, a minimal
// msgpack scan of STATIC_READ_OBJECTS bodies, the admission gate
// (global + per-peer-host in-flight caps, the overload.py semantics),
// and a mirror of the hot-key snapshot cache (epoch-id-stamped entries
// pushed down from Python at writeback/publish time).  A clockless read
// whose every object resolves from the mirror at the current serving
// epoch is answered entirely here — byte-identical to the Python
// fast path (proto/server.py _try_cache_read) — and Python only ever
// sees cache misses, writes, interactive txns and foreign-dialect
// frames via one packed batch-drain crossing (frontend_take_batch, one
// GIL acquisition per drain, like pump_take_batch).
//
// Build: python -m antidote_tpu.native_build (pinned flags; embeds the
// source sha for `make native-check`).  No third-party deps.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t MAX_FRAME = 64u * 1024u * 1024u;  // codec.MAX_FRAME
constexpr size_t QUEUE_CAP = 65536;                   // pump.cc discipline
constexpr int MAX_EVENTS = 256;

// message codes (proto/codec.py) + the apb dialect's request codes
// (proto/apb.py APB_REQUEST_CODES) — apb frames always cross to Python
constexpr uint8_t STATIC_READ_OBJECTS = 7;
constexpr uint8_t READ_OBJECTS_RESP = 66;
constexpr uint8_t ERROR_RESP = 127;

bool is_apb(uint8_t c) {
  switch (c) {
    case 116: case 118: case 119: case 120: case 121: case 122:
    case 123: case 129: case 130: case 131:
      return true;
    default:
      return false;
  }
}

// ---------------------------------------------------------------------
// minimal msgpack helpers (canonical shapes — msgpack-python parity)
// ---------------------------------------------------------------------
struct Rd {
  const uint8_t* p;
  const uint8_t* end;
};

inline bool rd_need(const Rd& r, size_t n) {
  return static_cast<size_t>(r.end - r.p) >= n;
}

inline uint16_t be16(const uint8_t* p) {
  return (uint16_t(p[0]) << 8) | p[1];
}
inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | p[3];
}

// skip one msgpack object; false on malformed/truncated input
bool mp_skip(Rd& r) {
  if (!rd_need(r, 1)) return false;
  uint8_t t = *r.p++;
  size_t n = 0;     // trailing payload bytes
  size_t items = 0; // child objects (array: n, map: 2n)
  if (t <= 0x7f || t >= 0xe0 || t == 0xc0 || t == 0xc2 || t == 0xc3) {
    return true;                       // fixint / nil / bool
  } else if (t >= 0x80 && t <= 0x8f) { // fixmap
    items = size_t(t & 0x0f) * 2;
  } else if (t >= 0x90 && t <= 0x9f) { // fixarray
    items = t & 0x0f;
  } else if (t >= 0xa0 && t <= 0xbf) { // fixstr
    n = t & 0x1f;
  } else {
    switch (t) {
      case 0xc4: case 0xd9:  // bin8 / str8
        if (!rd_need(r, 1)) return false;
        n = *r.p++;
        break;
      case 0xc5: case 0xda:  // bin16 / str16
        if (!rd_need(r, 2)) return false;
        n = be16(r.p); r.p += 2;
        break;
      case 0xc6: case 0xdb:  // bin32 / str32
        if (!rd_need(r, 4)) return false;
        n = be32(r.p); r.p += 4;
        break;
      case 0xcc: case 0xd0: n = 1; break;  // uint8 / int8
      case 0xcd: case 0xd1: n = 2; break;  // uint16 / int16
      case 0xce: case 0xd2: case 0xca: n = 4; break;  // u32/i32/f32
      case 0xcf: case 0xd3: case 0xcb: n = 8; break;  // u64/i64/f64
      case 0xd4: n = 2; break;   // fixext1 (type byte + 1)
      case 0xd5: n = 3; break;
      case 0xd6: n = 5; break;
      case 0xd7: n = 9; break;
      case 0xd8: n = 17; break;
      case 0xc7:  // ext8: len byte + type byte + len payload
        if (!rd_need(r, 2)) return false;
        n = *r.p; r.p += 2;
        break;
      case 0xc8:  // ext16
        if (!rd_need(r, 3)) return false;
        n = be16(r.p); r.p += 3;
        break;
      case 0xc9:  // ext32
        if (!rd_need(r, 5)) return false;
        n = be32(r.p); r.p += 5;
        break;
      case 0xdc:  // array16
        if (!rd_need(r, 2)) return false;
        items = be16(r.p); r.p += 2;
        break;
      case 0xdd:  // array32
        if (!rd_need(r, 4)) return false;
        items = be32(r.p); r.p += 4;
        break;
      case 0xde:  // map16
        if (!rd_need(r, 2)) return false;
        items = size_t(be16(r.p)) * 2; r.p += 2;
        break;
      case 0xdf:  // map32
        if (!rd_need(r, 4)) return false;
        items = size_t(be32(r.p)) * 2; r.p += 4;
        break;
      default:
        return false;  // 0xc1: never used
    }
  }
  if (n) {
    if (!rd_need(r, n)) return false;
    r.p += n;
  }
  for (size_t i = 0; i < items; ++i)
    if (!mp_skip(r)) return false;
  return true;
}

// read a str header; returns payload span or false (non-str)
bool mp_str(Rd& r, const uint8_t** s, size_t* n) {
  if (!rd_need(r, 1)) return false;
  uint8_t t = *r.p;
  if (t >= 0xa0 && t <= 0xbf) {
    *n = t & 0x1f; ++r.p;
  } else if (t == 0xd9) {
    if (!rd_need(r, 2)) return false;
    *n = r.p[1]; r.p += 2;
  } else if (t == 0xda) {
    if (!rd_need(r, 3)) return false;
    *n = be16(r.p + 1); r.p += 3;
  } else if (t == 0xdb) {
    if (!rd_need(r, 5)) return false;
    *n = be32(r.p + 1); r.p += 5;
  } else {
    return false;
  }
  if (!rd_need(r, *n)) return false;
  *s = r.p;
  r.p += *n;
  return true;
}

bool mp_array_hdr(Rd& r, size_t* n) {
  if (!rd_need(r, 1)) return false;
  uint8_t t = *r.p;
  if (t >= 0x90 && t <= 0x9f) {
    *n = t & 0x0f; ++r.p;
  } else if (t == 0xdc) {
    if (!rd_need(r, 3)) return false;
    *n = be16(r.p + 1); r.p += 3;
  } else if (t == 0xdd) {
    if (!rd_need(r, 5)) return false;
    *n = be32(r.p + 1); r.p += 5;
  } else {
    return false;
  }
  return true;
}

bool mp_map_hdr(Rd& r, size_t* n) {
  if (!rd_need(r, 1)) return false;
  uint8_t t = *r.p;
  if (t >= 0x80 && t <= 0x8f) {
    *n = t & 0x0f; ++r.p;
  } else if (t == 0xde) {
    if (!rd_need(r, 3)) return false;
    *n = be16(r.p + 1); r.p += 3;
  } else if (t == 0xdf) {
    if (!rd_need(r, 5)) return false;
    *n = be32(r.p + 1); r.p += 5;
  } else {
    return false;
  }
  return true;
}

// canonical (msgpack-python) packers for the busy reply
void pack_str(std::vector<uint8_t>& o, const char* s, size_t n) {
  if (n < 32) {
    o.push_back(uint8_t(0xa0 | n));
  } else if (n < 256) {
    o.push_back(0xd9);
    o.push_back(uint8_t(n));
  } else {
    o.push_back(0xda);
    o.push_back(uint8_t(n >> 8));
    o.push_back(uint8_t(n));
  }
  o.insert(o.end(), s, s + n);
}
void pack_str(std::vector<uint8_t>& o, const std::string& s) {
  pack_str(o, s.data(), s.size());
}
void pack_uint(std::vector<uint8_t>& o, uint64_t v) {
  if (v < 128) {
    o.push_back(uint8_t(v));
  } else if (v < 256) {
    o.push_back(0xcc);
    o.push_back(uint8_t(v));
  } else if (v < 65536) {
    o.push_back(0xcd);
    o.push_back(uint8_t(v >> 8));
    o.push_back(uint8_t(v));
  } else {
    o.push_back(0xce);
    for (int s = 24; s >= 0; s -= 8) o.push_back(uint8_t(v >> s));
  }
}
void push_be32(std::vector<uint8_t>& o, uint32_t v) {
  for (int s = 24; s >= 0; s -= 8) o.push_back(uint8_t(v >> s));
}

// ---------------------------------------------------------------------
// core structures
// ---------------------------------------------------------------------
struct Frame {
  long conn_id;
  int kind;  // 0 = conn closed, 1 = admitted frame, 2 = shed (aux = hint)
  long aux;
  std::vector<uint8_t> payload;
};

struct Conn {
  int fd = -1;
  long id = 0;
  std::string host;
  std::vector<uint8_t> in;
  std::vector<uint8_t> out;
  size_t out_off = 0;
  long pending = 0;   // queued-to-Python frames awaiting frontend_send
  long admitted = 0;  // of which hold an admission slot
  bool closed = false;
  bool want_out = false;
  bool rd_eof = false;  // peer half-closed; drain replies, then close
};

struct ObjSpan {
  const uint8_t* key_b; size_t key_n;
  const uint8_t* type_b; size_t type_n;
  const uint8_t* buck_b; size_t buck_n;
};

struct Entry {
  long stamp;
  std::string type_frag;  // packed type-name str fragment
  std::string val;        // packed encode_value(v) fragment
};

struct Frontend {
  int epfd = -1, lfd = -1, wakefd = -1;
  int port = 0;
  std::thread thr;
  std::atomic<bool> stop{false};

  std::mutex mu;
  std::condition_variable cv;
  std::deque<Frame> q;

  std::unordered_map<long, Conn> conns;
  std::unordered_map<int, long> by_fd;
  std::vector<long> out_dirty;  // conns with output buffered off-thread
  long next_id = 1;
  long n_open = 0;
  bool accept_paused = false;

  int max_conns = 1024;
  long max_in_flight = 256;
  long max_per_host = 64;
  long g_inflight = 0;
  long shed_streak = 0;
  std::unordered_map<std::string, long> host_inflight;

  std::unordered_map<std::string, Entry> mirror;
  size_t mirror_cap = 1u << 18;
  long cur_epoch = -1;
  bool clockless_ok = false;
  bool fast_serve = true;
  std::string clock_frag;  // packed commit_clock int-list fragment

  // stats (all under mu except where noted)
  long st_accept = 0, st_closed = 0, st_frames = 0, st_hits = 0,
       st_hit_objs = 0, st_shed = 0, st_fwd = 0, st_bad_frame = 0;
  std::atomic<long> st_drains{0};

  std::vector<ObjSpan> scratch_objs;
};

void wake(Frontend* f) {
  uint64_t one = 1;
  ssize_t r = write(f->wakefd, &one, sizeof(one));
  (void)r;
}

void arm_out(Frontend* f, Conn& c) {
  if (c.fd < 0 || c.want_out) return;
  epoll_event ev{};
  // after a half-close the fd stays level-triggered-readable forever —
  // poll only the write side once rd_eof is set
  ev.events = (c.rd_eof ? 0 : EPOLLIN) | EPOLLOUT;
  ev.data.fd = c.fd;
  epoll_ctl(f->epfd, EPOLL_CTL_MOD, c.fd, &ev);
  c.want_out = true;
}

void disarm_out(Frontend* f, Conn& c) {
  if (c.fd < 0 || !c.want_out) return;
  epoll_event ev{};
  ev.events = c.rd_eof ? 0 : EPOLLIN;
  ev.data.fd = c.fd;
  epoll_ctl(f->epfd, EPOLL_CTL_MOD, c.fd, &ev);
  c.want_out = false;
}

// flush as much buffered output as the socket accepts (mu held)
void flush_out(Frontend* f, Conn& c) {
  while (c.fd >= 0 && c.out_off < c.out.size()) {
    ssize_t w = send(c.fd, c.out.data() + c.out_off,
                     c.out.size() - c.out_off, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (w > 0) {
      c.out_off += size_t(w);
    } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      arm_out(f, c);
      return;
    } else {
      return;  // peer gone; the read side will close the conn
    }
  }
  if (c.out_off >= c.out.size()) {
    c.out.clear();
    c.out_off = 0;
    disarm_out(f, c);
  }
}

void resume_accept(Frontend* f) {
  if (!f->accept_paused) return;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = f->lfd;
  epoll_ctl(f->epfd, EPOLL_CTL_ADD, f->lfd, &ev);
  f->accept_paused = false;
}

// close the socket; keep a tombstone while Python still owes replies so
// the admission decrements in frontend_send find their host (mu held)
void close_conn(Frontend* f, long cid) {
  auto it = f->conns.find(cid);
  if (it == f->conns.end()) return;
  Conn& c = it->second;
  if (c.fd >= 0) {
    epoll_ctl(f->epfd, EPOLL_CTL_DEL, c.fd, nullptr);
    f->by_fd.erase(c.fd);
    ::close(c.fd);
    c.fd = -1;
    --f->n_open;
    ++f->st_closed;
    resume_accept(f);
  }
  if (c.closed) return;
  c.closed = true;
  c.in.clear();
  c.in.shrink_to_fit();
  c.out.clear();
  // conn-drop sentinel: the bridge tears down the conn worker and
  // aborts orphaned interactive txns (Handler.handle's finally)
  f->q.push_back(Frame{cid, 0, 0, {}});
  f->cv.notify_all();
  if (c.pending <= 0) f->conns.erase(it);
}

// half-close parity with the Python plane: a client that shut down its
// write side still receives every reply it is owed — the conn closes
// only once no crossed frame is pending AND the out buffer drained
// (mu held)
void maybe_close_eof(Frontend* f, long cid) {
  auto it = f->conns.find(cid);
  if (it == f->conns.end()) return;
  Conn& c = it->second;
  if (!c.rd_eof || c.closed) return;
  if (c.pending > 0 || c.out_off < c.out.size()) return;
  close_conn(f, cid);
}

// enqueue with the pump.cc backpressure discipline: a full crossing
// queue pauses the io thread (TCP backpressure), never grows unbounded.
// Returns with mu held; lk must hold mu on entry.
void enqueue(Frontend* f, std::unique_lock<std::mutex>& lk, Frame&& fr) {
  while (f->q.size() >= QUEUE_CAP && !f->stop.load()) {
    lk.unlock();
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
    lk.lock();
  }
  f->q.push_back(std::move(fr));
  f->cv.notify_all();
}

// parse a STATIC_READ_OBJECTS body (payload after the code byte) into
// per-object key/type/bucket spans.  Returns false when the read is not
// natively servable (clocked, deadline-bearing, malformed, non-map) —
// the frame is forwarded and Python owns parity.
bool parse_read(const uint8_t* body, size_t len,
                std::vector<ObjSpan>& objs) {
  objs.clear();
  Rd r{body, body + len};
  size_t pairs;
  if (!mp_map_hdr(r, &pairs)) return false;
  bool saw_objects = false;
  for (size_t i = 0; i < pairs; ++i) {
    const uint8_t* ks; size_t kn;
    if (!mp_str(r, &ks, &kn)) return false;
    if (kn == 7 && memcmp(ks, "objects", 7) == 0) {
      size_t n;
      if (!mp_array_hdr(r, &n)) return false;
      if (n > (1u << 20)) return false;
      objs.reserve(n);
      for (size_t j = 0; j < n; ++j) {
        size_t m;
        if (!mp_array_hdr(r, &m) || m != 3) return false;
        ObjSpan o{};
        o.key_b = r.p;
        if (!mp_skip(r)) return false;
        o.key_n = size_t(r.p - o.key_b);
        o.type_b = r.p;
        if (!mp_skip(r)) return false;
        o.type_n = size_t(r.p - o.type_b);
        o.buck_b = r.p;
        if (!mp_skip(r)) return false;
        o.buck_n = size_t(r.p - o.buck_b);
        objs.push_back(o);
      }
      saw_objects = true;
    } else if (kn == 5 && memcmp(ks, "clock", 5) == 0) {
      // clockless only: a session clock routes through Python (the
      // epoch-comparison discipline lives in _try_cache_read)
      if (!rd_need(r, 1) || *r.p != 0xc0) return false;
      ++r.p;
    } else if (kn == 11 && memcmp(ks, "deadline_ms", 11) == 0) {
      return false;  // deadline semantics stay with Python
    } else {
      if (!mp_skip(r)) return false;  // ignore unknown keys, like Python
    }
  }
  return saw_objects && r.p == r.end;
}

// hand-build the byte-identical Python fast-path reply:
// encode(READ_OBJECTS_RESP, {"values": [...], "commit_clock": [...]})
void build_hit_reply(Frontend* f, Conn& c,
                     const std::vector<const Entry*>& hits) {
  size_t n = hits.size();
  size_t arr_hdr = n < 16 ? 1 : (n < 65536 ? 3 : 5);
  size_t body = 1 + 7 + arr_hdr + 13 + f->clock_frag.size();
  for (const Entry* e : hits) body += e->val.size();
  std::vector<uint8_t>& o = c.out;
  o.reserve(o.size() + 5 + body);
  push_be32(o, uint32_t(body + 1));
  o.push_back(READ_OBJECTS_RESP);
  o.push_back(0x82);  // fixmap(2)
  pack_str(o, "values", 6);
  if (n < 16) {
    o.push_back(uint8_t(0x90 | n));
  } else if (n < 65536) {
    o.push_back(0xdc);
    o.push_back(uint8_t(n >> 8));
    o.push_back(uint8_t(n));
  } else {
    o.push_back(0xdd);
    push_be32(o, uint32_t(n));
  }
  for (const Entry* e : hits)
    o.insert(o.end(), e->val.begin(), e->val.end());
  pack_str(o, "commit_clock", 12);
  o.insert(o.end(), f->clock_frag.begin(), f->clock_frag.end());
}

// typed busy reply in the native dialect (overload.py semantics):
// encode(ERROR_RESP, {"error": "busy", "detail": ..., "retry_after_ms": N})
void build_busy_reply(Conn& c, const std::string& detail, long hint) {
  std::vector<uint8_t> body;
  body.reserve(64 + detail.size());
  body.push_back(0x83);
  pack_str(body, "error", 5);
  pack_str(body, "busy", 4);
  pack_str(body, "detail", 6);
  pack_str(body, detail);
  pack_str(body, "retry_after_ms", 14);
  pack_uint(body, uint64_t(hint));
  std::vector<uint8_t>& o = c.out;
  push_be32(o, uint32_t(body.size() + 1));
  o.push_back(ERROR_RESP);
  o.insert(o.end(), body.begin(), body.end());
}

long retry_hint(Frontend* f) {
  // overload.retry_hint_ms: pressure-scaled, bounded 25..500 ms
  ++f->shed_streak;
  long h = 25 * (1 + f->shed_streak / 4);
  return h < 25 ? 25 : (h > 500 ? 500 : h);
}

// one complete frame from conn `c` (mu held via lk)
void on_frame(Frontend* f, std::unique_lock<std::mutex>& lk, long cid,
              const uint8_t* payload, size_t len) {
  auto it = f->conns.find(cid);
  if (it == f->conns.end()) return;
  Conn* c = &it->second;
  ++f->st_frames;
  uint8_t code = len ? payload[0] : 0;
  bool apb = len && is_apb(code);

  // ---- native whole-batch cache hit (the headline path) -------------
  if (!apb && code == STATIC_READ_OBJECTS && f->fast_serve &&
      f->clockless_ok && f->cur_epoch >= 0 && c->pending == 0 &&
      parse_read(payload + 1, len - 1, f->scratch_objs) &&
      !f->scratch_objs.empty()) {
    std::vector<const Entry*> hits;
    hits.reserve(f->scratch_objs.size());
    std::string k;
    bool all = true;
    for (const ObjSpan& o : f->scratch_objs) {
      k.assign(reinterpret_cast<const char*>(o.key_b), o.key_n);
      k.append(reinterpret_cast<const char*>(o.buck_b), o.buck_n);
      auto e = f->mirror.find(k);
      if (e == f->mirror.end() || e->second.stamp != f->cur_epoch ||
          e->second.type_frag.size() != o.type_n ||
          memcmp(e->second.type_frag.data(), o.type_b, o.type_n) != 0) {
        all = false;
        break;
      }
      hits.push_back(&e->second);
    }
    if (all) {
      ++f->st_hits;
      f->st_hit_objs += long(hits.size());
      build_hit_reply(f, *c, hits);
      flush_out(f, *c);
      return;
    }
  }

  // ---- admission (overload.py AdmissionGate, natively) --------------
  std::string detail;
  if (f->g_inflight >= f->max_in_flight) {
    detail = "server at max_in_flight=" + std::to_string(f->max_in_flight);
  } else {
    long ph = 0;
    auto hi = f->host_inflight.find(c->host);
    if (hi != f->host_inflight.end()) ph = hi->second;
    if (ph >= f->max_per_host)
      detail = "client " + c->host + " at max_in_flight_per_client=" +
               std::to_string(f->max_per_host);
  }
  if (!detail.empty()) {
    ++f->st_shed;
    long hint = retry_hint(f);
    if (apb || c->pending > 0) {
      // apb busy replies are built by the apb codec, and a conn with
      // in-flight Python replies must keep per-conn reply order — both
      // cross as a shed frame the bridge answers in the frame's dialect
      c->pending += 1;
      Frame fr{cid, 2, hint, {}};
      fr.payload.assign(payload, payload + len);
      enqueue(f, lk, std::move(fr));
    } else {
      build_busy_reply(*c, detail, hint);
      flush_out(f, *c);
    }
    return;
  }

  // ---- admitted: cross to Python in the next drain ------------------
  f->shed_streak = 0;
  ++f->g_inflight;
  ++f->host_inflight[c->host];
  c->pending += 1;
  c->admitted += 1;
  ++f->st_fwd;
  Frame fr{cid, 1, 0, {}};
  fr.payload.assign(payload, payload + len);
  enqueue(f, lk, std::move(fr));
}

// drain every complete frame out of a conn's read buffer (mu held)
void drain_in(Frontend* f, std::unique_lock<std::mutex>& lk, long cid) {
  size_t off = 0;
  for (;;) {
    auto it = f->conns.find(cid);
    if (it == f->conns.end() || it->second.closed) return;
    Conn& c = it->second;
    if (c.in.size() - off < 4) break;
    uint32_t n = be32(c.in.data() + off);
    if (n < 1 || n > MAX_FRAME) {
      // codec read_frame_buffered raises ConnectionError here — the
      // Python server drops the conn silently; mirror that
      ++f->st_bad_frame;
      close_conn(f, cid);
      return;
    }
    if (c.in.size() - off < 4 + size_t(n)) break;
    // on_frame may release mu during enqueue backpressure; keep the
    // bytes alive independently of the (re-lookupable) conn buffer
    off += 4;
    std::vector<uint8_t> payload(c.in.begin() + off,
                                 c.in.begin() + off + n);
    off += n;
    on_frame(f, lk, cid, payload.data(), payload.size());
  }
  auto it = f->conns.find(cid);
  if (it == f->conns.end()) return;
  Conn& c = it->second;
  if (off) c.in.erase(c.in.begin(), c.in.begin() + off);
}

void do_accept(Frontend* f, std::unique_lock<std::mutex>& lk) {
  for (;;) {
    sockaddr_in sa{};
    socklen_t sl = sizeof(sa);
    int fd = accept4(f->lfd, reinterpret_cast<sockaddr*>(&sa), &sl,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    char hbuf[INET_ADDRSTRLEN] = "?";
    inet_ntop(AF_INET, &sa.sin_addr, hbuf, sizeof(hbuf));
    long cid = f->next_id++;
    Conn c;
    c.fd = fd;
    c.id = cid;
    c.host = hbuf;
    f->by_fd[fd] = cid;
    f->conns.emplace(cid, std::move(c));
    ++f->n_open;
    ++f->st_accept;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(f->epfd, EPOLL_CTL_ADD, fd, &ev);
    if (f->n_open >= f->max_conns) {
      // ranch-style backpressure: park accepting, let the kernel
      // listen backlog hold the excess (listen() backlog == cap)
      epoll_ctl(f->epfd, EPOLL_CTL_DEL, f->lfd, nullptr);
      f->accept_paused = true;
      return;
    }
  }
}

void io_loop(Frontend* f) {
  epoll_event evs[MAX_EVENTS];
  std::vector<uint8_t> buf(1 << 16);
  while (!f->stop.load()) {
    int n = epoll_wait(f->epfd, evs, MAX_EVENTS, 100);
    if (f->stop.load()) return;
    std::unique_lock<std::mutex> lk(f->mu);
    // output buffered by frontend_send while we slept
    if (!f->out_dirty.empty()) {
      for (long cid : f->out_dirty) {
        auto it = f->conns.find(cid);
        if (it != f->conns.end() && !it->second.closed)
          flush_out(f, it->second);
        maybe_close_eof(f, cid);
      }
      f->out_dirty.clear();
    }
    for (int i = 0; i < n; ++i) {
      int fd = evs[i].data.fd;
      if (fd == f->wakefd) {
        uint64_t junk;
        ssize_t r = read(f->wakefd, &junk, sizeof(junk));
        (void)r;
        continue;
      }
      if (fd == f->lfd) {
        do_accept(f, lk);
        continue;
      }
      auto bi = f->by_fd.find(fd);
      if (bi == f->by_fd.end()) continue;
      long cid = bi->second;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(f, cid);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        auto it = f->conns.find(cid);
        if (it != f->conns.end()) flush_out(f, it->second);
        maybe_close_eof(f, cid);
      }
      if (evs[i].events & EPOLLIN) {
        bool eof = false, err = false;
        for (;;) {
          ssize_t r = recv(fd, buf.data(), buf.size(), MSG_DONTWAIT);
          if (r > 0) {
            auto it = f->conns.find(cid);
            if (it == f->conns.end()) break;
            it->second.in.insert(it->second.in.end(), buf.data(),
                                 buf.data() + r);
            if (size_t(r) < buf.size()) break;
          } else if (r == 0) {
            eof = true;
            break;
          } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
            break;
          } else {
            err = true;
            break;
          }
        }
        drain_in(f, lk, cid);
        if (err) {
          close_conn(f, cid);
        } else if (eof) {
          auto it = f->conns.find(cid);
          if (it != f->conns.end() && !it->second.closed) {
            Conn& c = it->second;
            c.rd_eof = true;
            epoll_event ev{};
            ev.events = c.want_out ? EPOLLOUT : 0;
            ev.data.fd = c.fd;
            epoll_ctl(f->epfd, EPOLL_CTL_MOD, c.fd, &ev);
            maybe_close_eof(f, cid);
          }
        }
      }
    }
  }
}

}  // namespace

#ifndef ANTIDOTE_SRC_SHA
#define ANTIDOTE_SRC_SHA "unknown"
#endif

extern "C" {

const char* frontend_src_sha() { return ANTIDOTE_SRC_SHA; }

void* frontend_create(const char* host, int port, int max_conns,
                      long max_in_flight, long max_per_host,
                      long mirror_cap) {
  Frontend* f = new Frontend();
  f->max_conns = max_conns > 0 ? max_conns : 1024;
  f->max_in_flight = max_in_flight > 0 ? max_in_flight : 256;
  f->max_per_host = max_per_host > 0 ? max_per_host : 64;
  if (mirror_cap > 0) f->mirror_cap = size_t(mirror_cap);
  f->lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (f->lfd < 0) {
    delete f;
    return nullptr;
  }
  int one = 1;
  setsockopt(f->lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1)
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (bind(f->lfd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
      listen(f->lfd, f->max_conns) != 0) {
    ::close(f->lfd);
    delete f;
    return nullptr;
  }
  socklen_t sl = sizeof(sa);
  getsockname(f->lfd, reinterpret_cast<sockaddr*>(&sa), &sl);
  f->port = ntohs(sa.sin_port);
  f->epfd = epoll_create1(EPOLL_CLOEXEC);
  f->wakefd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  if (f->epfd < 0 || f->wakefd < 0) {
    ::close(f->lfd);
    if (f->epfd >= 0) ::close(f->epfd);
    if (f->wakefd >= 0) ::close(f->wakefd);
    delete f;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = f->lfd;
  epoll_ctl(f->epfd, EPOLL_CTL_ADD, f->lfd, &ev);
  ev.data.fd = f->wakefd;
  epoll_ctl(f->epfd, EPOLL_CTL_ADD, f->wakefd, &ev);
  f->thr = std::thread(io_loop, f);
  return f;
}

int frontend_port(void* h) {
  return static_cast<Frontend*>(h)->port;
}

// pack the drained crossing like pump_take_batch: payloads back-to-back
// in `out`, 4 longs per frame in `descs` (conn_id, kind, len, aux).
// Returns n frames, 0 on timeout, -1 when stopped, -2 when the first
// frame alone exceeds `cap` (descs[0..3] then carry its needs).
long frontend_take_batch(void* h, uint8_t* out, long cap, long* descs,
                         long max_n, long timeout_ms) {
  Frontend* f = static_cast<Frontend*>(h);
  std::unique_lock<std::mutex> lk(f->mu);
  if (f->q.empty()) {
    f->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                   [&] { return !f->q.empty() || f->stop.load(); });
  }
  if (f->q.empty()) return f->stop.load() ? -1 : 0;
  long n = 0, used = 0;
  while (n < max_n && !f->q.empty()) {
    Frame& fr = f->q.front();
    long need = long(fr.payload.size());
    if (used + need > cap) {
      if (n == 0) {
        descs[0] = fr.conn_id;
        descs[1] = fr.kind;
        descs[2] = need;
        descs[3] = fr.aux;
        return -2;
      }
      break;
    }
    memcpy(out + used, fr.payload.data(), size_t(need));
    descs[n * 4 + 0] = fr.conn_id;
    descs[n * 4 + 1] = fr.kind;
    descs[n * 4 + 2] = need;
    descs[n * 4 + 3] = fr.aux;
    used += need;
    ++n;
    f->q.pop_front();
  }
  f->st_drains.fetch_add(1);
  return n;
}

// append one fully-framed reply for `conn_id` (len may be 0: account
// only), release `n_admitted` admission slots, keep per-conn order.
void frontend_send(void* h, long conn_id, const uint8_t* buf, long len,
                   long n_admitted) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  auto it = f->conns.find(conn_id);
  if (n_admitted > 0) {
    f->g_inflight -= n_admitted;
    if (f->g_inflight < 0) f->g_inflight = 0;
    if (it != f->conns.end()) {
      auto hi = f->host_inflight.find(it->second.host);
      if (hi != f->host_inflight.end()) {
        hi->second -= n_admitted;
        if (hi->second <= 0) f->host_inflight.erase(hi);
      }
    }
  }
  if (it == f->conns.end()) return;
  Conn& c = it->second;
  c.pending -= 1;
  c.admitted -= n_admitted;
  if (!c.closed && len > 0) {
    bool was_empty = c.out.empty();
    c.out.insert(c.out.end(), buf, buf + len);
    if (was_empty) f->out_dirty.push_back(conn_id);
    wake(f);
  } else if (!c.closed && c.rd_eof && c.pending <= 0) {
    // half-closed conn just got its last (empty) reply: have the io
    // thread run the deferred close
    f->out_dirty.push_back(conn_id);
    wake(f);
  }
  if (c.closed && c.pending <= 0) f->conns.erase(it);
}

void frontend_close_conn(void* h, long conn_id) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  close_conn(f, conn_id);
}

// mirror protocol ------------------------------------------------------
// advance to serving epoch `epoch_id`: entries stamped with the
// PREVIOUS epoch survive (every mutation between the two invalidated
// its keys eagerly under the commit lock), anything older drops.
void frontend_advance(void* h, long epoch_id, const uint8_t* clock_frag,
                      long clock_len, int clockless_ok) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  if (epoch_id != f->cur_epoch) {
    long prev = f->cur_epoch;
    for (auto it = f->mirror.begin(); it != f->mirror.end();) {
      if (it->second.stamp == prev) {
        it->second.stamp = epoch_id;
        ++it;
      } else if (it->second.stamp == epoch_id) {
        ++it;
      } else {
        it = f->mirror.erase(it);
      }
    }
    f->cur_epoch = epoch_id;
  }
  f->clock_frag.assign(reinterpret_cast<const char*>(clock_frag),
                       size_t(clock_len));
  f->clockless_ok = clockless_ok != 0;
}

void frontend_fill(void* h, const uint8_t* key, long key_len,
                   const uint8_t* type_frag, long type_len,
                   const uint8_t* val, long val_len, long epoch_id) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  std::string k(reinterpret_cast<const char*>(key), size_t(key_len));
  if (f->mirror.size() >= f->mirror_cap && !f->mirror.count(k)) {
    f->mirror.erase(f->mirror.begin());  // capacity cap, arbitrary victim
  }
  Entry& e = f->mirror[k];
  e.stamp = epoch_id;
  e.type_frag.assign(reinterpret_cast<const char*>(type_frag),
                     size_t(type_len));
  e.val.assign(reinterpret_cast<const char*>(val), size_t(val_len));
}

void frontend_invalidate(void* h, const uint8_t* key, long key_len) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  f->mirror.erase(
      std::string(reinterpret_cast<const char*>(key), size_t(key_len)));
}

void frontend_mirror_reset(void* h) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  f->mirror.clear();
  f->cur_epoch = -1;
  f->clockless_ok = false;
}

void frontend_set_fast_serve(void* h, int on) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  f->fast_serve = on != 0;
}

void frontend_set_clockless_ok(void* h, int on) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  f->clockless_ok = on != 0;
}

// stats snapshot: [accepted, closed, frames, native_hits, hit_objects,
//                  sheds, forwarded, drains, mirror_size, in_flight,
//                  open_conns, bad_frames]
void frontend_stats(void* h, long* out, int n) {
  Frontend* f = static_cast<Frontend*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  long vals[12] = {f->st_accept, f->st_closed, f->st_frames, f->st_hits,
                   f->st_hit_objs, f->st_shed, f->st_fwd,
                   f->st_drains.load(), long(f->mirror.size()),
                   f->g_inflight, f->n_open, f->st_bad_frame};
  for (int i = 0; i < n && i < 12; ++i) out[i] = vals[i];
}

void frontend_stop(void* h) {
  Frontend* f = static_cast<Frontend*>(h);
  f->stop.store(true);
  wake(f);
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->cv.notify_all();
  }
  if (f->thr.joinable()) f->thr.join();
  std::lock_guard<std::mutex> lk(f->mu);
  for (auto& kv : f->conns) {
    if (kv.second.fd >= 0) {
      ::close(kv.second.fd);
      kv.second.fd = -1;
    }
  }
  f->conns.clear();
  f->by_fd.clear();
  if (f->lfd >= 0) ::close(f->lfd);
  if (f->epfd >= 0) ::close(f->epfd);
  if (f->wakefd >= 0) ::close(f->wakefd);
  f->lfd = f->epfd = f->wakefd = -1;
}

// never deleted: a racing frontend_take_batch may still sit in the cv
// wait — the quarantined struct outlives it (the pump_free discipline)
void frontend_free(void* h) {
  Frontend* f = static_cast<Frontend*>(h);
  if (!f->stop.load()) frontend_stop(h);
}

}  // extern "C"
