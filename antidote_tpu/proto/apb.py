"""antidote_pb wire-compatibility codec (the ``antidotec_pb`` dialect).

The reference speaks length-prefixed protobuf on port 8087: a 4-byte
big-endian frame length, then a 1-byte message code and a proto2 body
(/root/reference/src/antidote_pb_protocol.erl:42-88).  The message set and
code table live in the external ``antidote_pb_codec`` dependency
(/root/reference/rebar.config:12 — ``antidote.proto``, the public
AntidoteDB client protocol); they are reproduced here from that public
definition so existing Antidote clients can connect unmodified.  The
dispatch below mirrors the ``antidote_pb_process:process/1`` clauses
(/root/reference/src/antidote_pb_process.erl:49-135).

The proto2 wire format is hand-rolled (varint + length-delimited fields —
no generated-code dependency at runtime); ``tests/test_apb.py``
cross-checks every message against ``protoc``-generated encoders for the
same ``.proto`` and against hand-computed golden bytes.

One server socket speaks BOTH dialects: the apb request codes the
server dispatches (``APB_REQUEST_CODES`` = {116, 118-123}) are disjoint
from the native msgpack codec's request codes (1-11), so the server
dispatches per-frame on the code byte (proto/server.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

# ---------------------------------------------------------------------------
# proto2 wire primitives
# ---------------------------------------------------------------------------
_WT_VARINT, _WT_LEN = 0, 2


def _enc_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


# ---------------------------------------------------------------------------
# message schemas — antidote.proto (proto2), field numbers per the public
# antidote_pb_codec definition
# ---------------------------------------------------------------------------
#: name -> [(field_no, field_name, label, type)]; type is a scalar kind or
#: another message name
SCHEMAS: Dict[str, list] = {
    "ApbErrorResp": [(1, "errmsg", "required", "bytes"),
                     (2, "errcode", "required", "uint32")],
    "ApbCounterUpdate": [(1, "inc", "optional", "sint64")],
    "ApbGetCounterResp": [(1, "value", "required", "sint32")],
    "ApbSetUpdate": [(1, "optype", "required", "enum"),
                     (2, "adds", "repeated", "bytes"),
                     (3, "rems", "repeated", "bytes")],
    "ApbGetSetResp": [(1, "value", "repeated", "bytes")],
    "ApbRegUpdate": [(1, "value", "required", "bytes")],
    "ApbGetRegResp": [(1, "value", "required", "bytes")],
    "ApbGetMVRegResp": [(1, "values", "repeated", "bytes")],
    "ApbMapKey": [(1, "key", "required", "bytes"),
                  (2, "type", "required", "enum")],
    "ApbMapUpdate": [(1, "updates", "repeated", "ApbMapNestedUpdate"),
                     (2, "removedKeys", "repeated", "ApbMapKey")],
    "ApbMapNestedUpdate": [(1, "key", "required", "ApbMapKey"),
                           (2, "update", "required", "ApbUpdateOperation")],
    "ApbMapEntry": [(1, "key", "required", "ApbMapKey"),
                    (2, "value", "required", "ApbReadObjectResp")],
    "ApbGetMapResp": [(1, "entries", "repeated", "ApbMapEntry")],
    "ApbFlagUpdate": [(1, "value", "required", "bool")],
    "ApbGetFlagResp": [(1, "value", "required", "bool")],
    "ApbCrdtReset": [],
    "ApbBoundObject": [(1, "key", "required", "bytes"),
                       (2, "type", "required", "enum"),
                       (3, "bucket", "required", "bytes")],
    "ApbReadObjects": [(1, "boundobjects", "repeated", "ApbBoundObject"),
                       (2, "transaction_descriptor", "required", "bytes")],
    "ApbUpdateOperation": [(1, "counterop", "optional", "ApbCounterUpdate"),
                           (2, "setop", "optional", "ApbSetUpdate"),
                           (3, "regop", "optional", "ApbRegUpdate"),
                           (4, "resetop", "optional", "ApbCrdtReset"),
                           (5, "flagop", "optional", "ApbFlagUpdate"),
                           (6, "mapop", "optional", "ApbMapUpdate")],
    "ApbUpdateOp": [(1, "boundobject", "required", "ApbBoundObject"),
                    (2, "operation", "required", "ApbUpdateOperation")],
    "ApbUpdateObjects": [(1, "updates", "repeated", "ApbUpdateOp"),
                         (2, "transaction_descriptor", "required", "bytes")],
    "ApbStartTransaction": [(1, "timestamp", "optional", "bytes"),
                            (2, "properties", "optional", "ApbTxnProperties")],
    "ApbTxnProperties": [(1, "read_write", "optional", "uint32"),
                         (2, "red_blue", "optional", "uint32")],
    "ApbAbortTransaction": [(1, "transaction_descriptor", "required", "bytes")],
    "ApbCommitTransaction": [(1, "transaction_descriptor", "required", "bytes")],
    "ApbStaticUpdateObjects": [(1, "transaction", "required", "ApbStartTransaction"),
                               (2, "updates", "repeated", "ApbUpdateOp")],
    "ApbStaticReadObjects": [(1, "transaction", "required", "ApbStartTransaction"),
                             (2, "objects", "repeated", "ApbBoundObject")],
    "ApbCreateDC": [(1, "nodes", "repeated", "bytes")],
    "ApbConnectToDCs": [(1, "descriptors", "repeated", "bytes")],
    "ApbGetConnectionDescriptor": [],
    "ApbGetConnectionDescriptorResp": [(1, "success", "required", "bool"),
                                       (2, "descriptor", "optional", "bytes")],
    "ApbStartTransactionResp": [(1, "success", "required", "bool"),
                                (2, "transaction_descriptor", "optional", "bytes"),
                                (3, "errorcode", "optional", "uint32")],
    "ApbOperationResp": [(1, "success", "required", "bool"),
                         (2, "errorcode", "optional", "uint32")],
    "ApbReadObjectResp": [(1, "counter", "optional", "ApbGetCounterResp"),
                          (2, "set", "optional", "ApbGetSetResp"),
                          (3, "reg", "optional", "ApbGetRegResp"),
                          (4, "mvreg", "optional", "ApbGetMVRegResp"),
                          (6, "map", "optional", "ApbGetMapResp"),
                          (7, "flag", "optional", "ApbGetFlagResp")],
    "ApbReadObjectsResp": [(1, "success", "required", "bool"),
                           (2, "objects", "repeated", "ApbReadObjectResp"),
                           (3, "errorcode", "optional", "uint32")],
    "ApbCommitResp": [(1, "success", "required", "bool"),
                      (2, "commit_time", "optional", "bytes"),
                      (3, "errorcode", "optional", "uint32")],
    "ApbStaticReadObjectsResp": [(1, "objects", "required", "ApbReadObjectsResp"),
                                 (2, "committime", "required", "ApbCommitResp")],
}

#: message code byte (antidote_pb_codec's messageCodes table)
MSG_CODES: Dict[str, int] = {
    "ApbErrorResp": 0,
    "ApbRegUpdate": 107,
    "ApbGetRegResp": 108,
    "ApbCounterUpdate": 109,
    "ApbGetCounterResp": 110,
    "ApbOperationResp": 111,
    "ApbSetUpdate": 112,
    "ApbGetSetResp": 113,
    "ApbTxnProperties": 114,
    "ApbBoundObject": 115,
    "ApbReadObjects": 116,
    "ApbUpdateOp": 117,
    "ApbUpdateObjects": 118,
    "ApbStartTransaction": 119,
    "ApbAbortTransaction": 120,
    "ApbCommitTransaction": 121,
    "ApbStaticUpdateObjects": 122,
    "ApbStaticReadObjects": 123,
    "ApbStartTransactionResp": 124,
    "ApbReadObjectResp": 125,
    "ApbReadObjectsResp": 126,
    "ApbCommitResp": 127,
    "ApbStaticReadObjectsResp": 128,
    # DC management (antidote_pb_process:process create_dc /
    # get_connection_descriptor / connect_to_dcs clauses,
    # /root/reference/src/antidote_pb_process.erl:103-135); the
    # descriptor payload is an opaque blob to clients in the reference
    # too (term_to_binary there, msgpack here)
    "ApbCreateDC": 129,
    "ApbConnectToDCs": 130,
    "ApbGetConnectionDescriptor": 131,
    "ApbGetConnectionDescriptorResp": 132,
}
CODE_TO_NAME = {v: k for k, v in MSG_CODES.items()}

#: request codes the server dispatches to this codec (the antidotec_pb
#: client surface); disjoint from the native msgpack codec's codes 1-11
APB_REQUEST_CODES = frozenset((116, 118, 119, 120, 121, 122, 123,
                               129, 130, 131))

#: antidote.proto CRDT_type enum <-> our type registry names
CRDT_TYPES = {
    3: "counter_pn", 4: "set_aw", 5: "register_lww", 6: "register_mv",
    8: "map_go", 10: "set_rw", 11: "map_rr", 12: "counter_fat",
    13: "flag_ew", 14: "flag_dw", 15: "counter_b",
}
TYPE_IDS = {v: k for k, v in CRDT_TYPES.items()}

_SET_ADD, _SET_REMOVE = 1, 2


def _enc_scalar(kind: str, v) -> bytes:
    if kind == "bytes":
        v = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        return _enc_varint(len(v)) + bytes(v)
    if kind in ("uint32", "enum"):
        return _enc_varint(int(v))
    if kind == "bool":
        return _enc_varint(1 if v else 0)
    if kind == "sint64" or kind == "sint32":
        return _enc_varint(_zigzag(int(v)) & 0xFFFFFFFFFFFFFFFF)
    raise TypeError(kind)


def encode_msg(name: str, d: Dict[str, Any]) -> bytes:
    """One message body (no code byte), fields in schema order."""
    out = bytearray()
    for no, fname, label, kind in SCHEMAS[name]:
        v = d.get(fname)
        if v is None:
            if label == "required":
                raise ValueError(f"{name}.{fname} is required")
            continue
        vals = v if label == "repeated" else [v]
        for x in vals:
            if kind in SCHEMAS:  # nested message
                body = encode_msg(kind, x)
                out += _enc_varint((no << 3) | _WT_LEN)
                out += _enc_varint(len(body)) + body
            elif kind == "bytes":
                out += _enc_varint((no << 3) | _WT_LEN)
                out += _enc_scalar(kind, x)
            else:
                out += _enc_varint((no << 3) | _WT_VARINT)
                out += _enc_scalar(kind, x)
    return bytes(out)


def decode_msg(name: str, data: bytes) -> Dict[str, Any]:
    schema = {no: (fname, label, kind) for no, fname, label, kind in SCHEMAS[name]}
    out: Dict[str, Any] = {
        fname: [] for _, (fname, label, _) in schema.items() if label == "repeated"
    }
    pos = 0
    while pos < len(data):
        tag, pos = _dec_varint(data, pos)
        no, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            raw, pos = _dec_varint(data, pos)
        elif wt == _WT_LEN:
            ln, pos = _dec_varint(data, pos)
            raw = data[pos:pos + ln]
            pos += ln
        elif wt == 5:  # 32-bit, skip (unused by this schema)
            raw, pos = data[pos:pos + 4], pos + 4
        elif wt == 1:  # 64-bit, skip
            raw, pos = data[pos:pos + 8], pos + 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        ent = schema.get(no)
        if ent is None:
            continue  # unknown field: skip (proto2 forward compat)
        fname, label, kind = ent
        if kind in SCHEMAS:
            val = decode_msg(kind, raw)
        elif kind == "bytes":
            val = bytes(raw)
        elif kind in ("uint32", "enum"):
            val = int(raw)
        elif kind == "bool":
            val = bool(raw)
        elif kind in ("sint64", "sint32"):
            val = _unzigzag(int(raw))
        else:
            raise TypeError(kind)
        if label == "repeated":
            out[fname].append(val)
        else:
            out[fname] = val
    return out


def encode_frame_body(name: str, d: Dict[str, Any]) -> bytes:
    """Code byte + message body — what goes inside the 4-byte length frame."""
    return bytes([MSG_CODES[name]]) + encode_msg(name, d)


def decode_frame_body(body: bytes) -> Tuple[str, Dict[str, Any]]:
    name = CODE_TO_NAME[body[0]]
    return name, decode_msg(name, body[1:])


# ---------------------------------------------------------------------------
# semantic bridge: Apb messages <-> node API shapes
# ---------------------------------------------------------------------------
def _enc_clock(vc) -> bytes:
    """Commit clocks ride as opaque bytes (the reference ships
    term_to_binary'd vectorclocks the same way — clients echo them back)."""
    return msgpack.packb([int(x) for x in np.asarray(vc)])


def _dec_clock(data: Optional[bytes]):
    if not data:
        return None
    return msgpack.unpackb(data, raw=False)


def to_bytes(v) -> bytes:
    """Client-visible payloads as apb bytes: values written through this
    codec are stored as bytes and round-trip exactly; values written by
    native clients render best-effort."""
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, str):
        return v.encode()
    return msgpack.packb(v, use_bin_type=True)


def _bound_object(bo: Dict[str, Any]) -> Tuple[bytes, str, bytes]:
    t = CRDT_TYPES.get(bo["type"])
    if t is None:
        raise ValueError(f"unknown CRDT_type enum {bo['type']}")
    return bo["key"], t, bo["bucket"]


def ops_from_update_operation(upop: Dict[str, Any], type_name: str,
                              my_dc: int = 0) -> List[tuple]:
    """ApbUpdateOperation -> our op tuples (one apb op may expand to
    several, e.g. a set update carrying both adds and rems).  ``my_dc``
    is the actor lane for bounded-counter ops (the reference's BCOUNTER
    updates act on the receiving DC's rights the same way)."""
    if upop.get("counterop") is not None:
        inc = int(upop["counterop"].get("inc", 1))
        if type_name == "counter_b":
            # counter_b ops carry (amount, actor-lane)
            if inc >= 0:
                return [("increment", (inc, my_dc))]
            return [("decrement", (-inc, my_dc))]
        return [("increment", inc)]
    if upop.get("setop") is not None:
        so = upop["setop"]
        ops: List[tuple] = []
        if so.get("adds"):
            ops.append(("add_all", list(so["adds"])))
        if so.get("rems"):
            ops.append(("remove_all", list(so["rems"])))
        return ops
    if upop.get("regop") is not None:
        return [("assign", upop["regop"]["value"])]
    if upop.get("flagop") is not None:
        return [("enable" if upop["flagop"]["value"] else "disable", None)]
    if upop.get("resetop") is not None:
        return [("reset", None)]
    if upop.get("mapop") is not None:
        mo = upop["mapop"]
        ops = []
        fields = []
        for nest in mo.get("updates", []):
            fkey = nest["key"]["key"]
            ftype = CRDT_TYPES[nest["key"]["type"]]
            for sub in ops_from_update_operation(nest["update"], ftype,
                                                 my_dc):
                fields.append(((fkey, ftype), sub))
        if fields:
            ops.append(("update", fields))
        removed = [
            (mk["key"], CRDT_TYPES[mk["type"]])
            for mk in mo.get("removedKeys", [])
        ]
        if removed:
            ops.append(("remove_all", removed))
        return ops
    raise ValueError("empty ApbUpdateOperation")


def updates_from_update_ops(ups: List[Dict[str, Any]],
                            my_dc: int = 0) -> List[tuple]:
    out = []
    for up in ups:
        key, t, bucket = _bound_object(up["boundobject"])
        for op in ops_from_update_operation(up["operation"], t, my_dc):
            out.append((key, t, bucket, op))
    return out


def value_to_read_resp(type_name: str, value) -> Dict[str, Any]:
    """Our client value -> ApbReadObjectResp (per-type lane)."""
    if type_name in ("counter_pn", "counter_fat", "counter_b"):
        if type_name == "counter_b":
            # reference renders a bounded counter as its usable value
            value = int(value) if not isinstance(value, dict) else value.get(
                "value", 0
            )
        return {"counter": {"value": int(value)}}
    if type_name in ("set_aw", "set_rw", "set_go"):
        return {"set": {"value": [to_bytes(v) for v in value]}}
    if type_name == "register_lww":
        return {"reg": {"value": to_bytes(value) if value is not None else b""}}
    if type_name == "register_mv":
        return {"mvreg": {"values": [to_bytes(v) for v in value]}}
    if type_name in ("flag_ew", "flag_dw"):
        return {"flag": {"value": bool(value)}}
    if type_name in ("map_rr", "map_go"):
        entries = []
        for (f, ft), v in sorted(value.items(), key=lambda kv: to_bytes(kv[0][0])):
            entries.append({
                "key": {"key": to_bytes(f), "type": TYPE_IDS[ft]},
                "value": value_to_read_resp(ft, v),
            })
        return {"map": {"entries": entries}}
    raise ValueError(f"no apb value lane for {type_name}")


def _error(msg: str) -> bytes:
    return encode_frame_body("ApbErrorResp", {
        "errmsg": to_bytes(msg), "errcode": 0,
    })


def _overload_text(e) -> str:
    """Typed overload error text: proto2 ApbErrorResp has no structured
    retry field, so the kind + retry-after hint ride the errmsg prefix
    ("busy retry_after_ms=NN: ..."), which antidotec_pb clients surface
    verbatim."""
    from antidote_tpu.overload import BusyError, DeadlineExceeded

    if isinstance(e, BusyError):
        return f"busy retry_after_ms={int(e.retry_after_ms)}: {e}"
    if isinstance(e, DeadlineExceeded):
        return f"deadline: {e}"
    return f"read_only: {e}"


def overload_error(kind: str, msg: str, retry_after_ms: int = 0) -> bytes:
    """Pre-dispatch overload reply frame (the server's admission shed)."""
    hint = f" retry_after_ms={int(retry_after_ms)}" if retry_after_ms else ""
    return _error(f"{kind}{hint}: {msg}")


def handle_request(server, code: int, payload: bytes, conn_txns: set,
                   lock=None) -> bytes:
    """Dispatch one apb request; returns the response frame body (code
    byte + proto payload).  Mirrors antidote_pb_process:process/1
    (/root/reference/src/antidote_pb_process.erl:49-135); the error shape
    mirrors antidote_pb_protocol's catch-all
    (/root/reference/src/antidote_pb_protocol.erl:78-88).

    ``lock`` (the server's dispatch lock) is held only around the
    node/_txns mutation — protobuf decode/encode run outside it, like the
    native dialect."""
    import contextlib

    name = CODE_TO_NAME[code]
    try:
        req = decode_msg(name, payload)  # outside the lock
    except Exception as e:
        return _error(f"{type(e).__name__}: {e}")
    if name in ("ApbStaticReadObjects", "ApbStaticUpdateObjects"):
        # static ops ride the server's gate helpers (batched: the gate's
        # dispatcher thread takes the lock; unbatched: they lock inline)
        # — the only static dispatch path, so it cannot drift from a
        # duplicate branch in _dispatch
        resp_name, resp = _dispatch_static(server, name, req)
        return encode_frame_body(resp_name, resp)
    with (lock if lock is not None else contextlib.nullcontext()):
        resp_name, resp = _dispatch(server, name, req, conn_txns)
    return encode_frame_body(resp_name, resp)  # outside the lock


def _dispatch_static(server, name: str, req: Dict[str, Any]):
    node = server.node
    my_dc = getattr(node, "dc_id", 0)
    # proto2 ApbStaticRead/Update carry no deadline field, but the
    # server's configured default still applies: parked apb work that
    # outlives it is aborted at the batch-gate dequeue like any other
    from antidote_tpu.overload import deadline_from_ms

    deadline = deadline_from_ms(None, server.default_deadline_ms)
    try:
        if name == "ApbStaticUpdateObjects":
            clock = _dec_clock(req["transaction"].get("timestamp"))
            vc = server.static_update(
                updates_from_update_ops(req.get("updates", []), my_dc),
                clock, deadline=deadline,
            )
            return "ApbCommitResp", {
                "success": True, "commit_time": _enc_clock(vc),
            }
        clock = _dec_clock(req["transaction"].get("timestamp"))
        objs = [_bound_object(bo) for bo in req.get("objects", [])]
        vals, vc = server.static_read(objs, clock, deadline=deadline)
        return "ApbStaticReadObjectsResp", {
            "objects": {
                "success": True,
                "objects": [
                    value_to_read_resp(t, v)
                    for (_, t, _), v in zip(objs, vals)
                ],
            },
            "committime": {"success": True, "commit_time": _enc_clock(vc)},
        }
    except Exception as e:
        from antidote_tpu.overload import (BusyError, DeadlineExceeded,
                                           ReadOnlyError)

        if isinstance(e, (BusyError, DeadlineExceeded, ReadOnlyError)):
            return "ApbErrorResp", {
                "errmsg": to_bytes(_overload_text(e)), "errcode": 0,
            }
        return "ApbErrorResp", {
            "errmsg": to_bytes(f"{type(e).__name__}: {e}"), "errcode": 0,
        }


def _dispatch(server, name: str, req: Dict[str, Any],
              conn_txns: set) -> Tuple[str, Dict[str, Any]]:
    node = server.node
    my_dc = getattr(node, "dc_id", 0)
    try:
        if name == "ApbStartTransaction":
            txn = node.start_transaction(
                clock=_dec_clock(req.get("timestamp"))
            )
            server._txns[txn.txid] = txn
            conn_txns.add(txn.txid)
            return "ApbStartTransactionResp", {
                "success": True,
                "transaction_descriptor": str(txn.txid).encode(),
            }
        if name == "ApbReadObjects":
            txn = server._txns.get(int(req["transaction_descriptor"]))
            if txn is None:
                raise KeyError("unknown transaction")
            objs = [_bound_object(bo) for bo in req["boundobjects"]]
            vals = node.read_objects(objs, txn)
            return "ApbReadObjectsResp", {
                "success": True,
                "objects": [
                    value_to_read_resp(t, v)
                    for (_, t, _), v in zip(objs, vals)
                ],
            }
        if name == "ApbUpdateObjects":
            txid = int(req["transaction_descriptor"])
            txn = server._txns.get(txid)
            if txn is None:
                raise KeyError("unknown transaction")
            try:
                node.update_objects(
                    updates_from_update_ops(req["updates"], my_dc), txn
                )
            except Exception:
                # a failed update aborts the txn (as the reference's
                # coordinator FSM does) — merely dropping the handle
                # would leak an active txn that pins the cert-GC floor
                server._txns.pop(txid, None)
                conn_txns.discard(txid)
                if txn.active:
                    node.abort_transaction(txn)
                raise
            return "ApbOperationResp", {"success": True}
        if name == "ApbCommitTransaction":
            from antidote_tpu.overload import BusyError

            txid = int(req["transaction_descriptor"])
            txn = server._txns.get(txid)
            if txn is None:
                raise KeyError("unknown transaction")
            # keep the txn registered until the outcome is known: a
            # commit-backlog BusyError leaves it OPEN (the shed happens
            # before the group touches it), so the busy errmsg's retry
            # hint is honest — the SAME descriptor can be resubmitted
            # (mirrors the native dialect's COMMIT_TRANSACTION)
            try:
                vc = node.commit_transaction(txn)
            except BusyError:
                raise
            except BaseException:
                server._txns.pop(txid, None)  # txn is dead
                conn_txns.discard(txid)
                raise
            server._txns.pop(txid, None)
            conn_txns.discard(txid)
            return "ApbCommitResp", {
                "success": True, "commit_time": _enc_clock(vc),
            }
        if name == "ApbAbortTransaction":
            txid = int(req["transaction_descriptor"])
            txn = server._txns.pop(txid, None)
            conn_txns.discard(txid)
            if txn is not None:
                node.abort_transaction(txn)
            return "ApbOperationResp", {"success": True}
        if name == "ApbGetConnectionDescriptor":
            import msgpack

            return "ApbGetConnectionDescriptorResp", {
                "success": True,
                "descriptor": msgpack.packb(server._get_descriptor()),
            }
        if name == "ApbConnectToDCs":
            import msgpack

            server._connect_to_dcs(
                [msgpack.unpackb(b, raw=False)
                 for b in req.get("descriptors", [])]
            )
            return "ApbOperationResp", {"success": True}
        if name == "ApbCreateDC":
            server._create_dc([b.decode() if isinstance(b, bytes) else b
                               for b in req.get("nodes", [])])
            return "ApbOperationResp", {"success": True}
        return "ApbErrorResp", {
            "errmsg": to_bytes(f"unhandled apb request {name}"), "errcode": 0,
        }
    except Exception as e:  # mirror the reference's catch-all error reply
        from antidote_tpu.overload import (BusyError, DeadlineExceeded,
                                           ReadOnlyError)

        if isinstance(e, (BusyError, DeadlineExceeded, ReadOnlyError)):
            return "ApbErrorResp", {
                "errmsg": to_bytes(_overload_text(e)), "errcode": 0,
            }
        return "ApbErrorResp", {
            "errmsg": to_bytes(f"{type(e).__name__}: {e}"), "errcode": 0,
        }
