"""antidote_pb wire-compatibility codec (the ``antidotec_pb`` dialect).

The reference speaks length-prefixed protobuf on port 8087: a 4-byte
big-endian frame length, then a 1-byte message code and a proto2 body
(/root/reference/src/antidote_pb_protocol.erl:42-88).  The message set and
code table live in the external ``antidote_pb_codec`` dependency
(/root/reference/rebar.config:12 — ``antidote.proto``, the public
AntidoteDB client protocol); they are reproduced here from that public
definition so existing Antidote clients can connect unmodified.  The
dispatch below mirrors the ``antidote_pb_process:process/1`` clauses
(/root/reference/src/antidote_pb_process.erl:49-135).

The proto2 wire format is hand-rolled (varint + length-delimited fields —
no generated-code dependency at runtime); ``tests/test_apb.py``
cross-checks every message against ``protoc``-generated encoders for the
same ``.proto`` and against hand-computed golden bytes.

One server socket speaks BOTH dialects: the apb request codes the
server dispatches (``APB_REQUEST_CODES`` = {116, 118-123}) are disjoint
from the native msgpack codec's request codes (1-11), so the server
dispatches per-frame on the code byte (proto/server.py).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

# ---------------------------------------------------------------------------
# proto2 wire primitives
# ---------------------------------------------------------------------------
_WT_VARINT, _WT_LEN = 0, 2


def _enc_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


# ---------------------------------------------------------------------------
# message schemas — antidote.proto (proto2), field numbers per the public
# antidote_pb_codec definition
# ---------------------------------------------------------------------------
#: name -> [(field_no, field_name, label, type)]; type is a scalar kind or
#: another message name
SCHEMAS: Dict[str, list] = {
    "ApbErrorResp": [(1, "errmsg", "required", "bytes"),
                     (2, "errcode", "required", "uint32")],
    "ApbCounterUpdate": [(1, "inc", "optional", "sint64")],
    "ApbGetCounterResp": [(1, "value", "required", "sint32")],
    "ApbSetUpdate": [(1, "optype", "required", "enum"),
                     (2, "adds", "repeated", "bytes"),
                     (3, "rems", "repeated", "bytes")],
    "ApbGetSetResp": [(1, "value", "repeated", "bytes")],
    "ApbRegUpdate": [(1, "value", "required", "bytes")],
    "ApbGetRegResp": [(1, "value", "required", "bytes")],
    "ApbGetMVRegResp": [(1, "values", "repeated", "bytes")],
    "ApbMapKey": [(1, "key", "required", "bytes"),
                  (2, "type", "required", "enum")],
    "ApbMapUpdate": [(1, "updates", "repeated", "ApbMapNestedUpdate"),
                     (2, "removedKeys", "repeated", "ApbMapKey")],
    "ApbMapNestedUpdate": [(1, "key", "required", "ApbMapKey"),
                           (2, "update", "required", "ApbUpdateOperation")],
    "ApbMapEntry": [(1, "key", "required", "ApbMapKey"),
                    (2, "value", "required", "ApbReadObjectResp")],
    "ApbGetMapResp": [(1, "entries", "repeated", "ApbMapEntry")],
    "ApbFlagUpdate": [(1, "value", "required", "bool")],
    "ApbGetFlagResp": [(1, "value", "required", "bool")],
    "ApbCrdtReset": [],
    "ApbBoundObject": [(1, "key", "required", "bytes"),
                       (2, "type", "required", "enum"),
                       (3, "bucket", "required", "bytes")],
    "ApbReadObjects": [(1, "boundobjects", "repeated", "ApbBoundObject"),
                       (2, "transaction_descriptor", "required", "bytes")],
    "ApbUpdateOperation": [(1, "counterop", "optional", "ApbCounterUpdate"),
                           (2, "setop", "optional", "ApbSetUpdate"),
                           (3, "regop", "optional", "ApbRegUpdate"),
                           (4, "resetop", "optional", "ApbCrdtReset"),
                           (5, "flagop", "optional", "ApbFlagUpdate"),
                           (6, "mapop", "optional", "ApbMapUpdate")],
    "ApbUpdateOp": [(1, "boundobject", "required", "ApbBoundObject"),
                    (2, "operation", "required", "ApbUpdateOperation")],
    "ApbUpdateObjects": [(1, "updates", "repeated", "ApbUpdateOp"),
                         (2, "transaction_descriptor", "required", "bytes")],
    "ApbStartTransaction": [(1, "timestamp", "optional", "bytes"),
                            (2, "properties", "optional", "ApbTxnProperties")],
    "ApbTxnProperties": [(1, "read_write", "optional", "uint32"),
                         (2, "red_blue", "optional", "uint32")],
    "ApbAbortTransaction": [(1, "transaction_descriptor", "required", "bytes")],
    "ApbCommitTransaction": [(1, "transaction_descriptor", "required", "bytes")],
    "ApbStaticUpdateObjects": [(1, "transaction", "required", "ApbStartTransaction"),
                               (2, "updates", "repeated", "ApbUpdateOp")],
    "ApbStaticReadObjects": [(1, "transaction", "required", "ApbStartTransaction"),
                             (2, "objects", "repeated", "ApbBoundObject")],
    "ApbCreateDC": [(1, "nodes", "repeated", "bytes")],
    "ApbConnectToDCs": [(1, "descriptors", "repeated", "bytes")],
    "ApbGetConnectionDescriptor": [],
    "ApbGetConnectionDescriptorResp": [(1, "success", "required", "bool"),
                                       (2, "descriptor", "optional", "bytes")],
    "ApbStartTransactionResp": [(1, "success", "required", "bool"),
                                (2, "transaction_descriptor", "optional", "bytes"),
                                (3, "errorcode", "optional", "uint32")],
    "ApbOperationResp": [(1, "success", "required", "bool"),
                         (2, "errorcode", "optional", "uint32")],
    "ApbReadObjectResp": [(1, "counter", "optional", "ApbGetCounterResp"),
                          (2, "set", "optional", "ApbGetSetResp"),
                          (3, "reg", "optional", "ApbGetRegResp"),
                          (4, "mvreg", "optional", "ApbGetMVRegResp"),
                          (6, "map", "optional", "ApbGetMapResp"),
                          (7, "flag", "optional", "ApbGetFlagResp")],
    "ApbReadObjectsResp": [(1, "success", "required", "bool"),
                           (2, "objects", "repeated", "ApbReadObjectResp"),
                           (3, "errorcode", "optional", "uint32")],
    "ApbCommitResp": [(1, "success", "required", "bool"),
                      (2, "commit_time", "optional", "bytes"),
                      (3, "errorcode", "optional", "uint32")],
    "ApbStaticReadObjectsResp": [(1, "objects", "required", "ApbReadObjectsResp"),
                                 (2, "committime", "required", "ApbCommitResp"),
                                 # ring-hint extension (ISSUE 17): msgpack
                                 # {owner, followers, vnodes} attached to
                                 # PROXIED replies; proto2 decoders that
                                 # predate it skip the unknown field
                                 (3, "ring_hint", "optional", "bytes")],
}

#: message code byte (antidote_pb_codec's messageCodes table)
MSG_CODES: Dict[str, int] = {
    "ApbErrorResp": 0,
    "ApbRegUpdate": 107,
    "ApbGetRegResp": 108,
    "ApbCounterUpdate": 109,
    "ApbGetCounterResp": 110,
    "ApbOperationResp": 111,
    "ApbSetUpdate": 112,
    "ApbGetSetResp": 113,
    "ApbTxnProperties": 114,
    "ApbBoundObject": 115,
    "ApbReadObjects": 116,
    "ApbUpdateOp": 117,
    "ApbUpdateObjects": 118,
    "ApbStartTransaction": 119,
    "ApbAbortTransaction": 120,
    "ApbCommitTransaction": 121,
    "ApbStaticUpdateObjects": 122,
    "ApbStaticReadObjects": 123,
    "ApbStartTransactionResp": 124,
    "ApbReadObjectResp": 125,
    "ApbReadObjectsResp": 126,
    "ApbCommitResp": 127,
    "ApbStaticReadObjectsResp": 128,
    # DC management (antidote_pb_process:process create_dc /
    # get_connection_descriptor / connect_to_dcs clauses,
    # /root/reference/src/antidote_pb_process.erl:103-135); the
    # descriptor payload is an opaque blob to clients in the reference
    # too (term_to_binary there, msgpack here)
    "ApbCreateDC": 129,
    "ApbConnectToDCs": 130,
    "ApbGetConnectionDescriptor": 131,
    "ApbGetConnectionDescriptorResp": 132,
}
CODE_TO_NAME = {v: k for k, v in MSG_CODES.items()}

#: request codes the server dispatches to this codec (the antidotec_pb
#: client surface); disjoint from the native msgpack codec's codes 1-11
APB_REQUEST_CODES = frozenset((116, 118, 119, 120, 121, 122, 123,
                               129, 130, 131))

#: antidote.proto CRDT_type enum <-> our type registry names
CRDT_TYPES = {
    3: "counter_pn", 4: "set_aw", 5: "register_lww", 6: "register_mv",
    8: "map_go", 10: "set_rw", 11: "map_rr", 12: "counter_fat",
    13: "flag_ew", 14: "flag_dw", 15: "counter_b",
}
TYPE_IDS = {v: k for k, v in CRDT_TYPES.items()}

_SET_ADD, _SET_REMOVE = 1, 2


def _enc_scalar(kind: str, v) -> bytes:
    if kind == "bytes":
        v = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        return _enc_varint(len(v)) + bytes(v)
    if kind in ("uint32", "enum"):
        return _enc_varint(int(v))
    if kind == "bool":
        return _enc_varint(1 if v else 0)
    if kind == "sint64" or kind == "sint32":
        return _enc_varint(_zigzag(int(v)) & 0xFFFFFFFFFFFFFFFF)
    raise TypeError(kind)


def encode_msg(name: str, d: Dict[str, Any]) -> bytes:
    """One message body (no code byte), fields in schema order."""
    out = bytearray()
    for no, fname, label, kind in SCHEMAS[name]:
        v = d.get(fname)
        if v is None:
            if label == "required":
                raise ValueError(f"{name}.{fname} is required")
            continue
        vals = v if label == "repeated" else [v]
        for x in vals:
            if kind in SCHEMAS:  # nested message
                body = encode_msg(kind, x)
                out += _enc_varint((no << 3) | _WT_LEN)
                out += _enc_varint(len(body)) + body
            elif kind == "bytes":
                out += _enc_varint((no << 3) | _WT_LEN)
                out += _enc_scalar(kind, x)
            else:
                out += _enc_varint((no << 3) | _WT_VARINT)
                out += _enc_scalar(kind, x)
    return bytes(out)


def decode_msg(name: str, data: bytes) -> Dict[str, Any]:
    schema = {no: (fname, label, kind) for no, fname, label, kind in SCHEMAS[name]}
    out: Dict[str, Any] = {
        fname: [] for _, (fname, label, _) in schema.items() if label == "repeated"
    }
    pos = 0
    while pos < len(data):
        tag, pos = _dec_varint(data, pos)
        no, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            raw, pos = _dec_varint(data, pos)
        elif wt == _WT_LEN:
            ln, pos = _dec_varint(data, pos)
            raw = data[pos:pos + ln]
            pos += ln
        elif wt == 5:  # 32-bit, skip (unused by this schema)
            raw, pos = data[pos:pos + 4], pos + 4
        elif wt == 1:  # 64-bit, skip
            raw, pos = data[pos:pos + 8], pos + 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        ent = schema.get(no)
        if ent is None:
            continue  # unknown field: skip (proto2 forward compat)
        fname, label, kind = ent
        if kind in SCHEMAS:
            val = decode_msg(kind, raw)
        elif kind == "bytes":
            val = bytes(raw)
        elif kind in ("uint32", "enum"):
            val = int(raw)
        elif kind == "bool":
            val = bool(raw)
        elif kind in ("sint64", "sint32"):
            val = _unzigzag(int(raw))
        else:
            raise TypeError(kind)
        if label == "repeated":
            out[fname].append(val)
        else:
            out[fname] = val
    return out


def encode_frame_body(name: str, d: Dict[str, Any]) -> bytes:
    """Code byte + message body — what goes inside the 4-byte length frame."""
    return bytes([MSG_CODES[name]]) + encode_msg(name, d)


def decode_frame_body(body: bytes) -> Tuple[str, Dict[str, Any]]:
    name = CODE_TO_NAME[body[0]]
    return name, decode_msg(name, body[1:])


# ---------------------------------------------------------------------------
# semantic bridge: Apb messages <-> node API shapes
# ---------------------------------------------------------------------------
def _enc_clock(vc) -> bytes:
    """Commit clocks ride as opaque bytes (the reference ships
    term_to_binary'd vectorclocks the same way — clients echo them back)."""
    return msgpack.packb([int(x) for x in np.asarray(vc)])


def _dec_clock(data: Optional[bytes]):
    if not data:
        return None
    return msgpack.unpackb(data, raw=False)


def to_bytes(v) -> bytes:
    """Client-visible payloads as apb bytes: values written through this
    codec are stored as bytes and round-trip exactly; values written by
    native clients render best-effort."""
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, str):
        return v.encode()
    return msgpack.packb(v, use_bin_type=True)


def _bound_object(bo: Dict[str, Any]) -> Tuple[bytes, str, bytes]:
    t = CRDT_TYPES.get(bo["type"])
    if t is None:
        raise ValueError(f"unknown CRDT_type enum {bo['type']}")
    return bo["key"], t, bo["bucket"]


def ops_from_update_operation(upop: Dict[str, Any], type_name: str,
                              my_dc: int = 0) -> List[tuple]:
    """ApbUpdateOperation -> our op tuples (one apb op may expand to
    several, e.g. a set update carrying both adds and rems).  ``my_dc``
    is the actor lane for bounded-counter ops (the reference's BCOUNTER
    updates act on the receiving DC's rights the same way)."""
    if upop.get("counterop") is not None:
        inc = int(upop["counterop"].get("inc", 1))
        if type_name == "counter_b":
            # counter_b ops carry (amount, actor-lane)
            if inc >= 0:
                return [("increment", (inc, my_dc))]
            return [("decrement", (-inc, my_dc))]
        return [("increment", inc)]
    if upop.get("setop") is not None:
        so = upop["setop"]
        ops: List[tuple] = []
        if so.get("adds"):
            ops.append(("add_all", list(so["adds"])))
        if so.get("rems"):
            ops.append(("remove_all", list(so["rems"])))
        return ops
    if upop.get("regop") is not None:
        return [("assign", upop["regop"]["value"])]
    if upop.get("flagop") is not None:
        return [("enable" if upop["flagop"]["value"] else "disable", None)]
    if upop.get("resetop") is not None:
        return [("reset", None)]
    if upop.get("mapop") is not None:
        mo = upop["mapop"]
        ops = []
        fields = []
        for nest in mo.get("updates", []):
            fkey = nest["key"]["key"]
            ftype = CRDT_TYPES[nest["key"]["type"]]
            for sub in ops_from_update_operation(nest["update"], ftype,
                                                 my_dc):
                fields.append(((fkey, ftype), sub))
        if fields:
            ops.append(("update", fields))
        removed = [
            (mk["key"], CRDT_TYPES[mk["type"]])
            for mk in mo.get("removedKeys", [])
        ]
        if removed:
            ops.append(("remove_all", removed))
        return ops
    raise ValueError("empty ApbUpdateOperation")


def updates_from_update_ops(ups: List[Dict[str, Any]],
                            my_dc: int = 0) -> List[tuple]:
    out = []
    for up in ups:
        key, t, bucket = _bound_object(up["boundobject"])
        for op in ops_from_update_operation(up["operation"], t, my_dc):
            out.append((key, t, bucket, op))
    return out


def value_to_read_resp(type_name: str, value) -> Dict[str, Any]:
    """Our client value -> ApbReadObjectResp (per-type lane)."""
    if type_name in ("counter_pn", "counter_fat", "counter_b"):
        if type_name == "counter_b":
            # reference renders a bounded counter as its usable value
            value = int(value) if not isinstance(value, dict) else value.get(
                "value", 0
            )
        return {"counter": {"value": int(value)}}
    if type_name in ("set_aw", "set_rw", "set_go"):
        return {"set": {"value": [to_bytes(v) for v in value]}}
    if type_name == "register_lww":
        return {"reg": {"value": to_bytes(value) if value is not None else b""}}
    if type_name == "register_mv":
        return {"mvreg": {"values": [to_bytes(v) for v in value]}}
    if type_name in ("flag_ew", "flag_dw"):
        return {"flag": {"value": bool(value)}}
    if type_name in ("map_rr", "map_go"):
        entries = []
        for (f, ft), v in sorted(value.items(), key=lambda kv: to_bytes(kv[0][0])):
            entries.append({
                "key": {"key": to_bytes(f), "type": TYPE_IDS[ft]},
                "value": value_to_read_resp(ft, v),
            })
        return {"map": {"entries": entries}}
    raise ValueError(f"no apb value lane for {type_name}")


def read_resp_to_value(resp: Dict[str, Any]):
    """Client-side inverse of :func:`value_to_read_resp`: one decoded
    ApbReadObjectResp -> the client-visible value (counter int, set
    bytes list, register bytes, flag bool, map dict) — what an
    apb-dialect session client folds into its loop."""
    if resp.get("counter") is not None:
        return int(resp["counter"]["value"])
    if resp.get("set") is not None:
        return list(resp["set"].get("value", []))
    if resp.get("reg") is not None:
        return resp["reg"]["value"]
    if resp.get("mvreg") is not None:
        return list(resp["mvreg"].get("values", []))
    if resp.get("flag") is not None:
        return bool(resp["flag"]["value"])
    if resp.get("map") is not None:
        out = {}
        for ent in resp["map"].get("entries", []):
            k = ent["key"]
            out[(k["key"], CRDT_TYPES[k["type"]])] = read_resp_to_value(
                ent["value"])
        return out
    return None


def _op_to_operation(type_name: str, op: tuple) -> Dict[str, Any]:
    """One native op tuple -> ApbUpdateOperation (client-side inverse of
    :func:`ops_from_update_operation` for the wire-expressible ops)."""
    kind, arg = op[0], (op[1] if len(op) > 1 else None)
    if type_name in ("map_rr", "map_go"):
        # map ops ride the mapop lane — the generic branches below
        # would mis-encode a field tuple as a set payload
        if kind == "update":
            fields = list(arg) if isinstance(arg, (list, tuple)) \
                and arg and isinstance(arg[0], (list, tuple)) \
                and len(arg[0]) == 2 and isinstance(
                    arg[0][0], (list, tuple)) else [arg]
            return {"mapop": {"updates": [
                {"key": {"key": to_bytes(fk), "type": TYPE_IDS[ft]},
                 "update": _op_to_operation(ft, sub)}
                for (fk, ft), sub in fields
            ]}}
        if kind in ("remove", "remove_all"):
            fields = [arg] if kind == "remove" else list(arg)
            return {"mapop": {"removedKeys": [
                {"key": to_bytes(fk), "type": TYPE_IDS[ft]}
                for fk, ft in fields
            ]}}
        if kind == "reset":
            return {"resetop": {}}
        raise ValueError(f"map op {kind!r} has no apb wire form")
    if kind in ("increment", "decrement"):
        amt = arg if not isinstance(arg, (tuple, list)) else arg[0]
        amt = 1 if amt is None else int(amt)
        return {"counterop": {"inc": amt if kind == "increment"
                              else -amt}}
    if kind in ("add", "add_all", "remove", "remove_all"):
        vals = (list(arg) if kind.endswith("_all")
                else [arg])
        field = "adds" if kind.startswith("add") else "rems"
        return {"setop": {"optype": _SET_ADD if field == "adds"
                          else _SET_REMOVE,
                          field: [to_bytes(v) for v in vals]}}
    if kind == "assign":
        return {"regop": {"value": to_bytes(arg)}}
    if kind in ("enable", "disable"):
        return {"flagop": {"value": kind == "enable"}}
    if kind == "reset":
        return {"resetop": {}}
    raise ValueError(f"op {kind!r} has no apb wire form")


def update_op_from_native(update: tuple) -> Dict[str, Any]:
    """One native update tuple ``(key, type, bucket, op)`` ->
    ApbUpdateOp — what an apb-dialect session client sends for its
    writes."""
    key, t, bucket, op = update
    return {
        "boundobject": {"key": to_bytes(key), "type": TYPE_IDS[t],
                        "bucket": to_bytes(bucket)},
        "operation": _op_to_operation(t, op),
    }


def _error(msg: str) -> bytes:
    return encode_frame_body("ApbErrorResp", {
        "errmsg": to_bytes(msg), "errcode": 0,
    })


def error_text(kind: str, msg: str, retry_after_ms: int = 0,
               redirect=None, fleet=None, tenant=None) -> str:
    """Typed error text: proto2 ApbErrorResp has no structured retry or
    redirect field, so the kind + retry-after hint + owner redirect ride
    the errmsg prefix (``"lagging retry_after_ms=NN
    redirect=HOST:PORT: ..."``), which antidotec_pb clients surface
    verbatim and session-aware ones parse back with
    :func:`parse_error_text` — the apb twin of the native dialect's
    structured error fields (ISSUE 11).  ``fleet`` (a list of follower
    endpoints) is the errmsg-encoded ring hint (ISSUE 17): space-free
    ``fleet=H:P,H:P`` so the existing param grammar carries it.
    ``tenant`` (ISSUE 19) names the refusing tenant lane on
    ``tenant_busy`` replies — registry names are space-free by
    construction, so the same param grammar carries it."""
    out = kind
    if retry_after_ms:
        out += f" retry_after_ms={int(retry_after_ms)}"
    if tenant:
        out += f" tenant={tenant}"
    if redirect:
        out += f" redirect={redirect[0]}:{int(redirect[1])}"
    if fleet:
        out += " fleet=" + ",".join(
            f"{h}:{int(p)}" for h, p in fleet)
    return f"{out}: {msg}"


#: "kind key=val key=val: detail" — values are space-free (the redirect
#: value's own colon is fine: the detail separator is colon+SPACE)
_ERR_RE = re.compile(r"^([a-z_]+)((?: [a-z_]+=\S+)*): (.*)$", re.DOTALL)


def parse_error_text(errmsg) -> Dict[str, Any]:
    """Inverse of :func:`error_text`: decode an ApbErrorResp errmsg into
    ``{kind, retry_after_ms, redirect, detail}``.  Unrecognized shapes
    come back as ``kind="error"`` with the whole text as detail, so a
    plain reference-server error never crashes a session client."""
    text = errmsg.decode("utf-8", "replace") \
        if isinstance(errmsg, (bytes, bytearray)) else str(errmsg)
    m = _ERR_RE.match(text)
    if m is None:
        return {"kind": "error", "retry_after_ms": 0, "redirect": None,
                "detail": text}
    kind, params, detail = m.group(1), m.group(2), m.group(3)
    out: Dict[str, Any] = {"kind": kind, "retry_after_ms": 0,
                           "redirect": None, "fleet": None,
                           "tenant": None, "detail": detail}
    for part in params.split():
        k, _, v = part.partition("=")
        if k == "tenant":
            out["tenant"] = v
            continue
        # a malformed value (a foreign server whose errmsg happens to
        # match the prefix shape) falls back to the default, never a
        # crash — the documented never-breaks-a-session contract
        if k == "retry_after_ms":
            try:
                out["retry_after_ms"] = int(v)
            except ValueError:
                pass
        elif k == "redirect":
            host, _, port = v.rpartition(":")
            try:
                out["redirect"] = [host, int(port)]
            except ValueError:
                pass
        elif k == "fleet":
            eps = []
            for item in v.split(","):
                host, _, port = item.rpartition(":")
                try:
                    eps.append([host, int(port)])
                except ValueError:
                    eps = None
                    break
            if eps:
                out["fleet"] = eps
    return out


def overload_error(kind: str, msg: str, retry_after_ms: int = 0) -> bytes:
    """Pre-dispatch overload reply frame (the server's admission shed)."""
    return _error(error_text(kind, msg, retry_after_ms))


def _fleet_hint(server):
    """Errmsg ring-hint endpoints (ISSUE 17) for a follower's typed
    redirect: the owner first, then the live fleet — space-free
    ``H:P`` pairs for :func:`error_text`'s ``fleet=`` param."""
    plane = getattr(server, "proxy", None) if server is not None else None
    if plane is None:
        return None
    hint = plane.ring_hint()
    if hint is None:
        return None
    # FOLLOWERS only: the owner already rides the structured
    # ``redirect=`` param, and conflating the two would teach a session
    # client to put the owner on its read ring
    return hint.get("followers") or None


def _error_resp(e, server=None) -> Tuple[str, Dict[str, Any]]:
    """Map one exception to the typed ApbErrorResp reply — overload
    sheds, follower session redirects (lagging/not_owner, carrying the
    retry hint + owner redirect in the errmsg), forwarding failures
    (``forward_failed``: the owner may have executed), and the
    reference's catch-all shape for everything else.  ``server`` (when
    given and fronting a follower) lets redirect-class errors carry the
    errmsg-encoded fleet hint."""
    from antidote_tpu.overload import (BusyError, ColdMiss,
                                       DeadlineExceeded, ForwardFailed,
                                       InsufficientRightsError,
                                       NotOwnerError, ReadOnlyError,
                                       ReplicaLagging, TenantBusyError)

    if isinstance(e, TenantBusyError):
        # tenant-scoped refusal (ISSUE 19): checked BEFORE BusyError
        # (its base class) so the tenant_busy kind — distinguishable
        # from global busy — survives the errmsg round trip
        text = error_text("tenant_busy", str(e), e.retry_after_ms,
                          tenant=e.tenant)
    elif isinstance(e, BusyError):
        text = error_text("busy", str(e), e.retry_after_ms)
    elif isinstance(e, InsufficientRightsError):
        # escrow refusal (ISSUE 18): counter_b rights exceeded — the
        # hint tracks the background transfer loop's expected grant
        text = error_text("insufficient_rights", str(e),
                          e.retry_after_ms)
    elif isinstance(e, ColdMiss):
        text = error_text("cold_miss", str(e), e.retry_after_ms)
    elif isinstance(e, DeadlineExceeded):
        text = error_text("deadline", str(e))
    elif isinstance(e, ReadOnlyError):
        text = error_text("read_only", str(e))
    elif isinstance(e, ReplicaLagging):
        text = error_text("lagging", str(e), e.retry_after_ms,
                          e.redirect, fleet=_fleet_hint(server))
    elif isinstance(e, NotOwnerError):
        text = error_text("not_owner", str(e), redirect=e.redirect,
                          fleet=_fleet_hint(server))
    elif isinstance(e, ForwardFailed):
        text = error_text("forward_failed", str(e),
                          fleet=_fleet_hint(server))
    else:
        text = f"{type(e).__name__}: {e}"
    return "ApbErrorResp", {"errmsg": to_bytes(text), "errcode": 0}


#: apb requests a FOLLOWER refuses with a typed not_owner redirect:
#: writes and interactive transactions belong to the owner, and the DC
#: mesh mutations would subscribe the follower to streams the owner
#: never replicated (the native dialect's exact refusal set).  With a
#: proxy plane attached (ISSUE 17) only the DC-mesh mutations still
#: refuse — everything else forwards to the owner write plane.
FOLLOWER_REFUSED = frozenset((
    "ApbStartTransaction", "ApbReadObjects", "ApbUpdateObjects",
    "ApbCommitTransaction", "ApbStaticUpdateObjects",
    "ApbConnectToDCs", "ApbCreateDC",
))

#: apb requests a follower FORWARDS to the owner over the proxy plane
#: (satellite 1, ISSUE 17): the refusal set minus the DC-mesh mutations
#: (which stay refused — forwarding them would silently mutate the
#: owner's mesh), plus abort (finishing a forwarded txn must reach the
#: owner that holds it)
FOLLOWER_FORWARDED = frozenset((
    "ApbStartTransaction", "ApbReadObjects", "ApbUpdateObjects",
    "ApbCommitTransaction", "ApbAbortTransaction",
    "ApbStaticUpdateObjects",
))


def handle_request(server, code: int, payload: bytes, conn_txns: set,
                   lock=None) -> bytes:
    """Dispatch one apb request; returns the response frame body (code
    byte + proto payload).  Mirrors antidote_pb_process:process/1
    (/root/reference/src/antidote_pb_process.erl:49-135); the error shape
    mirrors antidote_pb_protocol's catch-all
    (/root/reference/src/antidote_pb_protocol.erl:78-88).

    ``lock`` (the server's dispatch lock) is held only around the
    node/_txns mutation — protobuf decode/encode run outside it, like the
    native dialect.

    On a follower replica (``server.follower``) this dialect keeps the
    native dialect's session discipline (ISSUE 11): static reads pass
    the follower's token gate (in :func:`_dispatch_static`), and
    writes/txns/DC mutations answer the typed not_owner redirect here —
    errmsg-encoded, since proto2 ApbErrorResp has no structured fields."""
    import contextlib

    name = CODE_TO_NAME[code]
    fol = getattr(server, "follower", None)
    plane = getattr(server, "proxy", None)
    if fol is not None and name in FOLLOWER_REFUSED and (
            plane is None or name not in FOLLOWER_FORWARDED):
        from antidote_tpu.overload import NotOwnerError

        server.metrics.session_redirects.inc(kind="not_owner",
                                             dialect="apb")
        return encode_frame_body(
            *_error_resp(NotOwnerError(fol.owner_client_addr),
                         server=server))
    try:
        req = decode_msg(name, payload)  # outside the lock
    except Exception as e:
        return _error(f"{type(e).__name__}: {e}")
    if (fol is not None and plane is not None
            and name in FOLLOWER_FORWARDED):
        # satellite 1 (ISSUE 17): apb writes/txns at a follower ride the
        # server-side forwarding plane instead of bouncing a typed
        # not_owner — the typed errors come back only when forwarding is
        # exhausted (errmsg-encoded by _error_resp, with the fleet hint)
        return encode_frame_body(
            *_forward_apb(server, plane, name, req, conn_txns))
    if name in ("ApbStaticReadObjects", "ApbStaticUpdateObjects"):
        # static ops ride the server's gate helpers (batched: the gate's
        # dispatcher thread takes the lock; unbatched: they lock inline)
        # — the only static dispatch path, so it cannot drift from a
        # duplicate branch in _dispatch
        resp_name, resp = _dispatch_static(server, name, req)
        return encode_frame_body(resp_name, resp)
    with (lock if lock is not None else contextlib.nullcontext()):
        resp_name, resp = _dispatch(server, name, req, conn_txns)
    return encode_frame_body(resp_name, resp)  # outside the lock


def _forward_apb(server, plane, name: str, req: Dict[str, Any],
                 conn_txns: set) -> Tuple[str, Dict[str, Any]]:
    """Forward one apb write/txn request from a follower to the owner
    write plane (satellite 1, ISSUE 17).  The request is decoded once
    here, relayed over the plane's native channels, and the owner's
    reply re-encoded apb — so both dialects share one failover loop,
    one at-most-once discipline, and one ``proxy.forward`` fault site."""
    from antidote_tpu.overload import BusyError, deadline_from_ms
    from antidote_tpu.proto.codec import MessageCode, decode_value

    node = server.node
    my_dc = getattr(node, "dc_id", 0)
    deadline = deadline_from_ms(None, server.default_deadline_ms)
    try:
        if name == "ApbStaticUpdateObjects":
            clock = _dec_clock(req["transaction"].get("timestamp"))
            vc = plane.forward_update(
                updates_from_update_ops(req.get("updates", []), my_dc),
                clock, deadline)
            return "ApbCommitResp", {
                "success": True, "commit_time": _enc_clock(vc),
            }
        if name == "ApbStartTransaction":
            resp = plane.txn_call(MessageCode.START_TRANSACTION, {
                "clock": _dec_clock(req.get("timestamp")),
            })
            txid = resp["txid"]
            plane.forwarded_txns.add(txid)
            conn_txns.add(txid)
            return "ApbStartTransactionResp", {
                "success": True,
                "transaction_descriptor": str(txid).encode(),
            }
        txid = int(req["transaction_descriptor"])
        if name == "ApbReadObjects":
            objs = [_bound_object(bo) for bo in req["boundobjects"]]
            resp = plane.txn_call(MessageCode.READ_OBJECTS, {
                "txid": txid, "objects": [list(o) for o in objs],
            })
            vals = [decode_value(v) for v in resp["values"]]
            return "ApbReadObjectsResp", {
                "success": True,
                "objects": [
                    value_to_read_resp(t, v)
                    for (_, t, _), v in zip(objs, vals)
                ],
            }
        if name == "ApbUpdateObjects":
            ups = updates_from_update_ops(req["updates"], my_dc)
            try:
                plane.txn_call(MessageCode.UPDATE_OBJECTS, {
                    "txid": txid, "updates": [list(u) for u in ups],
                })
            except Exception:
                # the owner aborted + unregistered the txn (its update
                # failure discipline) — drop the forwarded bookkeeping
                plane.forwarded_txns.discard(txid)
                conn_txns.discard(txid)
                raise
            return "ApbOperationResp", {"success": True}
        if name == "ApbCommitTransaction":
            try:
                resp = plane.txn_call(MessageCode.COMMIT_TRANSACTION,
                                      {"txid": txid})
            except BusyError:
                raise  # txn stays OPEN at the owner — retryable
            except Exception:
                plane.forwarded_txns.discard(txid)
                conn_txns.discard(txid)
                raise
            plane.forwarded_txns.discard(txid)
            conn_txns.discard(txid)
            return "ApbCommitResp", {
                "success": True,
                "commit_time": _enc_clock(resp["commit_clock"]),
            }
        # ApbAbortTransaction
        plane.txn_call(MessageCode.ABORT_TRANSACTION, {"txid": txid})
        plane.forwarded_txns.discard(txid)
        conn_txns.discard(txid)
        return "ApbOperationResp", {"success": True}
    except Exception as e:
        return _error_resp(e, server=server)


def _dispatch_static(server, name: str, req: Dict[str, Any]):
    node = server.node
    my_dc = getattr(node, "dc_id", 0)
    # proto2 ApbStaticRead/Update carry no deadline field, but the
    # server's configured default still applies: parked apb work that
    # outlives it is aborted at the batch-gate dequeue like any other
    from antidote_tpu.overload import deadline_from_ms

    deadline = deadline_from_ms(None, server.default_deadline_ms)
    try:
        if name == "ApbStaticUpdateObjects":
            clock = _dec_clock(req["transaction"].get("timestamp"))
            vc = server.static_update(
                updates_from_update_ops(req.get("updates", []), my_dc),
                clock, deadline=deadline,
            )
            return "ApbCommitResp", {
                "success": True, "commit_time": _enc_clock(vc),
            }
        clock = _dec_clock(req["transaction"].get("timestamp"))
        objs = [_bound_object(bo) for bo in req.get("objects", [])]
        fol = getattr(server, "follower", None)
        via_proxy = False
        if fol is not None:
            # the session token gate + serving-fabric routing (ISSUE
            # 17): in-arc keys serve locally behind the applied-clock
            # gate, out-of-arc keys proxy one hop to the arc owner —
            # byte-for-byte the native dialect's discipline (typed
            # lagging only as the last resort, errmsg-encoded)
            (vals, vc), via_proxy = server._follower_read(
                objs, clock, deadline, dialect="apb")
        else:
            vals, vc = server.static_read(objs, clock, deadline=deadline)
        resp = {
            "objects": {
                "success": True,
                "objects": [
                    value_to_read_resp(t, v)
                    for (_, t, _), v in zip(objs, vals)
                ],
            },
            "committime": {"success": True, "commit_time": _enc_clock(vc)},
        }
        if via_proxy:
            # teach capable clients the ring so they converge back to
            # zero-hop (proto2-safe: unknown optional field, skipped by
            # decoders that predate it)
            plane = getattr(server, "proxy", None)
            hint = plane.ring_hint() if plane is not None else None
            if hint is not None:
                resp["ring_hint"] = msgpack.packb(hint)
        return "ApbStaticReadObjectsResp", resp
    except Exception as e:
        return _error_resp(e, server=server)


def _dispatch(server, name: str, req: Dict[str, Any],
              conn_txns: set) -> Tuple[str, Dict[str, Any]]:
    node = server.node
    my_dc = getattr(node, "dc_id", 0)
    try:
        if name == "ApbStartTransaction":
            txn = node.start_transaction(
                clock=_dec_clock(req.get("timestamp"))
            )
            server._txns[txn.txid] = txn
            conn_txns.add(txn.txid)
            return "ApbStartTransactionResp", {
                "success": True,
                "transaction_descriptor": str(txn.txid).encode(),
            }
        if name == "ApbReadObjects":
            txn = server._txns.get(int(req["transaction_descriptor"]))
            if txn is None:
                raise KeyError("unknown transaction")
            objs = [_bound_object(bo) for bo in req["boundobjects"]]
            vals = node.read_objects(objs, txn)
            return "ApbReadObjectsResp", {
                "success": True,
                "objects": [
                    value_to_read_resp(t, v)
                    for (_, t, _), v in zip(objs, vals)
                ],
            }
        if name == "ApbUpdateObjects":
            txid = int(req["transaction_descriptor"])
            txn = server._txns.get(txid)
            if txn is None:
                raise KeyError("unknown transaction")
            try:
                node.update_objects(
                    updates_from_update_ops(req["updates"], my_dc), txn
                )
            except Exception:
                # a failed update aborts the txn (as the reference's
                # coordinator FSM does) — merely dropping the handle
                # would leak an active txn that pins the cert-GC floor
                server._txns.pop(txid, None)
                conn_txns.discard(txid)
                if txn.active:
                    node.abort_transaction(txn)
                raise
            return "ApbOperationResp", {"success": True}
        if name == "ApbCommitTransaction":
            from antidote_tpu.overload import BusyError

            txid = int(req["transaction_descriptor"])
            txn = server._txns.get(txid)
            if txn is None:
                raise KeyError("unknown transaction")
            # keep the txn registered until the outcome is known: a
            # commit-backlog BusyError leaves it OPEN (the shed happens
            # before the group touches it), so the busy errmsg's retry
            # hint is honest — the SAME descriptor can be resubmitted
            # (mirrors the native dialect's COMMIT_TRANSACTION)
            try:
                vc = node.commit_transaction(txn)
            except BusyError:
                raise
            except BaseException:
                server._txns.pop(txid, None)  # txn is dead
                conn_txns.discard(txid)
                raise
            server._txns.pop(txid, None)
            conn_txns.discard(txid)
            return "ApbCommitResp", {
                "success": True, "commit_time": _enc_clock(vc),
            }
        if name == "ApbAbortTransaction":
            txid = int(req["transaction_descriptor"])
            txn = server._txns.pop(txid, None)
            conn_txns.discard(txid)
            if txn is not None:
                node.abort_transaction(txn)
            return "ApbOperationResp", {"success": True}
        if name == "ApbGetConnectionDescriptor":
            import msgpack

            return "ApbGetConnectionDescriptorResp", {
                "success": True,
                "descriptor": msgpack.packb(server._get_descriptor()),
            }
        if name == "ApbConnectToDCs":
            import msgpack

            server._connect_to_dcs(
                [msgpack.unpackb(b, raw=False)
                 for b in req.get("descriptors", [])]
            )
            return "ApbOperationResp", {"success": True}
        if name == "ApbCreateDC":
            server._create_dc([b.decode() if isinstance(b, bytes) else b
                               for b in req.get("nodes", [])])
            return "ApbOperationResp", {"success": True}
        return "ApbErrorResp", {
            "errmsg": to_bytes(f"unhandled apb request {name}"), "errcode": 0,
        }
    except Exception as e:  # mirror the reference's catch-all error reply
        return _error_resp(e)
