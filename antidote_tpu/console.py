"""Operator console — the release entrypoint and admin CLI.

The analogue of the reference's release script + ``antidote_console``
(/root/reference/src/antidote_console.erl:34-50) and its riak-admin
commands: ``serve`` boots a node the way the OTP release does (WAL,
recovery, wire protocol, metrics endpoint, readiness gate), and the other
commands operate a running node over the client protocol or inspect a WAL
directory offline.

    python -m antidote_tpu.console serve --log-dir /data/dc0 --port 8087
    python -m antidote_tpu.console status --port 8087
    python -m antidote_tpu.console ready --port 8087
    python -m antidote_tpu.console read  --port 8087 KEY TYPE BUCKET
    python -m antidote_tpu.console update --port 8087 KEY TYPE BUCKET OP ARG
    python -m antidote_tpu.console inspect --log-dir /data/dc0
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _parse_arg(raw: str):
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return raw


def _parse_endpoints(csv: str):
    """``host:port[,host:port...]`` -> [(host, port)] (the --follower-of
    / --follower-peers fleet lists)."""
    out = []
    for part in csv.split(","):
        part = part.strip()
        if not part:
            continue
        h, p = part.rsplit(":", 1)
        out.append((h, int(p)))
    return out


def resolve_serve_shape(log_dir, shards, max_dcs):
    """Deployment shape for ``serve``: an explicit flag wins; otherwise an
    existing log dir's recorded {n_shards, max_dcs}; otherwise the
    defaults (16, 8).  An explicit flag CONFLICTING with the recorded
    shape is passed through — LogManager fails loudly on it rather than
    silently stranding committed shards."""
    import os

    if log_dir is not None and (shards is None or max_dcs is None):
        from antidote_tpu.log import load_dir_meta

        meta = load_dir_meta(log_dir) if os.path.isdir(log_dir) else None
        if meta is not None:
            if shards is None:
                shards = meta["n_shards"]
            if max_dcs is None:
                max_dcs = meta["max_dcs"]
    return shards or 16, max_dcs or 8


def cmd_serve(args) -> int:
    import os

    from antidote_tpu.config import (apply_jax_platform_env,
                                 enable_compilation_cache)

    apply_jax_platform_env()
    enable_compilation_cache()

    from antidote_tpu import faults as _faults
    from antidote_tpu.api import AntidoteNode
    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.proto.server import ProtocolServer

    # subprocess chaos hook: the chaos suite SIGKILLs serve children and
    # cannot install a plan in-process, so one may ride in the env
    _faults.install_from_env()

    owner_addr = None
    owner_addrs = []
    if args.follower_of:
        # follower replica (ISSUE 9/11): adopt the OWNER's deployment
        # shape and dc lane — a follower is a replica of that exact
        # store.  A CLUSTERED owner is given as a comma-separated list
        # of its members' client endpoints; the first one is the write
        # endpoint named in typed redirects
        if args.log_dir is None:
            log("--follower-of requires --log-dir (followers install "
                "checkpoint images into a durable WAL)")
            return 2
        owner_addrs = _parse_endpoints(args.follower_of)
        if not owner_addrs:
            log("--follower-of needs at least one HOST:PORT endpoint")
            return 2
        owner_addr = owner_addrs[0]
        from antidote_tpu.proto.client import AntidoteClient

        try:
            oc = AntidoteClient(*owner_addr)
            ost = oc.node_status()
            oc.close()
        except Exception as e:
            log(f"cannot reach the owner at {args.follower_of}: {e!r}")
            return 2
        if args.shards is None:
            args.shards = int(ost["n_shards"])
        elif args.shards != int(ost["n_shards"]):
            log(f"--shards {args.shards} conflicts with the owner's "
                f"n_shards={ost['n_shards']}: a follower replicates "
                "that exact store (drop the flag to adopt the shape)")
            return 2
        if args.max_dcs is None:
            args.max_dcs = int(ost["max_dcs"])
        elif args.max_dcs != int(ost["max_dcs"]):
            log(f"--max-dcs {args.max_dcs} conflicts with the owner's "
                f"max_dcs={ost['max_dcs']}")
            return 2
        args.dc_id = int(ost["dc_id"])

    shards, max_dcs = resolve_serve_shape(args.log_dir, args.shards,
                                          args.max_dcs)
    cfg = AntidoteConfig(n_shards=shards, max_dcs=max_dcs,
                         keys_per_table=args.keys_per_table,
                         wal_segments=args.wal_segments,
                         sync_log=args.sync_log,
                         use_pallas=args.pallas,
                         fold_chunk=args.fold_chunk)
    from antidote_tpu.log.checkpoint import has_checkpoints

    has_wal_data = args.log_dir is not None and os.path.isdir(args.log_dir) and (
        any(
            f.endswith(".wal")
            and os.path.getsize(os.path.join(args.log_dir, f)) > 0
            for f in os.listdir(args.log_dir)
        )
        # a published checkpoint is committed data even when every WAL
        # file below its floor was reclaimed
        or has_checkpoints(args.log_dir)
    )
    mesh_plane = None
    if getattr(args, "mesh_devices", 0):
        # mesh serving plane (ISSUE 10): shard the serving-epoch store
        # over a device mesh.  Built BEFORE the node so recovery-created
        # tables are placed at creation; attached after so the stable
        # pmin collective and per-shard publishes route through it.
        from antidote_tpu.parallel import MeshServingPlane

        try:
            mesh_plane = MeshServingPlane(cfg, args.mesh_devices)
        except ValueError as e:
            log(f"--mesh-devices {args.mesh_devices}: {e}")
            return 2
    if args.resident_rows > 0 and args.log_dir is None:
        log("--resident-rows requires --log-dir (cold rows live in "
            "checkpoint sidecars)")
        return 2
    recover = args.recover or has_wal_data
    node = AntidoteNode(cfg, dc_id=args.dc_id, log_dir=args.log_dir,
                        recover=recover,
                        sharding=mesh_plane.sharding
                        if mesh_plane is not None else None,
                        resident_rows=args.resident_rows,
                        cold_fault_rate_cap=args.cold_fault_rate_cap)
    if mesh_plane is not None:
        mesh_plane.metrics = node.metrics
        mesh_plane.attach(node.store)
    if args.log_dir is not None and args.checkpoint_interval_s > 0:
        node.start_checkpointer(interval_s=args.checkpoint_interval_s,
                                retain=args.checkpoint_retain,
                                rebase_every=args.checkpoint_rebase_every,
                                scrub_every_s=args.checkpoint_scrub_s)
    probes = node.check_ready()
    if not all(probes.values()):
        log(f"NOT READY: {probes}")
        return 1
    # the OTP supervision tree (antidote_sup one_for_one, 5-in-10s,
    # /root/reference/src/antidote_sup.erl:137): listener + metrics run
    # as supervised children; a flapping child takes the node down
    from antidote_tpu.supervise import Supervisor

    interdc = None
    fabric = None
    follower = None
    if args.interdc or args.follower_of:
        # geo-replication / follower plane: a TCP fabric + replica so
        # protocol clients can bootstrap a DC mesh, and followers can
        # subscribe + ship images (GetConnectionDescriptor /
        # ConnectToDCs on either dialect)
        from antidote_tpu.interdc import DCReplica, FollowerReplica
        from antidote_tpu.interdc.tcp import TcpFabric

        public = args.public_host
        if public is None and args.host not in ("0.0.0.0", "::"):
            public = args.host
        fabric = TcpFabric(host=args.host, port=args.interdc_port,
                           public_host=public)
        if public is None:
            log("WARNING: binding inter-DC on a wildcard address with no "
                "--public-host: connection descriptors will advertise the "
                "bind address, which remote DCs cannot reach")
        if args.follower_of:
            follower = FollowerReplica(
                node, fabric,
                name=(args.replica_name
                      or f"follower-{args.dc_id}-{os.getpid()}"),
                owner_client_addr=owner_addr,
                park_s=max(0.0, args.follower_park_ms) / 1e3,
                digest_every_s=args.divergence_check_s,
            )
        else:
            interdc = DCReplica(node, fabric, name=f"dc{args.dc_id}")
            if recover:
                interdc.restore_from_log()
    sup = Supervisor(on_giveup=lambda name: os._exit(70))
    if fabric is not None:
        # the replication drain loop runs as a SUPERVISED child: a pump
        # crash (bad frame, handler bug) restarts the loop instead of
        # silently freezing geo-replication while the node keeps serving
        # (the r5 advisor's "threads die silently" failure mode)
        from antidote_tpu.supervise import ThreadLoop

        sup.add(
            "interdc-pump",
            start=lambda: ThreadLoop(
                lambda: fabric.pump(timeout=0.2), interval_s=0.01,
                name="interdc-pump").start(),
            alive=lambda lp: lp.is_alive(),
            stop=lambda lp: lp.stop(),
        )
    if interdc is not None:
        # the escrow rights-transfer loop (ISSUE 18): supervised like
        # the pump — a crashed loop restarts instead of silently
        # freezing bounded-counter grants while decrements queue up
        sup.add(
            "escrow-pump",
            start=lambda: interdc.start_escrow_loop(),
            alive=lambda lp: lp.is_alive(),
            stop=lambda lp: lp.stop(),
        )
    server_box = {}

    from antidote_tpu.tenancy import TenantRegistry

    tenants = TenantRegistry.from_flags(getattr(args, "tenant", None))

    def start_proto():
        port = server_box["srv"].port if "srv" in server_box else args.port
        server_box["srv"] = ProtocolServer(
            node, host=args.host, port=port, interdc=interdc,
            tenants=tenants,
            max_connections=args.max_connections,
            max_in_flight=args.max_in_flight,
            max_in_flight_per_client=args.max_in_flight_per_client,
            default_deadline_ms=args.default_deadline_ms,
            epoch_tick_ms=args.epoch_tick_ms,
            snapshot_cache_size=args.snapshot_cache_size,
            group_commit_window_us=args.group_commit_window_us,
            follower=follower,
            native_frontend=args.native_frontend,
            server_proxy=not args.no_server_proxy,
        )
        return server_box["srv"]

    sup.add("proto", start_proto, alive=lambda s: s.is_alive(),
            stop=lambda s: s.close())
    if args.metrics_port is not None:
        def stop_metrics(m):
            # clear the cached handle FIRST: a close() failure must not
            # leave serve_metrics returning the dead server forever (the
            # flap would reach restart intensity and kill the node)
            node._metrics_server = None
            m.close()

        sup.add("metrics",
                lambda: node.serve_metrics(args.metrics_port),
                alive=lambda m: m._thread.is_alive(),
                stop=stop_metrics)
    sup.start()
    server = server_box["srv"]
    ready: dict = {"host": server.host, "port": server.port, "ready": True}
    if tenants.multi:
        ready["tenants"] = list(tenants.names)
    if follower is not None:
        # attach AFTER the fabric pump + server are supervised: the
        # bootstrap ships the fleet's images, catches the tails up, then
        # subscribes — only then is the ready line printed, so drivers
        # can gate on a SERVING follower.  Every owner-DC member's
        # descriptor is fetched (clustered owners), plus any
        # --follower-peers (geo owners: the peer DCs' origin chains
        # replicate live through the follower's own subscriptions)
        from antidote_tpu.proto.client import AntidoteClient

        peer_addrs = (_parse_endpoints(args.follower_peers)
                      if args.follower_peers else [])
        descs = []
        for addr in owner_addrs + peer_addrs:
            oc = AntidoteClient(*addr)
            descs.append(oc.get_connection_descriptor())
            oc.close()
        follower.client_addr = (args.public_host or server.host,
                                server.port)
        mode = follower.attach(descs)
        ready.update({"role": "follower", "bootstrap": mode,
                      "name": follower.name,
                      "fleet": {"owner_members": len(owner_addrs),
                                "peer_dcs": len(peer_addrs)}})
        log(f"follower {follower.name} of {args.follower_of} serving "
            f"(bootstrap mode={mode}, owner members={len(owner_addrs)})")
    if mesh_plane is not None:
        ready["mesh_devices"] = mesh_plane.n_devices
    if interdc is not None:
        # escrow plane health at boot (ISSUE 18): drivers gating on the
        # ready line see the rights-transfer loop armed + a clean queue
        ready["escrow"] = dict(node.txm.bcounters.status(), loop=True)
    log(f"antidote_tpu dc{args.dc_id} serving on "
        f"{server.host}:{server.port} (recovered={recover}, "
        f"keys={len(node.store.directory)}"
        + (f", mesh={mesh_plane.n_devices}dev"
           if mesh_plane is not None else "") + ")")
    print(json.dumps(ready), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log("shutting down")
        if node.checkpointer is not None:
            node.checkpointer.stop()
        sup.shutdown()
    return 0


def _client(args):
    from antidote_tpu.proto.client import AntidoteClient

    return AntidoteClient(args.host, args.port)


def cmd_status(args) -> int:
    c = _client(args)
    print(json.dumps(c.node_status(), indent=2))
    c.close()
    return 0


def cmd_ready(args) -> int:
    c = _client(args)
    ready = c.node_status(include_ready=True)["ready"]
    print(json.dumps(ready))
    c.close()
    return 0 if all(ready.values()) else 1


def cmd_read(args) -> int:
    c = _client(args)
    vals, vc = c.read_objects([(args.key, args.type, args.bucket)])
    print(json.dumps({"value": vals[0], "clock": list(vc)}, default=str))
    c.close()
    return 0


def cmd_update(args) -> int:
    c = _client(args)
    vc = c.update_objects(
        [(args.key, args.type, args.bucket, (args.op, _parse_arg(args.arg)))]
    )
    print(json.dumps({"commit_clock": list(vc)}))
    c.close()
    return 0


def cmd_inspect(args) -> int:
    """Offline WAL inspection (log_recovery debugging aid).  Segment
    files (``shard_P.sN.wal``) merge into their shard's summary in
    replay order, exactly as recovery would read them."""
    import glob
    import os
    import re

    from antidote_tpu.log import shard_segment_paths
    from antidote_tpu.log.wal import replay_segments

    shards = sorted({
        int(m.group(1))
        for p in glob.glob(os.path.join(args.log_dir, "shard_*.wal"))
        if (m := re.match(r"shard_(\d+)\.(?:s\d+\.)?(?:g\d+\.)?wal$",
                          os.path.basename(p)))
    })
    out = {}
    for shard in shards:
        paths = [p for p in shard_segment_paths(args.log_dir, shard)
                 if os.path.exists(p)]
        recs = 0
        chains: dict = {}
        types: dict = {}
        for rec in replay_segments(paths):
            recs += 1
            o = int(rec["o"])
            chains[o] = max(chains.get(o, 0), int(rec["id"]))
            types[rec["t"]] = types.get(rec["t"], 0) + 1
        out[f"shard_{shard}"] = {
            "records": recs, "opid_chains": chains,
            "records_by_type": types,
            "segments": len(paths),
            "bytes": sum(os.path.getsize(p) for p in paths),
        }
    print(json.dumps(out, indent=2))
    return 0


def cmd_checkpoint_now(args) -> int:
    """Run one synchronous checkpoint cycle on a serving node and print
    the published manifest (stamp, image bytes, WAL bytes reclaimed)."""
    c = _client(args)
    print(json.dumps(c.checkpoint_now(), indent=2))
    c.close()
    return 0


def cmd_inspect_checkpoint(args) -> int:
    """Offline checkpoint inspection: every published image's manifest
    (newest last), plus the decoded summary of the newest one — stamp
    VC, per-shard floors, replication chain floors, tables, extras
    (e.g. cluster membership at the stamp)."""
    from antidote_tpu.log import checkpoint as _ckpt

    root = _ckpt.checkpoint_root(args.log_dir)
    cks = _ckpt.list_checkpoints(root)
    out = {"root": root,
           "published": [m for _id, p in cks
                         if (m := _ckpt.load_manifest(p)) is not None]}
    latest = _ckpt.load_latest(args.log_dir)
    if latest is not None:
        image, manifest = latest
        out["latest"] = {
            "id": int(image["id"]),
            "verified": True,
            "keys": len(image["directory"]),
            "tables": {
                t: int(sum(int(x) for x in tb["used_rows"]))
                for t, tb in image["tables"].items()
            },
            "stamp_vc_max": manifest.get("stamp_vc_max"),
            "commit_counter": int(image["commit_counter"]),
            "floor_seqs": [int(x) for x in image["floor_seqs"]],
            "chain_floor": [[int(x) for x in row]
                            for row in image["chain_floor"]],
            "blobs": len(image.get("blobs", [])),
            "shard_resets": image.get("shard_resets", {}),
            "extras": sorted((image.get("extras") or {}).keys()),
        }
        membership = (image.get("extras") or {}).get("membership")
        if membership:
            out["latest"]["membership"] = membership
    print(json.dumps(out, indent=2))
    return 0


def cmd_replica_status(args) -> int:
    """Replica-plane view: against an owner, every known follower with
    its typed state (ok | lagging | down | bootstrapping | healing) and
    applied-VC lag — plus the consistent-hash ring a SessionClient
    would build over the serving fleet (size + per-endpoint arc
    shares); against a follower, its own state/bootstrap/divergence
    view.  Exit 1 when any follower is not ok."""
    c = _client(args)
    out = c.replica_admin("status")
    c.close()
    serving = [(f["addr"][0], int(f["addr"][1]))
               for f in (out.get("followers") or {}).values()
               if f.get("addr") and f.get("state") in ("ok", "lagging")]
    if serving:
        from antidote_tpu.proto.client import HashRing

        ring = HashRing(serving)
        out["ring"] = {"size": len(ring),
                       "arc_share": ring.arc_share_by_name()}
    print(json.dumps(out, indent=2))
    bad = [n for n, f in (out.get("followers") or {}).items()
           if f.get("state") != "ok"]
    if out.get("role") == "follower" and out.get("state") != "serving":
        bad.append(out.get("name"))
    return 1 if bad else 0


def cmd_replica_add(args) -> int:
    """Pre-register an expected follower with the owner (it shows as
    "down" until its first liveness report; also clears a prior
    remove's decommission tombstone)."""
    c = _client(args)
    addr = None
    if args.addr:
        h, p = args.addr.rsplit(":", 1)
        addr = (h, int(p))
    out = c.replica_admin("add", name=args.name, addr=addr)
    c.close()
    print(json.dumps(out, indent=2))
    return 0


def cmd_replica_remove(args) -> int:
    """Decommission a follower at the owner: dropped from the registry
    and its future liveness reports are refused (shut the follower
    process down separately)."""
    c = _client(args)
    out = c.replica_admin("remove", name=args.name)
    c.close()
    print(json.dumps(out, indent=2))
    return 0


def _member_rpc(args):
    from antidote_tpu.cluster.rpc import RpcClient

    host, port = args.rpc.rsplit(":", 1)
    return RpcClient(host, int(port))


def cmd_ringready(args) -> int:
    """All members of the DC up and answering (the riak_core ringready
    probe, /root/reference/src/antidote_console.erl:34-50)."""
    cli = _member_rpc(args)
    probes = cli.call("ctl_ready_all")
    cli.close()
    print(json.dumps(probes))
    return 0 if all(probes.values()) else 1


def cmd_cluster_status(args) -> int:
    cli = _member_rpc(args)
    print(json.dumps(cli.call("ctl_status")))
    cli.close()
    return 0


def cmd_cluster_resolve(args) -> int:
    cli = _member_rpc(args)
    n = cli.call("ctl_resolve", args.grace)
    cli.close()
    print(json.dumps({"resolved": n}))
    return 0


def cmd_cluster_sweep(args) -> int:
    cli = _member_rpc(args)
    n = cli.call("ctl_sweep", args.grace)
    cli.close()
    print(json.dumps({"swept": n}))
    return 0


def _parse_member_rpcs(spec: str):
    """``0=host:port,1=host:port,...`` -> {member_id: (host, port)}."""
    out = {}
    for part in spec.split(","):
        mid, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        out[int(mid)] = (host, int(port))
    return out


def _move_progress(shard, src, dst, done, total):
    log(f"[{done}/{total}] shard {shard}: member {src} -> member {dst}")


def cmd_cluster_join(args) -> int:
    """Live-join a booted-empty member into a serving DC (the staged
    join + ownership handoff of antidote_console.erl:34-50), with
    per-shard progress on stderr.  The joiner must already be running
    (`cluster.boot --joining`) and wired (`ctl_wire`)."""
    from antidote_tpu.cluster.join import live_join

    rpcs = _parse_member_rpcs(args.rpcs)
    moved = live_join(rpcs, new_id=args.joiner, progress=_move_progress)
    print(json.dumps({"joined": args.joiner, "moved": moved}))
    return 0


def cmd_cluster_leave(args) -> int:
    """Live-drain ANY member (except member 0, the sequencer) out of a
    serving DC: its shards stream to the least-loaded survivors, then
    every survivor forgets it.  Shut the leaver down afterwards."""
    from antidote_tpu.cluster.join import live_leave

    rpcs = _parse_member_rpcs(args.rpcs)
    moved = live_leave(rpcs, leaving_id=args.leaver,
                       progress=_move_progress)
    print(json.dumps({"left": args.leaver, "moved": moved}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="antidote_tpu.console")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sv = sub.add_parser("serve", help="boot a node and serve the protocol")
    sv.add_argument("--log-dir", default=None)
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8087)
    sv.add_argument("--metrics-port", type=int, default=None)
    sv.add_argument("--dc-id", type=int, default=0)
    sv.add_argument("--shards", type=int, default=None,
                    help="default: the log dir's recorded shape, else 16")
    sv.add_argument("--max-dcs", type=int, default=None,
                    help="default: the log dir's recorded shape, else 8")
    sv.add_argument("--recover", action="store_true")
    sv.add_argument("--interdc", action="store_true",
                    help="attach the inter-DC replication plane (TCP "
                         "fabric + replica) so clients can bootstrap a "
                         "DC mesh over the protocol")
    sv.add_argument("--follower-of", default=None,
                    metavar="HOST:PORT[,HOST:PORT...]",
                    help="boot as a READ REPLICA of the owner serving at "
                         "HOST:PORT (its client protocol port; the owner "
                         "must run --interdc): bootstraps from the "
                         "owner's checkpoint image / WAL tail, subscribes "
                         "to its txn stream, serves session reads, "
                         "refuses writes with a typed redirect.  A "
                         "CLUSTERED owner is the comma-separated list of "
                         "ALL its members' client endpoints (per-member "
                         "image composition + per-shard routed catch-up; "
                         "the first endpoint is named in redirects).  "
                         "Requires --log-dir; adopts the owner's shape")
    sv.add_argument("--follower-peers", default=None,
                    metavar="HOST:PORT[,...]",
                    help="with --follower-of against a GEO-REPLICATED "
                         "owner: the peer DCs' client endpoints, so "
                         "their origin chains replicate live through "
                         "the follower's own subscriptions (without "
                         "this, unsubscribed peer lanes show as "
                         "permanently 'skipped' divergence checks)")
    sv.add_argument("--replica-name", default=None,
                    help="follower name in the owner's replica registry "
                         "(default: follower-<dc>-<pid>)")
    sv.add_argument("--follower-park-ms", type=float, default=100.0,
                    help="how long a session read parks for the applied "
                         "clock to catch its token before the typed "
                         "lagging redirect")
    sv.add_argument("--no-server-proxy", action="store_true",
                    help="disable the symmetric serving fabric on this "
                         "follower: out-of-arc reads and writes answer "
                         "typed lagging/not_owner redirects instead of "
                         "being proxied/forwarded to the arc owner "
                         "(the pre-fabric smart-client-only behavior)")
    sv.add_argument("--divergence-check-s", type=float, default=5.0,
                    help="cadence of the follower's round-robin per-shard "
                         "digest comparison against the owner (detects "
                         "silent divergence; a mismatch re-bootstraps "
                         "from the image).  <= 0 disables")
    sv.add_argument("--interdc-port", type=int, default=0,
                    help="fixed listen port for the inter-DC fabric "
                         "(0 = ephemeral; fix it to publish through a "
                         "container/firewall boundary)")
    sv.add_argument("--public-host", default=None,
                    help="address advertised in connection descriptors "
                         "(required for remote DCs when binding 0.0.0.0)")
    sv.add_argument("--keys-per-table", type=int, default=4096,
                    help="initial rows per (type, shard); size near the "
                         "expected keyspace — every growth doubling "
                         "reallocates the device tables and recompiles "
                         "all serving shapes")
    sv.add_argument("--native-frontend", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="own the client port from the C++ epoll "
                         "front-end: accept, framing, admission and "
                         "whole-batch cache hits run off the GIL "
                         "(--no-native-frontend: the Python "
                         "socketserver plane; also the automatic "
                         "fallback when the module can't compile)")
    sv.add_argument("--max-connections", type=int, default=1024,
                    help="connection cap for the accept loop (native "
                         "and Python planes alike); excess connections "
                         "queue in the kernel listen backlog")
    sv.add_argument("--max-in-flight", type=int, default=256,
                    help="global admitted-request cap; past it the server "
                         "answers a typed busy error with a retry-after "
                         "hint instead of queueing")
    sv.add_argument("--max-in-flight-per-client", type=int, default=64,
                    help="per-client (peer host) admitted-request cap "
                         "(keeps one client machine's connection fleet "
                         "from monopolizing the global budget)")
    sv.add_argument("--default-deadline-ms", type=float, default=None,
                    help="server-side deadline for requests that carry no "
                         "deadline_ms field; work that outlives it is "
                         "aborted at dequeue (default: no deadline)")
    sv.add_argument("--mesh-devices", type=int, default=0,
                    help="shard the serving-epoch store over this many "
                         "devices (jax.sharding.Mesh; n_shards must be "
                         "divisible by it; 0 = single-chip serving "
                         "plane).  Stable time becomes a pmin "
                         "collective and epoch publishes go per-shard "
                         "incremental (ISSUE 10)")
    sv.add_argument("--epoch-tick-ms", type=float, default=100.0,
                    help="serving-epoch publication cadence for the "
                         "dedicated ticker (<= 0 disables the lock-split "
                         "epoch read plane entirely)")
    sv.add_argument("--snapshot-cache-size", type=int, default=None,
                    help="hot-key snapshot cache capacity in entries "
                         "(default: the store's built-in 65536)")
    sv.add_argument("--wal-segments", type=int, default=4,
                    help="parallel WAL append segments per shard: the "
                         "group-fsync coordinator syncs one segment "
                         "while the next commit group appends to its "
                         "neighbor (1 = classic single-file layout; "
                         "recovery merges either way)")
    sv.add_argument("--sync-log", action="store_true",
                    help="fsync before every commit ack (group fsync: "
                         "one fdatasync covers the whole merged batch)."
                         "  Default off, like the reference's "
                         "sync_log=false — an ack then means 'reached "
                         "the OS', durable within the WAL's background "
                         "sync interval")
    sv.add_argument("--pallas", action="store_true",
                    help="dispatch the materializer hot loops to the "
                         "fused Pallas kernels where one exists (counter "
                         "fold, set_aw add-wins fold, OR-set presence); "
                         "interpret mode off-TPU — the XLA scan stays "
                         "the fallback and semantics oracle")
    sv.add_argument("--fold-chunk", type=int, default=4096,
                    help="over-ring fold routing threshold: a replayed "
                         "key whose op log exceeds this many ops folds "
                         "with the chunked/sequence-sharded strategies "
                         "instead of one serial scan (docs/performance."
                         "md, 'Sequence-axis parallel folds')")
    sv.add_argument("--checkpoint-interval-s", type=float, default=300.0,
                    help="background checkpoint cadence (ISSUE 8): each "
                         "cycle publishes a VC-stamped store image and "
                         "reclaims WAL files below its floor, so restart "
                         "= load image + replay tail.  <= 0 disables "
                         "(restart then replays the whole WAL)")
    sv.add_argument("--checkpoint-rebase-every", type=int, default=8,
                    help="full-image rebase cadence of the incremental "
                         "checkpoint chain (ISSUE 13): between rebases, "
                         "a stamp writes only the rows dirtied since its "
                         "parent link (cost tracks the write working "
                         "set); the rebase re-bounds chain length and "
                         "reclaimable WAL.  1 = always full (pre-chain "
                         "behavior)")
    sv.add_argument("--checkpoint-scrub-s", type=float, default=900.0,
                    help="background bit-rot scrub cadence: CRC-verify "
                         "retained images/links off the commit lock; a "
                         "corrupt delta link is retired and a rebase "
                         "forced (0 disables — bit rot is then only "
                         "found at restart or follower bootstrap)")
    sv.add_argument("--resident-rows", type=int, default=0,
                    help="cold-tier device residency budget (ISSUE 13): "
                         "past this many resident table rows, the "
                         "coldest image-covered keys are evicted to the "
                         "checkpoint sidecar and faulted back on read "
                         "(typed cold_miss past the fault-rate cap).  "
                         "0 = unbounded (cold tier armed only for "
                         "fault-ins of an inherited beyond-RAM image)")
    sv.add_argument("--cold-fault-rate-cap", type=float, default=0.0,
                    help="cold fault-ins admitted per second before "
                         "reads are refused with a typed cold_miss "
                         "retry hint (0 = unlimited)")
    sv.add_argument("--checkpoint-retain", type=int, default=2,
                    help="published checkpoint images kept on disk; "
                         "older ones (and WAL files wholly below the "
                         "newest floor) are reclaimed after each publish")
    sv.add_argument("--tenant", action="append", default=None,
                    metavar="NAME:WEIGHT[,max_in_flight=N][,max_backlog=N]",
                    help="declare a tenant lane for weighted-fair "
                         "admission (repeatable; ISSUE 19).  Requests "
                         "map to the lane whose name prefixes their "
                         "bucket as 'tenant/bucket' (or carry an "
                         "explicit per-request tag); everything else "
                         "rides the built-in 'default' lane.  WEIGHT "
                         "sets the lane's deficit-round-robin share; "
                         "max_in_flight caps the tenant's admitted "
                         "requests, max_backlog its queued depth "
                         "(defaults: weight-proportional slice of the "
                         "shared bound).  Over-quota requests get a "
                         "typed tenant_busy refusal while other lanes "
                         "keep serving")
    sv.add_argument("--group-commit-window-us", type=float, default=0.0,
                    help="merge-point gather window in µs: the locked "
                         "worker keeps draining late-arriving commits "
                         "this long before taking the commit lock "
                         "(0 = natural batching only)")
    sv.set_defaults(fn=cmd_serve)

    for name, fn in (("status", cmd_status), ("ready", cmd_ready)):
        p = sub.add_parser(name)
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--port", type=int, default=8087)
        p.set_defaults(fn=fn)

    rd = sub.add_parser("read")
    rd.add_argument("--host", default="127.0.0.1")
    rd.add_argument("--port", type=int, default=8087)
    rd.add_argument("key"), rd.add_argument("type"), rd.add_argument("bucket")
    rd.set_defaults(fn=cmd_read)

    up = sub.add_parser("update")
    up.add_argument("--host", default="127.0.0.1")
    up.add_argument("--port", type=int, default=8087)
    up.add_argument("key"), up.add_argument("type"), up.add_argument("bucket")
    up.add_argument("op"), up.add_argument("arg")
    up.set_defaults(fn=cmd_update)

    ins = sub.add_parser("inspect", help="offline WAL inspection")
    ins.add_argument("--log-dir", required=True)
    ins.set_defaults(fn=cmd_inspect)

    cn = sub.add_parser("checkpoint-now",
                        help="run one synchronous checkpoint cycle on a "
                             "serving node (stamp, stream, publish, "
                             "reclaim) and print the manifest")
    cn.add_argument("--host", default="127.0.0.1")
    cn.add_argument("--port", type=int, default=8087)
    cn.set_defaults(fn=cmd_checkpoint_now)

    # follower-replica registry (ISSUE 9): add/remove/status against an
    # owner's replica plane (status also answers on a follower itself)
    rs = sub.add_parser("replica-status",
                        help="follower fleet health: typed ok/lagging/"
                             "down states, applied-VC lag, bootstrap "
                             "counts (exit 1 when any follower is "
                             "unhealthy)")
    rs.add_argument("--host", default="127.0.0.1")
    rs.add_argument("--port", type=int, default=8087)
    rs.set_defaults(fn=cmd_replica_status)

    ra = sub.add_parser("replica-add",
                        help="pre-register an expected follower with the "
                             "owner (shows 'down' until it reports)")
    ra.add_argument("--host", default="127.0.0.1")
    ra.add_argument("--port", type=int, default=8087)
    ra.add_argument("--name", required=True)
    ra.add_argument("--addr", default=None,
                    help="the follower's client endpoint host:port "
                         "(informational, shown in status)")
    ra.set_defaults(fn=cmd_replica_add)

    rr = sub.add_parser("replica-remove",
                        help="decommission a follower at the owner "
                             "(future reports from the name refused)")
    rr.add_argument("--host", default="127.0.0.1")
    rr.add_argument("--port", type=int, default=8087)
    rr.add_argument("--name", required=True)
    rr.set_defaults(fn=cmd_replica_remove)

    ic = sub.add_parser("inspect-checkpoint",
                        help="offline checkpoint inspection: published "
                             "manifests + the newest image's decoded "
                             "summary (stamp VC, floors, chain floors, "
                             "membership extras)")
    ic.add_argument("--log-dir", required=True)
    ic.set_defaults(fn=cmd_inspect_checkpoint)

    # cluster membership/ops commands against a member's control RPC
    # (antidote_console staged_join/down/ringready,
    # /root/reference/src/antidote_console.erl:34-50; rejoin a crashed
    # member with `python -m antidote_tpu.cluster.boot ... --recover`)
    for name, fn, hlp in (
        ("ringready", cmd_ringready,
         "all cluster members up and answering (riak_core ringready)"),
        ("cluster-status", cmd_cluster_status,
         "member topology, owned shards, stable VC"),
        ("cluster-resolve", cmd_cluster_resolve,
         "takeover: settle wedged commit chains (dead coordinator)"),
        ("cluster-sweep", cmd_cluster_sweep,
         "release prepared locks of never-sequenced dead txns"),
    ):
        p = sub.add_parser(name, help=hlp)
        p.add_argument("--rpc", required=True,
                       help="member control RPC as host:port")
        if name == "cluster-resolve":
            p.add_argument("--grace", type=float, default=0.0)
        if name == "cluster-sweep":
            p.add_argument("--grace", type=float, default=30.0)
        p.set_defaults(fn=fn)

    # live membership change (staged join/leave while the DC serves)
    cj = sub.add_parser(
        "cluster-join",
        help="live-join a booted-empty member (shards stream over while "
             "the cluster serves; per-shard progress on stderr)")
    cj.add_argument("--rpcs", required=True,
                    help="member control RPCs incl. the joiner, as "
                         "id=host:port,id=host:port,...")
    cj.add_argument("--joiner", type=int, required=True,
                    help="joining member id (fresh, highest)")
    cj.set_defaults(fn=cmd_cluster_join)

    cl = sub.add_parser(
        "cluster-leave",
        help="live-drain any member but the sequencer (member 0) out of "
             "a serving DC, then forget it everywhere")
    cl.add_argument("--rpcs", required=True,
                    help="member control RPCs incl. the leaver, as "
                         "id=host:port,...")
    cl.add_argument("--leaver", type=int, required=True,
                    help="departing member id (any id except 0)")
    cl.set_defaults(fn=cmd_cluster_leave)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
