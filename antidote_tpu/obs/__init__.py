"""Observability: metrics registry, error monitor, prometheus exposition.

Rebuilds the reference's stats layer (SURVEY §2.7):
``antidote_stats_collector`` (/root/reference/src/antidote_stats_collector.erl:80-93)
declares prometheus counters/gauges/histograms and periodically observes
staleness; ``antidote_error_monitor`` hooks the error logger; elli serves
``/metrics`` on :3001 (/root/reference/src/antidote_sup.erl:118-128).
"""

from antidote_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NetMetrics,
    NodeMetrics,
    install_error_monitor,
    net_metrics,
)
from antidote_tpu.obs.server import MetricsServer
from antidote_tpu.obs.trace import Timer, trace_span

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NetMetrics",
    "NodeMetrics",
    "MetricsServer",
    "net_metrics",
    "Timer",
    "install_error_monitor",
    "trace_span",
]
