"""HTTP /metrics endpoint.

The elli listener on :3001 (/root/reference/src/antidote_sup.erl:118-128;
``config/sys.config:27-33`` sets the port) re-provided with the stdlib HTTP
server on a daemon thread.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from antidote_tpu.obs.metrics import MetricsRegistry

DEFAULT_METRICS_PORT = 3001


class MetricsServer:
    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        registry_ref = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry_ref.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"metrics:{self.port}",
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
