"""Per-launch timing and JAX profiler hooks.

The reference has no per-request tracing (SURVEY §5 notes the gap and asks
the rebuild to add profiler hooks from day one).  ``Timer`` feeds the
``antidote_device_launch_seconds`` histogram; ``trace_span`` wraps a block
in a ``jax.profiler.TraceAnnotation`` when profiling is active, and is a
plain timer otherwise.
"""

from __future__ import annotations

import contextlib
import time


class Timer:
    """Context manager: measure a block, optionally feed a histogram."""

    def __init__(self, histogram=None):
        self.histogram = histogram
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self.histogram is not None:
            self.histogram.observe(self.elapsed)
        return False


@contextlib.contextmanager
def trace_span(name: str, histogram=None):
    """Named span: shows up in a JAX profiler trace (``jax.profiler
    .start_trace``) and in the launch-seconds histogram."""
    import jax

    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        if histogram is not None:
            histogram.observe(time.perf_counter() - t0)
