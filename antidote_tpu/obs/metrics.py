"""Prometheus-style metrics registry.

The metric set mirrors ``antidote_stats_collector``
(/root/reference/src/antidote_stats_collector.erl:80-93):

  antidote_error_count                counter
  antidote_staleness                  histogram (ms buckets 1..10000)
  antidote_open_transactions          gauge
  antidote_aborted_transactions_total counter
  antidote_operations_total{type}     counter (read | read_async | update)

plus framework-native extras (device launch timing, commit batch sizes).
Exposition follows the prometheus text format so the reference's Grafana
dashboard queries (monitoring/Antidote-Dashboard.json) keep working.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = "", label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        return self._values.get(key, 0.0)

    def snapshot(self) -> Dict[Tuple[str, ...], float]:
        """Locked copy of label-tuple -> value (readers must not iterate
        ``_values`` live: a concurrent first inc() of a new label set
        inserts a key mid-iteration)."""
        with self._lock:
            return dict(self._values)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # the HTTP server scrapes from another thread
            vals = dict(self._values)
        if not self.label_names and not vals:
            vals = {(): 0.0}
        for key, v in sorted(vals.items()):
            labels = dict(zip(self.label_names, key))
            out.append(f"{self.name}{_fmt_labels(labels)} {v:g}")
        return out


class Gauge:
    """Scalar gauge, optionally labeled (``label_names``): the labeled
    form keys one value per label tuple — e.g. the per-segment WAL
    depth gauge, ``antidote_wal_segment_depth{segment="0"}``."""

    def __init__(self, name: str, help_: str = "",
                 label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._value = 0.0
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels) -> Tuple[str, ...]:
        return tuple(str(labels.get(n, "")) for n in self.label_names)

    def set(self, v: float, **labels) -> None:
        with self._lock:
            if self.label_names:
                self._values[self._key(labels)] = v
            else:
                self._value = v

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            if self.label_names:
                k = self._key(labels)
                self._values[k] = self._values.get(k, 0.0) + amount
            else:
                self._value += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        if self.label_names:
            return self._values.get(self._key(labels), 0.0)
        return self._value

    def snapshot(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)

    def expose(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
        ]
        if not self.label_names:
            out.append(f"{self.name} {self._value:g}")
            return out
        with self._lock:
            vals = dict(self._values)
        for key, v in sorted(vals.items()):
            labels = dict(zip(self.label_names, key))
            out.append(f"{self.name}{_fmt_labels(labels)} {v:g}")
        return out


#: the reference's staleness buckets: ms 1..10000
#: (/root/reference/src/antidote_stats_collector.erl:82)
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 10000)


class Histogram:
    """Fixed-bucket histogram, optionally labeled.

    With ``label_names`` set, each observed label tuple gets its own
    (buckets, sum, count) child series in the exposition, while the
    unlabeled aggregate keeps feeding :meth:`summary` / :meth:`percentile`
    so node-status blocks stay label-agnostic.
    """

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS,
                 label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self.label_names = tuple(label_names)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        #: label tuple -> [bucket counts, sum, count]
        self._children: Dict[Tuple[str, ...], list] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, **labels) -> None:
        with self._lock:
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._n += 1
            if self.label_names:
                key = tuple(str(labels.get(n, "")) for n in self.label_names)
                child = self._children.get(key)
                if child is None:
                    child = [[0] * (len(self.buckets) + 1), 0.0, 0]
                    self._children[key] = child
                child[0][i] += 1
                child[1] += v
                child[2] += 1

    @property
    def count(self) -> int:
        return self._n

    def summary(self) -> Dict[str, float]:
        """Compact (count, mean, p50, p99) view for node-status blocks —
        quantiles are bucket upper bounds, same as :meth:`percentile`."""
        with self._lock:
            n, s = self._n, self._sum
        return {
            "count": n,
            "mean": (s / n) if n else 0.0,
            "p50": self.percentile(0.5),
            "p99": self.percentile(0.99),
        }

    def percentile(self, q: float) -> float:
        """Approximate q-quantile from bucket counts (upper bound)."""
        if self._n == 0:
            return 0.0
        target = q * self._n
        acc = 0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= target:
                return float(self.buckets[i]) if i < len(self.buckets) else float("inf")
        return float("inf")

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:  # consistent (buckets, sum, count) snapshot
            counts, total, n = list(self._counts), self._sum, self._n
            children = {
                k: (list(c[0]), c[1], c[2]) for k, c in self._children.items()
            }
        if self.label_names:
            for key in sorted(children):
                labels = dict(zip(self.label_names, key))
                ccounts, csum, cn = children[key]
                acc = 0
                for i, b in enumerate(self.buckets):
                    acc += ccounts[i]
                    out.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels({**labels, 'le': str(b)})} {acc}"
                    )
                acc += ccounts[-1]
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': '+Inf'})} {acc}"
                )
                out.append(f"{self.name}_sum{_fmt_labels(labels)} {csum:g}")
                out.append(f"{self.name}_count{_fmt_labels(labels)} {cn}")
            return out
        acc = 0
        for i, b in enumerate(self.buckets):
            acc += counts[i]
            out.append(f'{self.name}_bucket{{le="{b}"}} {acc}')
        acc += counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {acc}')
        out.append(f"{self.name}_sum {total:g}")
        out.append(f"{self.name}_count {n}")
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help_="", label_names=()):
        return self.register(Counter(name, help_, tuple(label_names)))

    def gauge(self, name, help_="", label_names=()):
        return self.register(Gauge(name, help_, tuple(label_names)))

    def histogram(self, name, help_="", buckets=DEFAULT_BUCKETS, label_names=()):
        return self.register(Histogram(name, help_, buckets, tuple(label_names)))

    def get(self, name):
        return self._metrics[name]

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class NetMetrics:
    """Process-wide fabric/RPC resilience counters.

    These live OUTSIDE any node's registry because their owners (the TCP
    fabric's reconnect loops, the cluster RPC client, the fault
    injector) have no node reference — yet operators need them on the
    same ``/metrics`` page.  :func:`net_metrics` returns the process
    singleton; ``NodeMetrics`` attaches the same counter objects into
    every node registry, so each node's exposition includes them.
    """

    def __init__(self):
        self.reconnects = Counter(
            "antidote_interdc_reconnects_total",
            "Successful inter-DC subscription reconnects", ("link",)
        )
        self.reconnect_attempts = Counter(
            "antidote_interdc_reconnect_attempts_total",
            "Inter-DC subscription reconnect dial attempts", ("link",)
        )
        self.corrupt_frames = Counter(
            "antidote_interdc_corrupt_frames_total",
            "Undecodable inter-DC stream frames discarded"
        )
        self.catchup_failures = Counter(
            "antidote_interdc_catchup_failures_total",
            "Log catch-up queries that failed transiently"
        )
        self.rpc_retries = Counter(
            "antidote_rpc_retries_total",
            "Cluster RPC attempts retried after a transport error"
        )
        self.rpc_deadline_exceeded = Counter(
            "antidote_rpc_deadline_exceeded_total",
            "Cluster RPC calls that exhausted their deadline/retry budget"
        )
        self.faults_injected = Counter(
            "antidote_faults_injected_total",
            "Fault-injection decisions taken", ("site", "action")
        )
        self.pump_fallback = Counter(
            "antidote_native_pump_fallback_total",
            "Times the native receive plane was unavailable and the "
            "Python reader fallback engaged"
        )
        self.frontend_fallback = Counter(
            "antidote_native_frontend_fallback_total",
            "Times the native serving front-end was unavailable and the "
            "Python socketserver plane engaged"
        )
        self.shard_moves = Counter(
            "antidote_cluster_shard_moves_total",
            "Live shard ownership moves (two-phase handoff legs)",
            ("role",)  # import | relinquish
        )
        self.route_updates = Counter(
            "antidote_interdc_reroutes_total",
            "Inter-DC catch-up routes re-pointed at a new shard owner "
            "via ownership-epoch gossip"
        )
        self.egress_window_drops = Counter(
            "antidote_interdc_egress_window_drops_total",
            "Egress frames dropped for lagging subscribers (bounded "
            "outbox overflow; the subscriber heals via opid-gap catch-up)"
        )
        self.ingress_shed = Counter(
            "antidote_interdc_ingress_shed_total",
            "Ingress txn messages shed past the gate/pending high-water "
            "mark (chain position NOT advanced; catch-up refills)"
        )

    def all_metrics(self):
        return (self.reconnects, self.reconnect_attempts,
                self.corrupt_frames, self.catchup_failures,
                self.rpc_retries, self.rpc_deadline_exceeded,
                self.faults_injected, self.pump_fallback,
                self.frontend_fallback, self.shard_moves,
                self.route_updates, self.egress_window_drops,
                self.ingress_shed)

    def attach(self, registry: "MetricsRegistry") -> None:
        """Register the shared counter objects into a node registry so
        they appear in that node's exposition (idempotent per registry)."""
        for m in self.all_metrics():
            try:
                registry.register(m)
            except ValueError:
                pass  # already attached to this registry

    def snapshot(self) -> Dict[str, float]:
        """Label-summed counter values (the console's status command)."""
        out: Dict[str, float] = {}
        for m in self.all_metrics():
            out[m.name] = sum(m._values.values()) if m._values else 0.0
        return out


_NET: Optional[NetMetrics] = None
_NET_LOCK = threading.Lock()


def net_metrics() -> NetMetrics:
    global _NET
    if _NET is None:
        with _NET_LOCK:
            if _NET is None:
                _NET = NetMetrics()
    return _NET


class NodeMetrics:
    """The per-replica metric set, named as in the reference."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.error_count = r.counter(
            "antidote_error_count", "Number of error messages logged"
        )
        self.staleness = r.histogram(
            "antidote_staleness", "Staleness of the stable snapshot (ms)"
        )
        self.open_transactions = r.gauge(
            "antidote_open_transactions", "Number of open interactive transactions"
        )
        self.aborted_transactions = r.counter(
            "antidote_aborted_transactions_total", "Aborted transactions"
        )
        self.operations = r.counter(
            "antidote_operations_total", "Operations by type", ("type",)
        )
        # framework-native extras
        self.device_launch_seconds = r.histogram(
            "antidote_device_launch_seconds",
            "Wall time of device kernel launches (s)",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        self.commit_batch_size = r.histogram(
            "antidote_commit_batch_size", "Effects per commit batch",
            buckets=(1, 2, 4, 8, 16, 64, 256, 1024, 4096, 16384),
        )
        # overload/backpressure plane (PR 4): every bound, shed, and
        # degraded-mode flip is observable
        self.shed = r.counter(
            "antidote_shed_total",
            "Requests shed by overload protection, by plane "
            "(server | server_queue | txn | deadline | read_only | "
            "tenant — tenant-scoped quota refusals, distinguishable "
            "from global busy)",
            ("plane",),
        )
        self.in_flight = r.gauge(
            "antidote_server_in_flight",
            "Wire-server requests currently admitted (AdmissionGate)",
        )
        # multi-tenant QoS plane (ISSUE 19): per-tenant interference
        # observability.  The `tenant` label is BOUNDED: every call
        # site MUST clamp the value through TenantRegistry.label()
        # (tools/lint.py tenant-label rule) — tenant names come from
        # operator config, never from the wire.
        self.tenant_shed = r.counter(
            "antidote_tenant_shed_total",
            "Tenant-scoped refusals by lane/stage "
            "(admission | batch_gate | locked | txn)",
            ("tenant", "plane"),
        )
        self.tenant_in_flight = r.gauge(
            "antidote_tenant_in_flight",
            "Requests currently admitted per tenant (AdmissionGate "
            "tenant accounting)",
            ("tenant",),
        )
        self.tenant_request_seconds = r.histogram(
            "antidote_tenant_request_seconds",
            "Wire-server request latency per tenant, submit to reply (s)",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
            label_names=("tenant",),
        )
        self.commit_gate_depth = r.gauge(
            "antidote_commit_gate_depth",
            "Static batch-gate queue depth (requests parked for the "
            "next group launch)",
        )
        self.interdc_gate_depth = r.gauge(
            "antidote_interdc_gate_depth",
            "Remote txns queued in the causal dependency gates",
        )
        self.degraded_read_only = r.gauge(
            "antidote_degraded_read_only",
            "1 while the node is in degraded read-only mode (WAL "
            "appends failing), else 0",
        )
        self.server_request_seconds = r.histogram(
            "antidote_server_request_seconds",
            "Wire-server request latency, admission to reply (s)",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
        )
        self.commit_seconds = r.histogram(
            "antidote_commit_seconds",
            "Commit-group latency inside the commit lock (s)",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
        )
        # serving pipeline (ISSUE 5): per-stage wire-server timings plus
        # the serving-epoch / hot-key snapshot-cache planes.  Stage
        # histograms use µs-resolution buckets — the whole point of the
        # staged pipeline is that each stage is far below a millisecond.
        stage_buckets = (2e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                         5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.5, 1)
        self.stage_decode_seconds = r.histogram(
            "antidote_stage_decode_seconds",
            "Pipeline stage: frame decode + admit, per request (s)",
            buckets=stage_buckets,
        )
        self.stage_parked_seconds = r.histogram(
            "antidote_stage_parked_seconds",
            "Pipeline stage: time parked in a bounded queue before its "
            "stage dequeued it, per request (s)",
            buckets=stage_buckets,
        )
        self.stage_launch_seconds = r.histogram(
            "antidote_stage_launch_seconds",
            "Pipeline stage: epoch-read classify + device launch, per "
            "batch — async dispatch only, never a device sync (s)",
            buckets=stage_buckets,
        )
        self.stage_writeback_seconds = r.histogram(
            "antidote_stage_writeback_seconds",
            "Pipeline stage: device materialize + decode + reply "
            "serialization, per batch (s)",
            buckets=stage_buckets,
        )
        self.snapshot_cache = r.counter(
            "antidote_snapshot_cache_total",
            "Hot-key snapshot cache events (hit | miss | evict)",
            ("event",),
        )
        self.serving_reads = r.counter(
            "antidote_serving_reads_total",
            "Static reads by serving path (cache | gather | locked)",
            ("path",),
        )
        self.epoch_publish = r.counter(
            "antidote_epoch_publish_total",
            "Serving-epoch publications by mode (scatter | copy | defer)",
            ("mode",),
        )
        self.epoch_rows = r.counter(
            "antidote_epoch_rows_total",
            "Rows re-frozen by serving-epoch publications, by mode — "
            "scatter rows scale with the write working set, copy rows "
            "with table size (the publish-cost cap's observable)",
            ("mode",),
        )
        self.serving_epoch_id = r.gauge(
            "antidote_serving_epoch_id",
            "Monotone id of the last published serving epoch",
        )
        # mesh serving plane (ISSUE 10): device count, per-shard
        # incremental publish rows, and the stable-time pmin collective
        self.mesh_devices = r.gauge(
            "antidote_mesh_devices",
            "Devices in the serving mesh (0 / absent = single-chip "
            "serving plane)",
        )
        self.mesh_publish = r.counter(
            "antidote_mesh_publish_total",
            "Rows re-frozen into each shard's device slice by serving-"
            "epoch publications on the mesh plane — an incremental "
            "publish advances only the dirty shards' labels; a full "
            "copy advances every shard by its table rows",
            ("shard",),
        )
        self.mesh_stable_seconds = r.histogram(
            "antidote_mesh_stable_seconds",
            "Stable-time pmin collective latency, launch to host "
            "readback (s); launched only when a commit advanced an "
            "applied clock (cached otherwise)",
            buckets=stage_buckets,
        )
        # materializer fold plane (ISSUE 15): which fold strategy served
        # each read / replay, and how long the over-ring replay folds take
        self.fold_dispatch = r.counter(
            "antidote_fold_dispatch_total",
            "Materializer fold dispatches by strategy (serial | assoc | "
            "long | mesh_assoc | pallas_counter | pallas_set_aw)",
            ("strategy",),
        )
        self.fold_seconds = r.histogram(
            "antidote_fold_seconds",
            "Over-ring replay fold latency, dispatch to host "
            "materialize (s)",
            buckets=(1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
            label_names=("strategy", "type"),
        )
        # write plane (ISSUE 6): cross-connection group commit, parallel
        # WAL group fsync, and the commutative-update cert bypass
        self.commit_merge_width = r.histogram(
            "antidote_commit_merge_width",
            "Write-bearing transactions fused per merged commit batch "
            "(one lock take / certification pass / WAL append / device "
            "scatter each)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 1024),
        )
        self.wal_fsync_batch = r.histogram(
            "antidote_wal_fsync_batch",
            "Commit barriers covered per group-fsync pass (sync_log="
            "true; >1 means barriers coalesced into one fsync)",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.wal_segment_depth = r.gauge(
            "antidote_wal_segment_depth",
            "Bytes appended since the segment's last commit barrier/"
            "fsync, per WAL segment index (in-flight durability debt)",
            label_names=("segment",),
        )
        self.cert_bypass = r.counter(
            "antidote_cert_bypass_total",
            "Transactions that skipped certification via the blind-"
            "commutative bypass (no reads, commutative-type blind "
            "updates only, no explicit certify=true)",
        )
        # checkpointed fast restart (ISSUE 8): recovery phase timings,
        # replayed-record counts, image age, and WAL bytes reclaimed by
        # the guarded truncation below the checkpoint floor
        self.recovery_seconds = r.gauge(
            "antidote_recovery_seconds",
            "Wall time of the last recovery, by phase (checkpoint = "
            "image load + install; tail = WAL tail replay)",
            ("phase",),
        )
        self.recovery_records = r.counter(
            "antidote_recovery_records_total",
            "WAL records replayed by recovery (tail-only when a "
            "checkpoint image was installed)",
        )
        self.checkpoint_age = r.gauge(
            "antidote_checkpoint_age_seconds",
            "Age of the newest published checkpoint image (how much "
            "tail a crash-now restart would replay)",
        )
        self.wal_reclaimed = r.counter(
            "antidote_wal_bytes_reclaimed_total",
            "WAL bytes reclaimed by checkpoint truncation (files wholly "
            "below a published floor)",
        )
        self.checkpoint_total = r.counter(
            "antidote_checkpoint_total",
            "Checkpoint attempts by outcome (ok | error); an error "
            "publishes and truncates nothing",
            ("status",),
        )
        # incremental checkpoint chains + scrub + cold tier (ISSUE 13)
        self.checkpoint_stamp = r.counter(
            "antidote_checkpoint_stamp_total",
            "Published checkpoint stamps by kind (full = rebase image "
            "with cold sidecar; delta = parent-linked incremental link "
            "whose cost scales with the dirty set)",
            ("kind",),
        )
        self.checkpoint_stamp_rows = r.counter(
            "antidote_checkpoint_stamp_rows_total",
            "Table rows written per checkpoint stamp by kind — delta "
            "rows track the write working set, full rows the resident "
            "extent (the incremental-cost observable)",
            ("kind",),
        )
        self.checkpoint_scrub = r.counter(
            "antidote_checkpoint_scrub_total",
            "Background bit-rot scrub verifications of retained "
            "images/links (ok | corrupt — a corrupt delta link is "
            "retired and a rebase forced)",
            ("result",),
        )
        self.coldtier_events = r.counter(
            "antidote_coldtier_events_total",
            "Cold-tier transitions (evict = device row dropped to the "
            "sidecar; fault = row faulted back in; refused = typed "
            "ColdMiss past the rate cap or an I/O fault; crc_fail = "
            "fault-in caught on-disk corruption; lost = key tombstoned "
            "after bit rot on every retained image)",
            ("event",),
        )
        self.coldtier_resident_rows = r.gauge(
            "antidote_coldtier_resident_rows",
            "Device rows currently holding key state (bounded by "
            "--resident-rows when the cold tier is armed)",
        )
        self.coldtier_cold_keys = r.gauge(
            "antidote_coldtier_cold_keys",
            "Keys whose state lives only in the checkpoint sidecar",
        )
        # follower read replicas & session tier (ISSUE 9): owner-side
        # lag per follower, session redirects (park-then-redirect +
        # not-owner write refusals), bootstrap/repair cycles by mode,
        # and the divergence-detection comparisons
        self.follower_lag = r.gauge(
            "antidote_follower_applied_vc_lag",
            "Owner-side commits the named follower's applied own-lane "
            "clock trails the owner's commit counter by (from its last "
            "liveness report)",
            label_names=("follower",),
        )
        self.session_redirects = r.counter(
            "antidote_session_redirects_total",
            "Session requests a replica refused with a typed redirect "
            "(lagging = applied clock behind the token after the park "
            "window; not_owner = write/txn sent to a follower), by wire "
            "dialect (native msgpack | apb protobuf)",
            ("kind", "dialect"),
        )
        self.fleet_followers = r.gauge(
            "antidote_fleet_followers",
            "Followers currently registered with this owner's replica "
            "registry (the fleet the hash ring routes over)",
        )
        # symmetric serving fabric (ISSUE 17): server-side proxying /
        # forwarding volume by kind (read | write | txn) and outcome
        # (ok | failover = served after >=1 dead hop | error), the
        # per-hop proxy latency, and the node's local fleet-health view
        self.proxy_total = r.counter(
            "antidote_proxy_total",
            "Requests this node proxied/forwarded to another fleet "
            "member (kind: read | write | txn; outcome: ok | failover "
            "| error)",
            ("kind", "outcome"),
        )
        self.proxy_hop_seconds = r.histogram(
            "antidote_proxy_hop_seconds",
            "Wall time of one server-side proxy/forward hop, dial to "
            "decoded reply (s)",
            buckets=(1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                     2.5e-2, 5e-2, 0.1, 0.5, 1, 5),
        )
        self.fleet_health = r.gauge(
            "antidote_fleet_health",
            "This node's live view of each fleet endpoint (1 = "
            "serving, 0 = dead/down — registry state merged with local "
            "connect/timeout observations)",
            label_names=("endpoint",),
        )
        self.follower_bootstrap = r.counter(
            "antidote_follower_bootstrap_total",
            "Follower bootstrap/repair cycles by mode (image = full "
            "checkpoint-image install; delta = re-install because the "
            "chain position fell below the owner's compaction floor or "
            "divergence was detected; tail = WAL catch-up only)",
            ("mode",),
        )
        self.divergence_checks = r.counter(
            "antidote_divergence_checks_total",
            "Follower-vs-owner per-shard digest comparisons (ok | "
            "skipped = applied clocks unequal, nothing comparable | "
            "unsubscribed = the lag is on a peer lane this follower was "
            "never given a descriptor for (--follower-peers) | "
            "mismatch = divergence detected and healed)",
            ("result",),
        )
        # Merkle-split divergence repair (ISSUE 13)
        self.merkle_probe_hashes = r.counter(
            "antidote_merkle_probe_hashes_total",
            "Hash comparisons spent walking the divergence Merkle tree "
            "(O(fanout·log n) per localized mismatch — the flat digest "
            "compared O(1) hashes but healed O(shard))",
        )
        self.divergence_heals = r.counter(
            "antidote_divergence_heals_total",
            "Divergence repairs by mode (range = Merkle-localized "
            "leaf fetch, quarantine without re-install; image = full "
            "re-bootstrap fallback)",
            ("mode",),
        )
        # escrow economy (ISSUE 18): bounded-counter refusals, rights
        # grants by role, transfer round-trip latency, and the queued
        # shortfall the background rights-transfer loop is working off
        self.escrow_refusals = r.counter(
            "antidote_escrow_refusals_total",
            "counter_b decrements/transfers refused typed by the "
            "group-commit escrow certification (insufficient locally-"
            "held rights; zero oversell is the invariant this buys)",
        )
        self.escrow_grants = r.counter(
            "antidote_escrow_grants_total",
            "Escrow rights-transfer grants by role (granter = this node "
            "committed a transfer out of its lane; requester = a grant "
            "this node asked for landed; failed = a request refused, "
            "lost, or surfaced typed on the at-most-once channel — "
            "never blind-resent)",
            ("role",),
        )
        self.escrow_transfer_seconds = r.histogram(
            "antidote_escrow_transfer_seconds",
            "Rights-transfer request round trip on the inter-DC query "
            "channel, send to decoded grant (s)",
            buckets=(1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        self.escrow_shortfall = r.gauge(
            "antidote_escrow_shortfall",
            "Rights currently queued for by refused decrements (the "
            "background transfer loop's pending demand; 0 = every "
            "refusal has been covered or retired)",
        )
        # process-wide fabric/RPC resilience counters ride along in this
        # node's exposition (shared objects — see NetMetrics)
        net_metrics().attach(r)

    # -- staleness observer (every 10 s in the reference,
    #    /root/reference/src/antidote_stats_collector.erl:87-93); here it
    #    is called by whoever owns a clock source, typically the node.
    def observe_staleness(self, ms: float) -> None:
        self.staleness.observe(ms)


class _ErrorCountHandler(logging.Handler):
    def __init__(self, metrics: NodeMetrics):
        super().__init__(level=logging.ERROR)
        self.metrics = metrics

    def emit(self, record):
        self.metrics.error_count.inc()


def install_error_monitor(metrics: NodeMetrics,
                          logger: Optional[logging.Logger] = None):
    """Hook the logging tree so every ERROR-level record bumps
    ``antidote_error_count`` (antidote_error_monitor,
    /root/reference/src/antidote_error_monitor.erl:36-48).  Returns the
    handler so callers can remove it."""
    h = _ErrorCountHandler(metrics)
    (logger or logging.getLogger()).addHandler(h)
    return h


def staleness_ms(wallclock_of_stable_entry: float) -> float:
    """now − min stable-snapshot entry, in ms (the reference computes this
    from its physical-clock VCs; our logical clocks need a wallclock map)."""
    return max(0.0, (time.time() - wallclock_of_stable_entry) * 1e3)
