"""SPMD execution over a device mesh — the riak_core ring on ICI.

The reference distributes state over a 16-partition consistent-hash ring of
Erlang vnode processes (/root/reference/src/antidote_app.erl:42-59) and
computes the DC-wide stable snapshot by 1 s metadata gossip + entry-wise
min (/root/reference/src/meta_data_sender.erl:224-255,
/root/reference/src/stable_time_functions.erl:51-85).

Here the ring is a ``jax.sharding.Mesh`` with one axis, ``"shard"``: every
table array carries a leading shard axis laid out over the mesh, the data
plane (scatter-append, materializer fold) is embarrassingly parallel per
shard, and the stable snapshot is a single ``lax.pmin`` collective over ICI
per step — replacing the gossip rounds entirely.

``sharded_step_fn`` builds the full replica step as ONE jitted program:
  1. scatter a routed commit batch into the op rings (per shard)
  2. materialize a routed read batch (per shard)
  3. advance per-shard applied clocks and pmin them into the stable VC
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_tpu.compat import shard_map
from antidote_tpu.store.typed_table import _shard_base_select_body, _shard_read_body

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(devices[:n], (SHARD_AXIS,))


def shard_axis_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding for table arrays: [P, ...] over the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def sharded_step_fn(ty, cfg, mesh: Mesh):
    """One jitted replica step over the mesh (see module docstring).

    All batch operands are per-shard routed/padded:
      app_rows/app_slots i64[P, Ma], app_a i64[P, Ma, A], app_b i32[P, Ma, B],
      app_vc i32[P, Ma, D], app_origin i32[P, Ma];
      read_rows i64[P, Mr], read_n_ops i32[P, Mr], read_vcs i32[P, Mr, D];
      applied_vc i32[P, D].
    Returns (new ops arrays, read state pytree [P, Mr, ...], applied [P, Mr],
    complete [P, Mr], new_applied_vc [P, D], stable_vc [P, D] — the pmin,
    identical on every shard row).

    With ``cfg.use_pallas`` and a counter table, the ring fold inside
    the step dispatches to the fused Pallas kernel with SHARD-LOCAL
    extents (``pallas_kernels.counter_fold_local``): each shard's block
    runs its own kernel grid inside the shard_map body, so the fold
    stays device-local on a mesh (interpret mode off-TPU).  CALLER
    CONTRACT: the kernel sums lane-0 deltas in i32, and a static step
    fn cannot host-gate per batch — only enable ``use_pallas`` when
    every |delta| ≤ INT32_MAX // ops_per_key (the bound typed_table
    enforces dynamically via its host-tracked ``max_abs_delta`` before
    choosing ITS pallas dispatch; here the check is yours).
    """
    from antidote_tpu.materializer import pallas_kernels as pk

    read_body = _shard_read_body(ty, cfg)
    # platform-gated like the store's strategy picker: interpret-mode
    # kernels on CPU regress the step, they don't accelerate it
    use_pallas = (bool(getattr(cfg, "use_pallas", False))
                  and pk.in_path_ok())
    pallas_counter = use_pallas and ty.name == "counter_pn"
    pallas_set_aw = use_pallas and ty.name == "set_aw"
    select_body = (
        _shard_base_select_body(ty, cfg)
        if (pallas_counter or pallas_set_aw)
        else None
    )

    def per_shard(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                  app_rows, app_slots, app_a, app_b, app_vc, app_origin,
                  read_rows, read_n_ops, read_vcs, applied_vc):
        # shard_map hands each shard its block with the leading axis of
        # size 1 kept; drop it for the body.
        sq = lambda t: jax.tree.map(lambda x: x[0], t)
        (snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
         app_rows, app_slots, app_a, app_b, app_vc, app_origin,
         read_rows, read_n_ops, read_vcs, applied_vc) = map(
            sq,
            (snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
             app_rows, app_slots, app_a, app_b, app_vc, app_origin,
             read_rows, read_n_ops, read_vcs, applied_vc),
        )
        # 1. commit scatter (padding rows are out-of-range → dropped)
        ops_a = ops_a.at[app_rows, app_slots].set(app_a, mode="drop")
        ops_b = ops_b.at[app_rows, app_slots].set(app_b, mode="drop")
        ops_vc = ops_vc.at[app_rows, app_slots].set(app_vc, mode="drop")
        ops_origin = ops_origin.at[app_rows, app_slots].set(
            app_origin, mode="drop"
        )
        # 2. advance this shard's applied clock
        n = ops_a.shape[0]
        valid = (app_rows < n)[:, None]
        new_applied = jnp.maximum(
            applied_vc, jnp.max(jnp.where(valid, app_vc, 0), axis=0)
        )
        # 3. stable snapshot: entry-wise min across shards, over ICI
        stable = lax.pmin(new_applied, SHARD_AXIS)
        # 4. batched materializer read
        rows_clip = jnp.minimum(read_rows, n - 1)
        if pallas_counter:
            # Pallas fold with shard-local extents, inside the sharded
            # step: version-select the base on this shard's block, then
            # one fused masked-sum kernel over the local ring slice —
            # the kernel grid never crosses the shard axis
            from antidote_tpu.materializer import pallas_kernels as pk

            base_state, base_vc, complete = select_body(
                snap, snap_vc, snap_seq, rows_clip, read_vcs
            )
            dcnt, applied = pk.counter_fold_local(
                ops_a[rows_clip][..., 0].astype(jnp.int32),
                ops_vc[rows_clip], read_n_ops, base_vc, read_vcs,
            )
            state = {"cnt": base_state["cnt"] + dcnt.astype(jnp.int64)}
        elif pallas_set_aw:
            # same shape: base select on this shard's block, then the
            # fused add-wins fold kernel over the local ring slice — the
            # BASELINE workload's own fold, shard-local on the mesh
            from antidote_tpu.materializer import pallas_kernels as pk

            base_state, base_vc, complete = select_body(
                snap, snap_vc, snap_seq, rows_clip, read_vcs
            )
            state, applied = pk.set_aw_fold_local(
                base_state, ops_a[rows_clip], ops_b[rows_clip],
                ops_vc[rows_clip], ops_origin[rows_clip],
                read_n_ops, base_vc, read_vcs,
            )
        else:
            state, applied, complete = read_body(
                snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                rows_clip, read_n_ops, read_vcs,
            )
        ex = lambda t: jax.tree.map(lambda x: x[None], t)
        return (
            ex(ops_a), ex(ops_b), ex(ops_vc), ex(ops_origin),
            ex(state), ex(applied), ex(complete),
            ex(new_applied), ex(stable),
        )

    spec = P(SHARD_AXIS)
    n_in = 17
    step = jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(spec,) * n_in,
            out_specs=(spec,) * 9,
            check_vma=False,
        )
    )
    return step
