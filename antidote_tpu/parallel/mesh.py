"""Mesh serving plane — the serving-epoch store sharded over devices.

The reference runs one ``materializer_vnode`` per ring partition and
aggregates the DC-wide stable snapshot with 1 s ``meta_data_sender``
gossip + entry-wise min (/root/reference/src/meta_data_sender.erl:224-255,
/root/reference/src/stable_time_functions.erl:51-85).  PRs 5-9 rebuilt
the serving structures — serving-epoch double buffers, the snapshot
cache, the staged wire pipeline — but all of it single-chip.  This
module is the multi-chip rendering (ROADMAP item 3 / SURVEY §7 step 6):

  * every table's arrays (and therefore the frozen serving-epoch double
    buffers cut from them) carry a ``NamedSharding`` over a one-axis
    ``jax.sharding.Mesh`` — contiguous shard blocks: device ``d`` owns
    shards ``[d*spd, (d+1)*spd)`` where ``spd = n_shards // n_devices``,
    permanently;
  * epoch-eligible wire reads launch as ROUTED per-shard gathers
    (``[P, M']`` row blocks through an explicit ``shard_map``), so each
    device gathers only its own shards' rows over ICI-free local HBM —
    the LAUNCH stage ships one program, not per-device work lists, and
    nothing is concatenated on the host until the writeback stage
    materializes the (already assembled) global array;
  * the stable/safe vector clock is a ``lax.pmin`` COLLECTIVE over the
    per-device applied clocks — the gossip rounds collapse into one ICI
    all-reduce (``stable_vc``), replacing the host-side min reduction
    for mesh-resident stores;
  * epoch publication is PER-SHARD INCREMENTAL: the freeze scatters
    each dirty shard's rows into that shard's device slice only
    (``TypedTable.freeze_serving``'s routed path), so one hot shard's
    write burst republishes its own slice, not the whole table —
    observable per shard via ``antidote_mesh_publish_total{shard}``.

GC folds and head folds were already per-shard vmapped bodies
(store/typed_table.py); with the arrays mesh-placed, XLA partitions
them across devices with no cross-device traffic on the data plane, and
the Pallas fold kernels dispatch with SHARD-LOCAL extents inside the
sharded step (``spmd.sharded_step_fn`` + ``pallas_kernels.
counter_fold_local``).

On this CPU container the mesh is the 8 virtual devices the test
harness forces (tests/conftest.py); on real TPU hardware the same code
places shards over ICI-connected chips — the pmin becomes a real
cross-chip collective and the per-shard gathers stay HBM-local.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from antidote_tpu.compat import shard_map
from antidote_tpu.materializer import longlog
from antidote_tpu.parallel.spmd import SHARD_AXIS
from antidote_tpu.store.typed_table import _shard_read_latest_body


class MeshServingPlane:
    """Placement + collectives for one store's serving plane.

    Build with the deployment config, then :meth:`attach` a
    :class:`~antidote_tpu.store.kv.KVStore` (or pass ``sharding`` into
    ``AntidoteNode`` so recovery-built tables are placed at creation,
    then attach).  ``n_shards`` must be divisible by ``n_devices`` so
    every device owns a whole number of shards — the routed [P, M']
    layouts and the pmin blocks both split on that boundary.
    """

    def __init__(self, cfg, n_devices: int | None = None, metrics=None):
        devices = jax.devices()
        n = int(n_devices) if n_devices else len(devices)
        if not 1 <= n <= len(devices):
            raise ValueError(
                f"mesh wants {n} devices; jax sees {len(devices)}"
            )
        if cfg.n_shards % n:
            raise ValueError(
                f"n_shards={cfg.n_shards} is not divisible by "
                f"{n} mesh devices: every device must own a whole "
                "number of shards"
            )
        self.cfg = cfg
        self.n_devices = n
        self.mesh = Mesh(np.array(devices[:n]), (SHARD_AXIS,))
        self.sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        #: NodeMetrics (attached with the store; may arrive later)
        self.metrics = metrics
        self.store = None
        self._pmin_fn = None
        #: last computed stable VC keyed by the applied-clock snapshot it
        #: was computed from — txn starts call stable_vc() per request,
        #: and the collective only relaunches when a commit actually
        #: advanced a clock
        self._stable_cache: "tuple | None" = None
        self._stable_lock = threading.Lock()
        #: pmin collectives actually launched (cache misses)
        self.stable_collectives = 0
        #: compiled sequence-parallel giant-key folds, keyed by
        #: (type name, cfg) — cfg is a frozen (hashable) dataclass
        self._giant_fold_fns: dict = {}
        #: giant-key folds dispatched through the mesh (node status)
        self.giant_folds = 0

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def attach(self, store) -> "MeshServingPlane":
        """Adopt ``store``: place every existing table over the mesh,
        point new-table creation at the mesh sharding, and route the
        store's stable-time computation through the pmin collective."""
        if self.metrics is None:
            self.metrics = store.metrics
        store.sharding = self.sharding
        for t in store.tables.values():
            self.place_table(t)
        store.mesh = self
        self.store = store
        if self.metrics is not None:
            self.metrics.mesh_devices.set(self.n_devices)
        return self

    def place_table(self, t) -> None:
        """Move one table's device arrays onto the mesh (idempotent).
        Frozen epoch copies cut from the old placement die with it —
        readers fall back to the locked path until the next publish."""
        if t.sharding is self.sharding:
            return
        t.sharding = self.sharding
        put = lambda x: jax.device_put(x, self.sharding)
        t.snap = {f: put(x) for f, x in t.snap.items()}
        t.head = {f: put(x) for f, x in t.head.items()}
        t.snap_vc = put(t.snap_vc)
        t.snap_seq = put(t.snap_seq)
        t.ops_a = put(t.ops_a)
        t.ops_b = put(t.ops_b)
        t.ops_vc = put(t.ops_vc)
        t.ops_origin = put(t.ops_origin)
        t.head_vc = put(t.head_vc)
        t.invalidate_epochs()

    # ------------------------------------------------------------------
    # routed epoch gathers (the LAUNCH stage's SPMD read)
    # ------------------------------------------------------------------
    def epoch_gather(self, t, head, head_vc, row_mat, vc_mat):
        """One merged frozen-head gather for a routed ``[P, M']`` batch,
        executed SPMD via an explicit ``shard_map``: each device gathers
        its own shards' rows from its local slice of the frozen epoch
        buffers and resolves them in place — no cross-device traffic,
        no host-side concat.  Returns (resolved fields [P, M', ...],
        fresh [P, M']) as device handles (the writeback stage owns the
        materialize)."""
        fn = getattr(t, "_mesh_gather_fn", None)
        if fn is None or getattr(t, "_mesh_gather_plane", None) is not self:
            fn = self._build_gather(t)
            t._mesh_gather_fn = fn
            t._mesh_gather_plane = self
        return fn(head, head_vc, row_mat, vc_mat)

    def _build_gather(self, t):
        ty, cfg = t.ty, t.cfg
        latest = _shard_read_latest_body(ty, cfg)
        spec = P(SHARD_AXIS)

        def body(head, head_vc, rows, read_vcs):
            # per-device block: [P_local, ...] — vmap the per-shard
            # gather body over the local shards, resolve in place
            state, fresh = jax.vmap(latest)(head, head_vc, rows, read_vcs)
            resolved = (
                ty.resolve(cfg, state)
                if ty.resolve_spec(cfg) is not None
                else state
            )
            return resolved, fresh

        return jax.jit(shard_map(
            body, mesh=self.mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec), check_vma=False,
        ))

    # ------------------------------------------------------------------
    # giant-key sequence sharding (ROADMAP item 6 / SURVEY §5)
    # ------------------------------------------------------------------
    def fold_giant_key(self, ty, cfg, state0, ops_a, ops_b, ops_vc,
                       ops_origin, n_ops, base_vc, read_vc):
        """Fold ONE key's over-ring op log with the op axis sharded over
        the device mesh: every device reduces its contiguous chunk of the
        sequence to a partial delta, one ``all_gather`` exchanges the
        (tiny) deltas, and the monoid tree merges them in sequence order
        — ring attention's partial-softmax exchange, rendered for the
        celebrity-key materialization (``longlog.sharded_assoc_fold_fn``).

        Host-assembled operands on the leading op axis L (e.g. from WAL
        replay): ops_a i64[L, A], ops_b i32[L, B], ops_vc i32[L, D],
        ops_origin i32[L]; ``n_ops`` = the true op count ≤ L; base_vc /
        read_vc i32[D].  Requires ``ty.supports_assoc``.  L is padded to
        a power-of-two device multiple here — padded slots sit at global
        index ≥ n_ops, so the inclusion mask drops them; the bucketing
        keeps one XLA compile family per doubling, not per log length.

        Returns (state pytree, applied) as DEVICE arrays — callers own
        the materialize (no sync here).
        """
        fn = self._giant_fold_fns.get((ty.name, cfg))
        if fn is None:
            fn = longlog.sharded_assoc_fold_fn(ty, cfg, self.mesh)
            self._giant_fold_fns[(ty.name, cfg)] = fn
        l = int(ops_vc.shape[0])
        padded = self.n_devices
        while padded < l:
            padded *= 2
        pad = padded - l

        def padl(x, dtype):
            x = np.asarray(x, dtype)  # sync-ok: host-assembled replay log
            if pad:
                x = np.concatenate(
                    [x, np.zeros((pad,) + x.shape[1:], dtype)]
                )
            return x

        self.giant_folds += 1
        return fn(
            state0,
            padl(ops_a, np.int64), padl(ops_b, np.int32),
            padl(ops_vc, np.int32), padl(ops_origin, np.int32),
            # sync-ok: host scalars/clocks from the replay cut, not
            # device arrays
            np.int32(n_ops), np.asarray(base_vc, np.int32),
            np.asarray(read_vc, np.int32),
        )

    # ------------------------------------------------------------------
    # stable time: the pmin collective
    # ------------------------------------------------------------------
    def _pmin(self):
        if self._pmin_fn is None:
            spec = P(SHARD_AXIS)

            def body(clocks):
                # local entry-wise min over this device's shards, then
                # one pmin all-reduce over the mesh axis — the ICI
                # rendering of stable_time_functions:get_min_time
                return lax.pmin(jnp.min(clocks, axis=0), SHARD_AXIS)

            self._pmin_fn = jax.jit(shard_map(
                body, mesh=self.mesh, in_specs=(spec,), out_specs=P(),
                check_vma=False,
            ))
        return self._pmin_fn

    def stable_vc(self, applied: np.ndarray | None = None) -> np.ndarray:
        """DC-wide stable snapshot as a device collective: entry-wise
        pmin over the per-device applied clocks.  Identical to the host
        reduction by construction (min is min); cached per applied-clock
        version so only clock ADVANCES pay the launch.

        ``applied`` is the caller's clock matrix — KVStore.stable_vc
        passes its OWN ``applied_vc`` so that, across a follower
        reinstall (the plane re-attaches to the fresh store before the
        txn manager swaps over), a concurrent lock-free txn start on
        the old store still computes from the old store's intact
        clocks, never the new store's zeroed ones."""
        if applied is None:
            applied = self.store.applied_vc
        with self._stable_lock:
            c = self._stable_cache
            if c is not None and np.array_equal(c[0], applied):
                return c[1].copy()
            snap = applied.copy()
        t0 = time.monotonic()
        # applied_vc is host i32 already; device_put shards it directly
        dev = jax.device_put(snap, self.sharding)
        # sync-ok: the stable-time collective's readback — a [D]-entry
        # clock vector, launched only when a commit advanced a clock
        # (cached otherwise); never on the lock-free read path
        out = np.asarray(self._pmin()(dev))
        if self.metrics is not None:
            self.metrics.mesh_stable_seconds.observe(time.monotonic() - t0)
        with self._stable_lock:
            self.stable_collectives += 1
            self._stable_cache = (snap, out)
        return out.copy()

    # ------------------------------------------------------------------
    def status(self) -> dict:
        """The node-status ``mesh`` block."""
        out = {
            "devices": self.n_devices,
            "axis": SHARD_AXIS,
            "shards_per_device": self.cfg.n_shards // self.n_devices,
            "stable_collectives": self.stable_collectives,
            "giant_folds": self.giant_folds,
        }
        m = self.metrics
        if m is not None:
            # int keys, numeric order (labels are strings internally)
            out["publish_by_shard"] = dict(sorted(
                (int(k[0]), int(v))
                for k, v in m.mesh_publish.snapshot().items()
            ))
            s = m.mesh_stable_seconds.summary()
            out["stable_pmin_us"] = {
                "count": s["count"],
                "mean_us": round(s["mean"] * 1e6, 1),
                "p99_us": round(s["p99"] * 1e6, 1),
            }
        return out
