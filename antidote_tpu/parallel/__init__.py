from antidote_tpu.parallel.mesh import MeshServingPlane
from antidote_tpu.parallel.spmd import (
    SHARD_AXIS,
    make_mesh,
    shard_axis_sharding,
    sharded_step_fn,
)

__all__ = [
    "MeshServingPlane",
    "SHARD_AXIS",
    "make_mesh",
    "shard_axis_sharding",
    "sharded_step_fn",
]
