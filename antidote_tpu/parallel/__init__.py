from antidote_tpu.parallel.spmd import make_mesh, shard_axis_sharding, sharded_step_fn

__all__ = ["make_mesh", "shard_axis_sharding", "sharded_step_fn"]
