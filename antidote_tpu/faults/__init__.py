"""Deterministic fault injection for the self-healing fabric.

The OTP reference earns its resilience claims with supervision trees and
riak_core handoff retries; this package earns ours with seeded chaos: a
:class:`FaultPlan` declares which messages die, stutter, rot, or stall at
named injection sites threaded through the inter-DC fabric
(``interdc/tcp.py``), the cluster RPC plane (``cluster/rpc.py``), the WAL
(``log/wal.py``), and the native pump load path
(``interdc/native_pump.py``).  ``tests/test_chaos.py`` drives the plans
and asserts the invariant that matters: after faults heal, every DC
converges to identical materialized snapshots with zero lost effects.

Usage::

    from antidote_tpu import faults

    plan = faults.FaultPlan(seed=42)
    plan.drop("interdc.deliver", key=(0, 1), p=0.3)   # lossy link 0->1
    inj = faults.install(plan)
    inj.sever(0, 1)       # full partition (stream + query channel)
    ...
    inj.heal_all()
    faults.uninstall()    # disarm; sites return to zero-overhead no-ops

Sites pay one module-global read when no plan is armed, so production
paths are unaffected.
"""

from antidote_tpu.faults.plan import (
    ACTIONS,
    PLAN_ENV,
    Decision,
    FaultInjector,
    FaultPlan,
    FaultRule,
    armed_prefix,
    get_injector,
    hit,
    install,
    install_from_env,
    is_severed,
    plan_from_env,
    uninstall,
)

__all__ = [
    "ACTIONS", "PLAN_ENV", "Decision", "FaultInjector", "FaultPlan",
    "FaultRule", "armed_prefix", "get_injector", "hit", "install",
    "install_from_env", "is_severed", "plan_from_env", "uninstall",
]
