"""Seeded fault plans + the process-wide injector.

The deterministic core of the chaos layer (see package docstring in
``__init__.py``): a :class:`FaultPlan` is a declarative, seeded list of
rules; :func:`install` arms it as the process-wide
:class:`FaultInjector` that instrumented sites consult.  All decisions
draw from one ``random.Random(seed)`` under a lock, so a given plan +
a deterministic delivery order (the single pump thread) reproduces the
same fault sequence run after run.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

#: actions a rule may take at a site; sites interpret them locally:
#:   drop     — discard the message / skip the operation
#:   dup      — deliver the message twice (chain logic must dedupe)
#:   delay    — defer the message one delivery round / sleep arg seconds
#:   truncate — corrupt the frame to its first ``arg`` bytes
#:   error    — raise (ConnectionError at transports, IOError at the WAL)
#:   enospc   — WAL-append site only: raise OSError(errno.ENOSPC) — a
#:              full disk; drives the node's read-only degraded mode
#:   io_error — WAL-append site only: raise OSError(errno.EIO) — a
#:              dying device; same degraded-mode path
ACTIONS = ("drop", "dup", "delay", "truncate", "error", "enospc",
           "io_error")


class Decision:
    """What a site should do for one hit: ``action`` + optional arg."""

    __slots__ = ("action", "arg", "site")

    def __init__(self, action: str, arg: Any = None, site: str = ""):
        self.action = action
        self.arg = arg
        self.site = site

    def __repr__(self):
        return f"Decision({self.action!r}, arg={self.arg!r}, site={self.site!r})"


class FaultRule:
    """One match+action rule.  ``key=None`` matches every key at the
    site; ``p`` is the per-hit firing probability; ``times`` bounds the
    total number of firings (None = unlimited)."""

    __slots__ = ("site", "action", "key", "p", "times", "arg", "fired")

    def __init__(self, site: str, action: str, key=None, p: float = 1.0,
                 times: Optional[int] = None, arg: Any = None):
        assert action in ACTIONS, action
        self.site = site
        self.action = action
        self.key = key
        self.p = float(p)
        self.times = times
        self.arg = arg
        self.fired = 0

    def matches(self, site: str, key) -> bool:
        if site != self.site:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        return self.key is None or self.key == key

    def __repr__(self):
        return (f"FaultRule({self.site!r}, {self.action!r}, key={self.key!r},"
                f" p={self.p}, times={self.times}, fired={self.fired})")


class FaultPlan:
    """A seeded, declarative set of fault rules.

        plan = FaultPlan(seed=7)
        plan.drop("interdc.deliver", key=(0, 1), p=0.3)
        plan.dup("interdc.deliver", p=0.1, times=5)
        plan.error("wal.append", times=1)
        inj = faults.install(plan)

    Known sites (grep for ``faults.hit``):

    ==================  =============================  =================
    site                key                            planes
    ==================  =============================  =================
    interdc.deliver     (publisher_dc, subscriber_dc)  TcpFabric streams
    interdc.rpc         (src_dc, target_dc)            log catch-up + query
    rpc.call            method name                    intra-DC cluster RPC
    wal.append          WAL file basename              durable log
    wal.fsync           WAL file basename              group-fsync plane
                                                       (delay stretches the
                                                       sync window; error/
                                                       enospc/io_error fail
                                                       the covering ticket)
    wal.truncate_below  WAL file basename              checkpoint reclaim
                                                       (delay holds the
                                                       deleter mid-pass;
                                                       error aborts it —
                                                       retried next ckpt)
    ckpt.write          checkpoint name (ckpt_N)       image stream (per
                                                       chunk: delay holds
                                                       the writer mid-
                                                       stream; enospc/
                                                       io_error abort the
                                                       attempt, publishing
                                                       and truncating
                                                       nothing)
    ckpt.fsync          checkpoint name                image fsync (rides
                                                       the group-fsync
                                                       coordinator)
    ckpt.rename         checkpoint name                atomic publish
                                                       rename
    ckpt.ship           checkpoint name (ckpt_N)       follower image
                                                       shipping (per
                                                       fetched chunk:
                                                       delay holds the
                                                       shipper mid-image
                                                       so chaos can kill
                                                       a follower mid-
                                                       bootstrap; error/
                                                       io_error/enospc
                                                       fail the fetch —
                                                       the follower's
                                                       bootstrap retries)
    coldtier.fault      tiered table name              cold-tier fault-in
                                                       (ISSUE 13: delay
                                                       holds the read
                                                       mid-fault-in;
                                                       error/io_error/
                                                       enospc refuse it
                                                       with a typed
                                                       ColdMiss — never
                                                       a wrong value,
                                                       the client
                                                       retries on the
                                                       hint)
    bcounter.transfer   (key, granter_dc)              escrow grant plane
                                                       (ISSUE 18: delay
                                                       stretches a grant
                                                       so chaos can kill
                                                       the granter mid-
                                                       transfer; drop/
                                                       error starve the
                                                       requester — the
                                                       at-most-once
                                                       channel never
                                                       blind-resends, the
                                                       next tick re-asks)
    native_pump.load    None                           native receive plane
    ==================  =============================  =================
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rules: List[FaultRule] = []

    def add(self, site: str, action: str, key=None, p: float = 1.0,
            times: Optional[int] = None, arg: Any = None) -> "FaultPlan":
        self.rules.append(FaultRule(site, action, key, p, times, arg))
        return self

    # -- conveniences ---------------------------------------------------
    def drop(self, site: str, key=None, p: float = 1.0,
             times: Optional[int] = None) -> "FaultPlan":
        return self.add(site, "drop", key, p, times)

    def dup(self, site: str, key=None, p: float = 1.0,
            times: Optional[int] = None) -> "FaultPlan":
        return self.add(site, "dup", key, p, times)

    def delay(self, site: str, key=None, p: float = 1.0,
              times: Optional[int] = None, seconds: float = 0.0) -> "FaultPlan":
        return self.add(site, "delay", key, p, times, arg=seconds)

    def truncate(self, site: str, key=None, p: float = 1.0,
                 times: Optional[int] = None, keep: int = 4) -> "FaultPlan":
        return self.add(site, "truncate", key, p, times, arg=keep)

    def error(self, site: str, key=None, p: float = 1.0,
              times: Optional[int] = None, message: str = "injected fault"
              ) -> "FaultPlan":
        return self.add(site, "error", key, p, times, arg=message)

    def enospc(self, site: str = "wal.append", key=None, p: float = 1.0,
               times: Optional[int] = None) -> "FaultPlan":
        """Full-disk injection on the WAL append path: the site raises
        ``OSError(errno.ENOSPC)``, flipping the node into read-only
        degraded mode until the rule stops firing (auto-recovery)."""
        return self.add(site, "enospc", key, p, times)

    def io_error(self, site: str = "wal.append", key=None, p: float = 1.0,
                 times: Optional[int] = None) -> "FaultPlan":
        """Dying-device injection on the WAL append path
        (``OSError(errno.EIO)``); same degraded-mode path as enospc."""
        return self.add(site, "io_error", key, p, times)


class FaultInjector:
    """The armed form of a plan: holds the seeded RNG, live partition
    state, per-(site, action) hit counters, and the named kill/restart
    registry for endpoints (fabric listeners, RPC servers)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.rules = list(plan.rules)
        self.counts: Dict[Tuple[str, str], int] = {}
        #: severed link pairs, stored unordered (a partition cuts both
        #: the stream and the query channel in both directions)
        self._severed: set = set()
        #: name -> (kill_fn, restart_fn) for registered endpoints
        self._endpoints: Dict[str, Tuple[Callable, Callable]] = {}
        self._lock = threading.Lock()

    # -- rule evaluation ------------------------------------------------
    def hit(self, site: str, key=None) -> Optional[Decision]:
        """Evaluate the site against the plan; None means proceed
        normally.  The FIRST matching rule that fires wins."""
        with self._lock:
            for rule in self.rules:
                if not rule.matches(site, key):
                    continue
                if rule.p < 1.0 and self.rng.random() >= rule.p:
                    continue
                rule.fired += 1
                ck = (site, rule.action)
                self.counts[ck] = self.counts.get(ck, 0) + 1
                self._count_metric(site, rule.action)
                return Decision(rule.action, rule.arg, site)
        return None

    def _count_metric(self, site: str, action: str) -> None:
        try:
            from antidote_tpu.obs.metrics import net_metrics

            net_metrics().faults_injected.inc(site=site, action=action)
        except Exception:  # metrics must never break injection
            pass

    def fired(self, site: str, action: Optional[str] = None) -> int:
        """Total decisions taken at a site (optionally one action)."""
        with self._lock:
            return sum(n for (s, a), n in self.counts.items()
                       if s == site and (action is None or a == action))

    # -- partitions -----------------------------------------------------
    def sever(self, a: int, b: int) -> None:
        """Cut the link between two DCs (both directions, both the
        stream and the query channel)."""
        with self._lock:
            self._severed.add(frozenset((a, b)))
        log.info("faults: severed link %s <-> %s", a, b)

    def heal(self, a: int, b: int) -> None:
        with self._lock:
            self._severed.discard(frozenset((a, b)))
        log.info("faults: healed link %s <-> %s", a, b)

    def heal_all(self) -> None:
        with self._lock:
            self._severed.clear()
        log.info("faults: all links healed")

    def is_severed(self, a, b) -> bool:
        if not self._severed:
            return False
        return frozenset((a, b)) in self._severed

    # -- endpoint kill/restart -----------------------------------------
    def register_endpoint(self, name: str, kill: Callable[[], None],
                          restart: Callable[[], None]) -> None:
        """Transports self-register their listeners here so chaos
        drivers can crash and revive them by name."""
        with self._lock:
            self._endpoints[name] = (kill, restart)

    def endpoints(self) -> List[str]:
        with self._lock:
            return sorted(self._endpoints)

    def kill(self, name: str) -> None:
        kill, _ = self._endpoints[name]
        log.info("faults: killing endpoint %r", name)
        kill()

    def restart(self, name: str) -> None:
        _, restart = self._endpoints[name]
        log.info("faults: restarting endpoint %r", name)
        restart()


#: env var carrying a JSON fault plan for SUBPROCESS chaos: entrypoints
#: that cannot be reached by an in-process ``install`` (console serve
#: children the chaos suite SIGKILLs) arm it at boot via
#: :func:`install_from_env`.  Shape:
#:   {"seed": 7, "rules": [{"site": "ckpt.write", "action": "delay",
#:                          "key": null, "p": 1.0, "times": null,
#:                          "arg": 0.05}, ...]}
PLAN_ENV = "ANTIDOTE_FAULT_PLAN"


def plan_from_env() -> Optional[FaultPlan]:
    """Parse :data:`PLAN_ENV` into a FaultPlan (None when unset).  A
    malformed spec raises — a chaos run silently proceeding WITHOUT its
    faults would green-light untested behavior."""
    import json
    import os

    raw = os.environ.get(PLAN_ENV)
    if not raw:
        return None
    spec = json.loads(raw)
    plan = FaultPlan(seed=int(spec.get("seed", 0)))
    for r in spec.get("rules", []):
        key = r.get("key")
        if isinstance(key, list):
            key = tuple(key)
        plan.add(r["site"], r["action"], key=key,
                 p=float(r.get("p", 1.0)), times=r.get("times"),
                 arg=r.get("arg"))
    return plan


def install_from_env() -> Optional[FaultInjector]:
    """Arm the env-declared plan, if any (subprocess chaos hook)."""
    plan = plan_from_env()
    if plan is None:
        return None
    log.warning("arming fault plan from %s: %d rule(s), seed %d",
                PLAN_ENV, len(plan.rules), plan.seed)
    return install(plan)


# ---------------------------------------------------------------------------
# process-wide installation
# ---------------------------------------------------------------------------
_ACTIVE: Optional[FaultInjector] = None


def install(plan: FaultPlan) -> FaultInjector:
    """Arm a plan process-wide; returns the injector (also reachable via
    :func:`get_injector`).  Replaces any previously installed plan."""
    global _ACTIVE
    _ACTIVE = FaultInjector(plan)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def get_injector() -> Optional[FaultInjector]:
    return _ACTIVE


def hit(site: str, key=None) -> Optional[Decision]:
    """Site-side fast path: one global read when no plan is armed."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.hit(site, key)


def is_severed(a, b) -> bool:
    inj = _ACTIVE
    if inj is None:
        return False
    return inj.is_severed(a, b)


def armed_prefix(prefix: str) -> bool:
    """True when ANY armed rule targets a site under ``prefix`` — the
    native front-end consults this at server start: with a
    ``frontend.*`` rule armed it disables its in-C++ fast-serve path so
    every frame crosses to Python, where the rule actually fires (a
    natively-served hit would otherwise dodge the chaos plan)."""
    inj = _ACTIVE
    if inj is None:
        return False
    with inj._lock:
        return any(r.site.startswith(prefix) for r in inj.rules)
