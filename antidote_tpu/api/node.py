"""AntidoteNode — the public API facade.

The surface of ``antidote.erl`` (/root/reference/src/antidote.erl:36-54):
static & interactive transactions, typed bound objects, hook registration —
over one replica's TransactionManager + KVStore.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional, Sequence

import numpy as np

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import is_type
from antidote_tpu.store.kv import KVStore
from antidote_tpu.txn.manager import (
    AbortError,
    Transaction,
    TransactionManager,
    Update,
)

BoundObject = Any


class AntidoteNode:
    """One replica ("DC") of the store.

    ``dc_id`` is the dense clock lane of this replica (the dcid→lane
    registry replacing Antidote's dict VCs keyed by dcid).
    """

    def __init__(
        self,
        cfg: Optional[AntidoteConfig] = None,
        dc_id: int = 0,
        sharding=None,
        cert: bool = True,
        log_dir: Optional[str] = None,
        recover: bool = False,
        meta=None,
        store: Optional[KVStore] = None,
        resident_rows: int = 0,
        cold_fault_rate_cap: float = 0.0,
    ):
        """``store`` adopts an existing KVStore (e.g. the output of
        ``handoff.reshard``) instead of building one; ``log_dir`` must be
        None then — the adopted store keeps its own log."""
        if store is not None and cfg is None:
            cfg = store.cfg
        self.cfg = cfg or AntidoteConfig()
        self.dc_id = dc_id
        # durable, DC-replicated metadata/flag store (stable_meta_data_server)
        if meta is None:
            from antidote_tpu.meta import MetaDataStore

            meta = MetaDataStore()
        self.meta = meta
        log = None
        if store is not None:
            assert log_dir is None, "store= and log_dir= are exclusive"
            if recover:
                raise RuntimeError(
                    "store= adopts already-populated tables; recover=True "
                    "would replay its log on top of them (double-apply)"
                )
            log = store.log
        elif log_dir is not None and self.cfg.enable_logging:
            import glob
            import os

            from antidote_tpu.log import LogManager
            from antidote_tpu.log.checkpoint import has_checkpoints

            # a published checkpoint carries committed data even when
            # every WAL file below its floor was reclaimed — such a dir
            # must recover, never boot fresh over the image
            has_data = any(
                os.path.getsize(p) > 0
                for p in glob.glob(os.path.join(log_dir, "shard_*.wal"))
            ) or has_checkpoints(log_dir)
            if has_data and not recover:
                # appending to an existing log with fresh counters would
                # mint duplicate (commit counter, origin) dots — corruption
                raise RuntimeError(
                    f"log_dir {log_dir!r} contains existing WAL data; pass "
                    "recover=True (or point at an empty directory)"
                )
            log = LogManager(
                self.cfg, log_dir,
                sync_on_commit=self.meta.get_env("sync_log",
                                                 self.cfg.sync_log),
            )
        elif recover:
            raise RuntimeError(
                "recover=True requires log_dir and cfg.enable_logging"
            )
        self.store = store if store is not None else KVStore(
            self.cfg, sharding=sharding, log=log
        )
        self.txm = TransactionManager(
            self.store, my_dc=dc_id,
            cert=self.meta.get_env("txn_cert", cert),
            protocol=self.meta.get_env("txn_prot", "clocksi"),
        )
        from antidote_tpu.obs import NodeMetrics, install_error_monitor

        #: prometheus-parity metric set (antidote_stats_collector, SURVEY §2.7)
        self.metrics = NodeMetrics()
        self.txm.metrics = self.metrics
        # snapshot-cache / serving-epoch counters land in the same registry
        self.store.metrics = self.metrics
        if self.store.log is not None:
            # group-fsync coordinator -> antidote_wal_fsync_batch
            self.store.log.on_fsync_batch = (
                self.metrics.wal_fsync_batch.observe)
        # count this package's ERROR-level log records (antidote_error_monitor)
        self._error_handler = install_error_monitor(
            self.metrics, logging.getLogger("antidote_tpu")
        )
        self._metrics_server = None
        if store is not None:
            # adopted (already-populated) store: continue the commit
            # counter above every applied clock so new commits never mint
            # duplicate (counter, origin) dots
            self.txm.commit_counter = int(self.store.dc_max_vc()[dc_id])
        #: background checkpoint writer (ISSUE 8); started by
        #: start_checkpointer (console serve) or lazily by checkpoint_now
        self.checkpointer = None
        import threading as _threading

        self._ckpt_init_lock = _threading.Lock()
        #: extras blobs restored from the checkpoint image (membership
        #: state etc.) for attached subsystems to consult
        self.checkpoint_extras: dict = {}
        #: name -> provider of extra state to embed in checkpoint images
        #: (cluster members register their membership snapshot here);
        #: shared with the Checkpointer so late registrations are seen
        self.checkpoint_extras_providers: dict = {}
        # --- cold tier (ISSUE 13): attach BEFORE recovery so a chain
        # image's cold_directory can register fault-in refs and the tail
        # replay stays under the resident budget
        if resident_rows > 0 and self.store.cold is None:
            # enable_cold_tier raises without a durable log — the
            # explicitly-requested residency bound must never be a
            # silent no-op
            self.enable_cold_tier(resident_rows, cold_fault_rate_cap)
        if recover and log is not None:
            # node restart (check_node_restart,
            # /root/reference/src/inter_dc_manager.erl:156-206).  Fast
            # path (ISSUE 8/13): compose the newest verifiable FULL
            # checkpoint image with its parent-linked delta chain, then
            # replay only the WAL tail above the last good link's floor;
            # the full-log replay remains the no-checkpoint fallback and
            # the semantics oracle (both rebuild certification +
            # counters).  A corrupt mid-chain link truncates the
            # composition — the tail above the surviving prefix is still
            # on disk (reclaim never passes the retained fulls' floors).
            from antidote_tpu.log import checkpoint as _ckpt

            rlog = logging.getLogger("antidote_tpu.recovery")
            t0 = time.monotonic()
            loaded = _ckpt.load_chain(log_dir)
            if loaded is not None:
                image, manifest, deltas = loaded
                summary = _ckpt.install_image(self.store, self.txm, image)
                self.checkpoint_extras = image.get("extras", {}) or {}
                if summary["cold_directory"]:
                    # beyond-RAM image: the cold keys get NO device row —
                    # reads fault them in against this image's sidecar
                    if self.store.cold is None:
                        self.enable_cold_tier(0, cold_fault_rate_cap)
                    self.store.cold.seed(summary["cold_directory"],
                                         int(manifest["id"]))
                if self.store.cold is not None \
                        and (manifest.get("cold") is not None):
                    # resident keys' image coords double as evict hints
                    # (their rows ARE the sidecar rows) — the budget
                    # pass below and the commit path both need them
                    self.store.cold.seed_hints(int(manifest["id"]))
                for delta, dman in deltas:
                    ds = _ckpt.install_delta(self.store, self.txm, delta)
                    self.checkpoint_extras.update(
                        delta.get("extras", {}) or {})
                    rlog.info(
                        "recovery chain link %d: %d rows, %d keys, "
                        "%d evicted", ds["id"], ds["rows"], ds["keys"],
                        ds["evicted"])
                ckpt_s = time.monotonic() - t0
                self.metrics.recovery_seconds.set(ckpt_s,
                                                  phase="checkpoint")
                rlog.info(
                    "recovery phase checkpoint: image %d + %d chain "
                    "link(s) (%d keys, %d rows, %d tables%s, %d cold) "
                    "installed in %.2f s",
                    summary["id"], len(deltas), summary["keys"],
                    summary["rows"], summary["tables"],
                    (f", dropped shards {summary['dropped_shards']}"
                     if summary["dropped_shards"] else ""),
                    len(summary["cold_directory"]), ckpt_s,
                )
            t1 = time.monotonic()
            last = self.store.recover(track_origin=dc_id)
            self.txm.committed_keys.update(last)
            self.txm.commit_counter = int(self.store.dc_max_vc()[dc_id])
            tail_s = time.monotonic() - t1
            n_tail = int(getattr(self.store, "last_recovery_records", 0))
            self.metrics.recovery_seconds.set(tail_s, phase="tail")
            self.metrics.recovery_records.inc(n_tail)
            rlog.info(
                "recovery phase tail: %d record(s) replayed in %.2f s "
                "(total %.2f s, %s)",
                n_tail, tail_s, time.monotonic() - t0,
                "checkpoint + tail" if loaded is not None
                else "full replay — no checkpoint found",
            )
            if self.store.cold is not None \
                    and self.store.cold.budget > 0:
                # a beyond-RAM restart re-enforces the resident budget
                # BEFORE serving: rows the installed image covers (and
                # the tail left untouched) go straight back cold
                n_ev = self.store.cold.enforce_budget()
                if n_ev:
                    rlog.info("recovery cold tier: %d row(s) re-evicted "
                              "to the resident budget (%d)", n_ev,
                              self.store.cold.budget)
        # react to replicated flag flips from ANY node in the DC
        # (registered last: construction-time get_env seeds fire watchers)
        self.meta.watch(self._on_meta_change)

    # --- cold tier (ISSUE 13) -------------------------------------------
    def enable_cold_tier(self, resident_rows: int = 0,
                         fault_rate_cap: float = 0.0):
        """Attach the cold tier: device residency bounded by
        ``resident_rows`` (0 = unbounded; fault-in only), fault-ins past
        ``fault_rate_cap``/s refused with a typed ColdMiss.  Requires a
        durable log (the cold state lives in checkpoint sidecars)."""
        if self.store.log is None:
            raise RuntimeError("the cold tier requires log_dir (cold "
                               "rows live in checkpoint sidecars)")
        if self.store.cold is None:
            from antidote_tpu.store.coldtier import ColdTier

            self.store.cold = ColdTier(
                self.store, budget=resident_rows,
                fault_rate_cap=fault_rate_cap, lock=self.txm.commit_lock,
            )
            cp = self.checkpointer
            if cp is not None:
                self.store.cold.on_pressure = cp.request
                self.store.cold.on_corrupt = cp._on_cold_corrupt
        else:
            self.store.cold.budget = int(resident_rows)
            self.store.cold.fault_rate_cap = float(fault_rate_cap)
        return self.store.cold

    # --- checkpointing (ISSUE 8) ----------------------------------------
    def start_checkpointer(self, interval_s: float = 300.0,
                           retain: int = 2, rebase_every: int = 8,
                           scrub_every_s: float = 0.0):
        """Attach (and, for ``interval_s`` > 0, start) the background
        checkpoint writer.  Requires a durable log.  Idempotent and
        race-safe: CHECKPOINT_NOW is served outside the wire dispatch
        lock, so two concurrent admin calls must not construct two
        checkpointers racing over the same image ids."""
        if self.store.log is None:
            raise RuntimeError("checkpointing requires log_dir (a durable "
                               "WAL to stamp floors into)")
        with self._ckpt_init_lock:
            if self.checkpointer is None:
                from antidote_tpu.log.checkpoint import Checkpointer

                cp = Checkpointer(
                    self.store, self.txm, metrics=self.metrics,
                    interval_s=interval_s, retain=retain,
                    rebase_every=rebase_every,
                    scrub_every_s=scrub_every_s,
                )
                cp.extras_providers = self.checkpoint_extras_providers
                cp.start()
                self.checkpointer = cp
        return self.checkpointer

    def checkpoint_now(self, full: Optional[bool] = None) -> dict:
        """Run one synchronous checkpoint cycle (stamp, stream, publish,
        reclaim); returns the published manifest summary.  ``full``
        forces a rebase (True) or a delta link (False); None lets the
        chain cadence decide."""
        if self.checkpointer is None:
            self.start_checkpointer(interval_s=0.0)
        return self.checkpointer.checkpoint_now(full=full)

    # --- readiness (wait_init, /root/reference/src/wait_init.erl:50-88) --
    def check_ready(self) -> dict:
        """Probe every subsystem; returns {probe: bool}.  All-true means
        the node can serve traffic (the reference's check_ready polls
        clocksi tables + read servers + materializer + stable meta)."""
        probes = {}
        probes["types"] = bool(is_type("counter_pn"))
        try:
            probes["meta"] = self.meta.get_env("txn_prot", "clocksi") in (
                "clocksi", "gr")
        except Exception:
            probes["meta"] = False
        try:
            self.store.stable_vc()
            probes["clocks"] = True
        except Exception:
            probes["clocks"] = False
        if self.store.log is not None:
            try:
                self.store.log.commit_barrier([0])
                probes["log"] = True
            except Exception:
                probes["log"] = False
        else:
            probes["log"] = True  # ephemeral mode: nothing to probe
        metrics, self.txm.metrics = self.txm.metrics, None
        try:
            # full txn machinery + device round trip, then rolled back —
            # also warms the jit caches (first TPU compile is ~20-40 s,
            # better here than on the first client request).  Metrics are
            # detached so health polling never skews op/abort dashboards;
            # the aborted probe txn binds no rows (reads of never-written
            # keys allocate nothing, commits never happen).
            txn = self.start_transaction()
            self.update_objects(
                [("__ready__", "counter_pn", "__ready__", ("increment", 1))],
                txn)
            self.read_objects([("__ready__", "counter_pn", "__ready__")], txn)
            self.abort_transaction(txn)
            probes["txn"] = True
        except Exception:
            logging.getLogger("antidote_tpu").exception("readiness probe")
            probes["txn"] = False
        finally:
            self.txm.metrics = metrics
        return probes

    def is_ready(self) -> bool:
        return all(self.check_ready().values())

    def status(self, include_ready: bool = False) -> dict:
        """Operator-facing snapshot (the console's `status` command).

        Passive by default — ``include_ready=True`` additionally runs the
        full readiness probe (a device round trip + WAL barrier), which is
        too heavy for high-frequency monitoring polls."""
        stable = self.store.stable_vc()
        out = {
            "dc_id": self.dc_id,
            "n_shards": self.cfg.n_shards,
            "max_dcs": self.cfg.max_dcs,
            "protocol": self.txm.protocol,
            "certification": self.txm.cert,
            "stable_vc": [int(x) for x in stable],
            "commit_counter": int(self.txm.commit_counter),
            "keys": len(self.store.directory),
            "tables": {
                t: {"rows_used": int(tab.used_rows.sum()),
                    "n_rows": tab.n_rows}
                for t, tab in self.store.tables.items()
            },
            "durable": self.store.log is not None,
        }
        if self.store.mesh is not None:
            # mesh serving plane (ISSUE 10): device count, per-shard
            # publish rows, stable-collective latency
            out["mesh"] = self.store.mesh.status()
        # fabric/RPC resilience counters (process-wide; see NetMetrics):
        # operators watch these to see partitions heal and retries drain
        from antidote_tpu.obs.metrics import net_metrics

        out["net"] = {k: v for k, v in net_metrics().snapshot().items()
                      if v}
        # overload/degradation view (PR 4): every bound and shed is
        # visible here and on /metrics — a wedged-looking node should
        # explain itself from one status call
        shed = {
            plane[0]: v
            for plane, v in sorted(self.metrics.shed.snapshot().items())
            if v
        }
        out["overload"] = {
            "read_only": self.txm.read_only_reason,
            "commit_backlog": self.txm._commit_backlog,
            "max_commit_backlog": self.txm.max_commit_backlog,
            "shed": shed,
        }
        # escrow economy (ISSUE 18): typed bounded-counter refusals,
        # queued shortfall, and the rights-transfer traffic this node
        # has driven/served — the zero-oversell plane's one-call view
        out["escrow"] = dict(
            self.txm.bcounters.status(),
            grants={
                role[0]: int(v) for role, v in sorted(
                    self.metrics.escrow_grants.snapshot().items()) if v
            },
        )
        # write plane (ISSUE 6): merge width, group-fsync batching,
        # per-segment durability debt, bypass counts — the knobs table
        # in docs/operations.md explains how to read these
        def _hist(h):
            s = h.summary()
            return {"count": s["count"], "mean": round(s["mean"], 2),
                    "p50": s["p50"], "p99": s["p99"]}

        wlog = self.store.log
        out["write_plane"] = {
            "merge_width": _hist(self.metrics.commit_merge_width),
            "fsync_batch": _hist(self.metrics.wal_fsync_batch),
            "cert_bypass_total": int(self.metrics.cert_bypass.value()),
            "sync_log": (bool(wlog.wals[0].sync_on_commit)
                         if wlog is not None else None),
            "wal_segments": wlog.n_segments if wlog is not None else 0,
            "segment_depth_bytes": (wlog.segment_depths()
                                    if wlog is not None else []),
        }
        # checkpoint / fast-restart view (ISSUE 8): last published image
        # stamp, size, age, and how much tail a crash-now restart would
        # replay; reads from disk when no checkpointer is attached so a
        # passive status poll still sees the inherited image
        if wlog is not None:
            if self.checkpointer is not None:
                out["checkpoint"] = self.checkpointer.status()
            else:
                from antidote_tpu.log import checkpoint as _ckpt

                cks = _ckpt.list_checkpoints(
                    _ckpt.checkpoint_root(wlog.dir))
                blk = {
                    "interval_s": 0,
                    "tail_records": int(
                        (wlog.seqs - wlog.floor_seqs).sum()),
                }
                if cks:
                    m = _ckpt.load_manifest(cks[-1][1]) or {}
                    blk.update({
                        "last_id": m.get("id"),
                        "stamp_vc_max": m.get("stamp_vc_max"),
                        "image_bytes": m.get("image_bytes"),
                        "age_s": round(
                            time.time() - m.get("created_at", 0), 1),
                    })
                out["checkpoint"] = blk
        if self.store.cold is not None:
            # cold tier (ISSUE 13): residency vs budget, fault/evict
            # counters, anchor image — the beyond-RAM health view
            out["cold_tier"] = self.store.cold.status()
        if include_ready:
            out["ready"] = self.check_ready()
        return out

    # --- shard handoff (riak_core handoff receiver) ---------------------
    def receive_handoff(self, pkg, shard: Optional[int] = None) -> None:
        """Install an exported shard package (see store/handoff.py) and
        re-sync the commit counter above every imported clock, so this
        node's own-lane snapshots cover the moved commits."""
        from antidote_tpu.store import handoff as _handoff

        _handoff.import_shard(self.store, pkg, shard)
        if pkg.get("compacted"):
            # SYNCHRONOUS import-then-checkpoint barrier (ISSUE 9
            # satellite, closing the PR-7 residual): the source's WAL was
            # checkpoint-truncated, so the package's ride-along log holds
            # only the tail — this node's WAL cannot rebuild the imported
            # rows' pre-checkpoint history, and the in-memory chain floor
            # installed above is not durable either.  The old
            # nudge-the-checkpointer left a window where a crash lost the
            # moved rows' pre-checkpoint state silently; now the import
            # does not RETURN (and therefore the two-phase move's confirm
            # and the source's relinquish cannot proceed) until a local
            # image covers the moved rows.  A failed checkpoint fails the
            # import loudly — the source keeps the shard.
            if self.store.log is not None:
                summary = self.checkpoint_now()
                logging.getLogger("antidote_tpu").info(
                    "compacted-source shard import sealed by local "
                    "checkpoint %s (import-then-checkpoint barrier)",
                    summary.get("id"),
                )
            else:
                logging.getLogger("antidote_tpu").warning(
                    "imported a shard from a checkpoint-compacted source "
                    "into a LOG-LESS node: there is no durable history "
                    "for the moved rows at all (ephemeral mode)"
                )
        self.txm.commit_counter = max(
            self.txm.commit_counter,
            int(self.store.dc_max_vc()[self.dc_id]),
        )
        # rebuild the certification table for the moved keys: their last
        # own-lane commit is the head clock's own lane (same role as the
        # recover path's track_origin scan) — without this, a txn whose
        # snapshot predates the import could overwrite a moved commit
        # unchecked (first-committer-wins violation)
        from antidote_tpu.store.kv import freeze_key

        for key, bucket, tname, row in pkg["directory"]:
            lane = int(pkg["tables"][tname]["head_vc"][row][self.dc_id])
            if lane:
                dk = (freeze_key(key), bucket)
                self.txm.committed_keys[dk] = max(
                    self.txm.committed_keys.get(dk, 0), lane
                )

    # --- transactions (antidote.erl:36-54) -----------------------------
    def start_transaction(self, clock=None, props=None) -> Transaction:
        return self.txm.start_transaction(clock, props)

    def read_objects(self, objects: Sequence, txn: Optional[Transaction] = None,
                     clock=None):
        if txn is not None:
            return self.txm.read_objects(objects, txn)
        return self.txm.read_objects_static(objects, clock)

    def update_objects(self, updates: Sequence[Update],
                       txn: Optional[Transaction] = None, clock=None):
        if txn is not None:
            self.txm.update_objects(updates, txn)
            return None
        return self.txm.update_objects_static(updates, clock)

    def commit_transaction(self, txn: Transaction) -> np.ndarray:
        return self.txm.commit_transaction(txn)

    def abort_transaction(self, txn: Transaction) -> None:
        self.txm.abort_transaction(txn)

    def get_log_operations(self, object_clock_pairs: Sequence) -> list:
        """Logged update operations newer than a snapshot time, per object
        (``antidote:get_log_operations``,
        /root/reference/src/antidote.erl:69-90).

        ``object_clock_pairs`` is ``[((key, type, bucket), clock), ...]``;
        ``clock`` is a dense VC (``None`` = all ops).  Returns one list per
        object of ``(opid, op)`` dicts where ``op`` carries the origin
        lane, commit VC, and decoded effect — an op is included iff its
        commit VC is NOT dominated by the given clock (the reference's
        ``get_from_time`` newer-than filter,
        /root/reference/src/logging_vnode.erl:194-200).
        """
        from antidote_tpu.store.kv import effect_from_rec, freeze_key
        from antidote_tpu.store.kv import key_to_shard

        log = self.store.log
        if log is None:
            raise RuntimeError("get_log_operations requires a durable log "
                               "(node started with log_dir)")
        wanted: dict = {}  # (shard) -> [(out_idx, key, type, bucket, vc)]
        for i, ((key, type_name, bucket), clock) in enumerate(
                object_clock_pairs):
            key = freeze_key(key)
            shard = key_to_shard(key, bucket, self.cfg.n_shards)
            vc = None
            if clock is not None:
                vc = np.zeros(self.cfg.max_dcs, np.int64)
                clock = np.asarray(clock, np.int64)
                vc[: len(clock)] = clock[: self.cfg.max_dcs]
            wanted.setdefault(shard, []).append(
                (i, key, type_name, bucket, vc))
        out: list = [[] for _ in object_clock_pairs]
        for shard, items in wanted.items():
            by_obj: dict = {}  # an object may be asked at several clocks
            for i, k, t, b, vc in items:
                by_obj.setdefault((k, t, b), []).append((i, vc))
            for rec in log.replay_shard(shard):  # one scan per shard
                hits = by_obj.get((freeze_key(rec["k"]), rec["t"], rec["b"]))
                if hits is None:
                    continue
                rec_vc = np.zeros(self.cfg.max_dcs, np.int64)
                rv = np.asarray(rec["vc"], np.int64)
                rec_vc[: len(rv)] = rv[: self.cfg.max_dcs]
                for i, vc in hits:
                    if vc is not None and (rec_vc <= vc).all():
                        continue  # op already in the given snapshot
                    out[i].append((int(rec["id"]), {
                        "origin": int(rec["o"]),
                        "commit_vc": rec_vc,
                        "effect": effect_from_rec(rec),
                    }))
        return out

    # --- hooks (antidote.erl register_pre/post_hook) -------------------
    def register_pre_hook(self, bucket: str, fn) -> None:
        self.txm.hooks.register_pre_hook(bucket, fn)

    def register_post_hook(self, bucket: str, fn) -> None:
        self.txm.hooks.register_post_hook(bucket, fn)

    def unregister_hook(self, kind: str, bucket: str) -> None:
        self.txm.hooks.unregister_hook(kind, bucket)

    # --- introspection -------------------------------------------------
    @staticmethod
    def is_type(type_name: str) -> bool:
        return is_type(type_name)

    def stable_vc(self) -> np.ndarray:
        return self.store.stable_vc()

    def set_sync_log(self, sync: bool) -> None:
        """Flip fsync-on-commit DC-wide (replicated runtime flag;
        /root/reference/src/logging_vnode.erl:256-258).  The broadcast
        reaches every member node's watcher, which applies it to its
        running log."""
        self.meta.set_env("sync_log", sync)

    def _on_meta_change(self, key: str, value) -> None:
        if key == "env:sync_log" and self.store.log is not None:
            self.store.log.set_sync(bool(value))
        elif key == "env:txn_cert":
            self.txm.cert = bool(value)

    # --- observability (elli /metrics on :3001 in the reference,
    #     /root/reference/src/antidote_sup.erl:118-128) ------------------
    def serve_metrics(self, port: Optional[int] = None):
        from antidote_tpu.obs import MetricsServer
        from antidote_tpu.obs.server import DEFAULT_METRICS_PORT

        if port is None:
            port = DEFAULT_METRICS_PORT
        if self._metrics_server is not None:
            if port not in (0, self._metrics_server.port):
                raise RuntimeError(
                    f"metrics already served on port "
                    f"{self._metrics_server.port}, not {port}"
                )
            return self._metrics_server
        self._metrics_server = MetricsServer(self.metrics.registry, port=port)
        return self._metrics_server


__all__ = ["AntidoteNode", "AbortError"]
