from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.txn.manager import AbortError

__all__ = ["AntidoteNode", "AbortError"]
