"""TCP inter-DC fabric — real sockets between replicas.

The reference's two inter-DC channels (SURVEY §5) were ZeroMQ sockets:
PUB/SUB for the txn stream (port 8086, /root/reference/src/inter_dc_pub.erl)
and REQ/XREP for log catch-up + bcounter transfers (port 8085,
/root/reference/src/inter_dc_query.erl).  ``TcpFabric`` reproduces both
over plain TCP with the same length-prefixed framing as the client
protocol: each DC runs one endpoint socket; peers open one connection for
the subscription stream (server pushes frames) and one for synchronous
queries.

Interface-compatible with ``LoopbackHub``: incoming stream messages are
queued and delivered on ``pump()`` so replica state is only touched from
the control thread; query/request handlers run on server threads under the
DC's handler lock (the same single-writer discipline the vnode processes
gave the reference).
"""

from __future__ import annotations

import collections
import logging
import queue
import random
import socket
import socketserver
import time
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

from antidote_tpu import faults
from antidote_tpu.obs.metrics import net_metrics

log = logging.getLogger(__name__)

_HDR = struct.Struct(">IB")
K_SUB, K_PUSH, K_LOGQ, K_REQ, K_REPLY, K_ERR = 1, 2, 3, 4, 5, 6
#: native-pump sentinel (pump.cc K_CONN_DROP): a subscription stream
#: died; resubscribe instead of delivering
K_CONN_DROP = 0


def _send(sock, kind: int, body) -> None:
    payload = msgpack.packb(body, use_bin_type=True)
    sock.sendall(_HDR.pack(len(payload) + 1, kind) + payload)


def _recv(sock) -> Tuple[int, object]:
    hdr = _read_exact(sock, _HDR.size)
    n, kind = _HDR.unpack(hdr)
    payload = _read_exact(sock, n - 1)
    return kind, msgpack.unpackb(payload, raw=False, strict_map_key=False)


def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _hard_close(sock) -> None:
    """shutdown + close.  A bare close() while ANOTHER thread is parked
    in recv() on the socket never sends the FIN — the in-flight syscall
    keeps the kernel file alive — so the peer would never learn the
    connection died.  shutdown() tears the stream down immediately and
    wakes the parked reader."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _Subscriber:
    """One subscription stream's sender side: a BOUNDED outbox drained
    by a dedicated writer thread.

    The old design wrote frames synchronously from ``push()`` under a
    per-stream lock — one stalled subscriber could park the publisher's
    COMMIT path for the full SO_SNDTIMEO window, and the kernel socket
    buffer was the only bound.  Now ``push()`` never blocks: past the
    outbox cap the frame is dropped for THIS subscriber only (counted in
    ``antidote_interdc_egress_window_drops_total``) and the subscriber
    heals through the opid-gap catch-up path — the same repair that
    covers a severed link, so a lagging peer costs a bounded outbox, not
    unbounded publisher memory."""

    #: frames parked per lagging subscriber before drops begin; sized so
    #: a normal pump hiccup (GC pause, one slow device launch) rides
    #: through, while a wedged peer caps out in ~1 MB of small frames
    OUTBOX_MAX = 1024
    _CLOSE = object()

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.outbox: "queue.Queue" = queue.Queue(maxsize=self.OUTBOX_MAX)
        self._thread: Optional[threading.Thread] = None

    def start(self, on_dead: Callable[["_Subscriber"], None]) -> None:
        self._thread = threading.Thread(
            target=self._writer, args=(on_dead,), daemon=True,
            name=f"interdc-egress:{self.sock.fileno()}")
        self._thread.start()

    def _writer(self, on_dead) -> None:
        while True:
            data = self.outbox.get()
            if data is self._CLOSE:
                return
            try:
                _send(self.sock, K_PUSH, data)
            except OSError:  # dead, or stalled past SO_SNDTIMEO
                on_dead(self)
                return

    def offer(self, data: bytes) -> bool:
        """Queue one frame; False = outbox full, frame dropped."""
        try:
            self.outbox.put_nowait(data)
            return True
        except queue.Full:
            return False

    def stop(self) -> None:
        try:
            self.outbox.put_nowait(self._CLOSE)
        except queue.Full:
            pass  # writer will exit on the closed socket's send error


class _Endpoint:
    """One DC's listening side: accepts subscriber streams and queries."""

    def __init__(self, fabric: "TcpFabric", dc_id: int, host: str, port: int):
        self.fabric = fabric
        self.dc_id = dc_id
        self.lock = threading.RLock()          # guards handler invocations
        self.query_handler: Optional[Callable] = None
        self.request_handler: Optional[Callable] = None
        #: live subscription streams (each a _Subscriber with its own
        #: bounded outbox + writer thread); _subs_lock guards membership
        self._subs: List[_Subscriber] = []
        self._subs_lock = threading.Lock()
        #: live query/request connections (server side): close() must
        #: shut these down too, or a killed endpoint would keep serving
        #: RPCs through parked handler threads
        self._queries: set = set()
        ep = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    kind, body = _recv(self.request)
                except (ConnectionError, OSError):
                    return
                if kind == K_SUB:
                    # a send-only timeout (SO_SNDTIMEO) bounds how long
                    # one stalled subscriber can wedge its WRITER THREAD
                    # (not the publisher — push() never blocks); reads
                    # (the park loop below) are unaffected
                    self.request.setsockopt(
                        socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        struct.pack("ll", 10, 0),
                    )
                    # register BEFORE the ack, start the writer AFTER it:
                    # a publish racing registration only enqueues, and the
                    # outbox preserves order — so the ack is always the
                    # stream's first frame, and once subscribe() returns
                    # every later publish sees the subscriber
                    # (observe_dcs_sync semantics,
                    # /root/reference/src/inter_dc_manager.erl:209-230)
                    entry = _Subscriber(self.request)
                    with ep._subs_lock:
                        ep._subs.append(entry)
                    try:
                        _send(self.request, K_REPLY, "subscribed")
                    except OSError:
                        ep._drop_sub(entry)
                        return
                    entry.start(ep._drop_sub)
                    # park until the peer closes (reads detect EOF)
                    try:
                        while self.request.recv(1):
                            pass
                    except OSError:
                        pass
                    ep._drop_sub(entry)
                    entry.stop()
                    return
                # query connection: serve request/reply until EOF
                with ep._subs_lock:
                    ep._queries.add(self.request)
                try:
                    while True:
                        try:
                            reply = ep._serve(kind, body)
                            _send(self.request, K_REPLY, reply)
                            kind, body = _recv(self.request)
                        except (ConnectionError, OSError):
                            return
                        except Exception as e:
                            try:
                                _send(self.request, K_ERR, repr(e))
                                kind, body = _recv(self.request)
                            except (ConnectionError, OSError):
                                return
                finally:
                    with ep._subs_lock:
                        ep._queries.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"interdc:{dc_id}:{self.port}",
        )
        self._thread.start()

    def _serve(self, kind: int, body):
        if kind == K_LOGQ:
            # read-only (scans the replica's sent chain): lock-free, so a
            # catch-up query from a peer that is itself mid-pump can never
            # join a cross-DC lock cycle
            msgs = self.query_handler(
                body["shard"], body["origin"], body["from"]
            )
            return [bytes(m) for m in msgs]
        if kind == K_REQ:
            # mutates node state (e.g. a bcounter grant commits a txn):
            # excluded against this DC's pump by the handler lock
            with self.lock:
                return self.request_handler(body["kind"], body["payload"])
        raise ValueError(f"unknown frame kind {kind}")

    def _drop_sub(self, entry: _Subscriber) -> None:
        with self._subs_lock:
            if entry in self._subs:
                self._subs.remove(entry)
        try:
            entry.sock.close()
        except OSError:
            pass

    def push(self, data: bytes) -> None:
        """Fan one frame out to every subscriber WITHOUT blocking: each
        stream has a bounded outbox drained by its own writer thread.  A
        full outbox (lagging subscriber) drops the frame for that stream
        only — its opid-gap catch-up replays the loss from the log."""
        with self._subs_lock:
            conns = list(self._subs)
        for entry in conns:
            if not entry.offer(data):
                net_metrics().egress_window_drops.inc()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._subs_lock:
            # _hard_close, not close(): the park/serve threads are
            # blocked in recv() on these sockets, and a bare close never
            # sends the FIN that tells subscribers the stream died
            for s in self._subs:
                _hard_close(s.sock)
                s.stop()
            self._subs.clear()
            for c in list(self._queries):
                _hard_close(c)
            self._queries.clear()


class TcpFabric:
    """LoopbackHub-compatible transport over real sockets.

    In-process it behaves like the hub (tests run 2-3 DCs on localhost);
    across processes, exchange ``address_of`` endpoints via descriptors and
    call ``connect_remote`` (the descriptor exchange of
    inter_dc_manager:observe_dcs_sync,
    /root/reference/src/inter_dc_manager.erl:67-109).
    """

    #: inbox high-water mark (frames parked for pump()); past it the
    #: Python readers shed — see ``inbox`` below
    INBOX_MAX = 16384

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 public_host: Optional[str] = None, reconnect: bool = True,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 max_reconnect_tries: Optional[int] = None):
        self.host = host
        #: fixed listen port for the FIRST registered endpoint (0 =
        #: ephemeral).  Deployments binding 0.0.0.0 need a publishable
        #: port — an ephemeral one can't be mapped through a container
        #: boundary or firewall
        self._bind_port = port
        #: address advertised in connection descriptors; a 0.0.0.0 bind
        #: address is meaningless to a REMOTE DC (it would connect to
        #: itself), so operators set the reachable name here.  It is
        #: substituted ONLY into exported Descriptors — local dialing
        #: (in-process subscribe/_rpc) keeps the bind address, which an
        #: external DNS/LB name may not hairpin back to
        self.public_host = public_host
        #: severed subscriptions re-dial with jittered exponential
        #: backoff in [backoff_base, backoff_max] seconds; None budget =
        #: keep trying until the fabric closes (the riak_core stance:
        #: links heal, processes don't give up on them)
        self.reconnect = reconnect
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.max_reconnect_tries = max_reconnect_tries
        self.endpoints: Dict[int, _Endpoint] = {}
        #: dc_id -> tick callback (deferred-heartbeat flush at pump)
        self._ticks: Dict[int, Callable] = {}
        #: dc_id -> (host, port) dialable FROM THIS PROCESS
        self.addresses: Dict[int, Tuple[str, int]] = {}
        #: dc_id -> (host, port) to put in exported descriptors
        self.advertised: Dict[int, Tuple[str, int]] = {}
        #: subscriber-side inbox: (deliver, data) pairs await pump().
        #: BOUNDED — when the pump falls this far behind, readers shed
        #: the newest frames instead of buffering without limit; the
        #: per-chain opid gap the shed opens is closed by catch-up once
        #: the pump drains (antidote_interdc_ingress_shed_total counts)
        self.inbox: "queue.Queue" = queue.Queue(maxsize=self.INBOX_MAX)
        self._readers: List[threading.Thread] = []
        self._closed = False
        #: jitter source for reconnect backoff (NOT the fault plan's rng)
        self._rng = random.Random()
        #: native receive plane (cpp/pump.cc): ONE epoll thread owns all
        #: subscription sockets — kernel reads + framing in C++ (the
        #: libzmq io-thread role, SURVEY §2.9); None = build/load
        #: failed, per-subscription Python readers take over
        from antidote_tpu.interdc.native_pump import NativePump

        self._np = NativePump.create()
        #: native-pump handle lifecycle guard (the r5 Weak #5 teardown
        #: race): close() could null + free the pump BETWEEN a pump
        #: thread's None-check and its take_batch/add call.  A plain
        #: mutex around the calls would serialize concurrent pumpers
        #: across the full blocking poll window, so instead callers
        #: REFCOUNT the handle (_np_enter/_np_exit) and close() waits
        #: under the condition until every in-flight native call drains
        #: before freeing — concurrency preserved, no use-after-free.
        self._np_cv = threading.Condition()
        self._np_users = 0
        self._np_tags: Dict[int, Callable] = {}
        self._np_next = 1
        #: decoded frames awaiting delivery (batch drains outpace pump)
        # bounded-by: one native take_batch crossing (≤512 frames),
        # consumed before the next crossing in _get_message
        self._np_ready: "collections.deque" = collections.deque()
        self._query_conns: Dict[Tuple[int, int], socket.socket] = {}
        self._query_lock = threading.Lock()
        self.delivered = 0

    # -- LoopbackHub interface -----------------------------------------
    def register(self, dc_id: int, on_message, query_handler) -> None:
        # the fixed port (if any) goes to the first endpoint; in-process
        # multi-DC tests register several per fabric and keep ephemeral
        port = self._bind_port if not self.endpoints else 0
        ep = _Endpoint(self, dc_id, self.host, port)
        ep.query_handler = query_handler
        self.endpoints[dc_id] = ep
        # local dialing keeps the BIND address; public_host goes only
        # into exported descriptors (advertised_of) — an external LB/DNS
        # name may not resolve or hairpin from inside this process
        self.addresses[dc_id] = (ep.host, ep.port)
        self.advertised[dc_id] = (self.public_host or ep.host, ep.port)
        inj = faults.get_injector()
        if inj is not None:
            inj.register_endpoint(
                f"interdc.ep.{dc_id}",
                kill=lambda d=dc_id: self.kill_endpoint(d),
                restart=lambda d=dc_id: self.restart_endpoint(d),
            )

    def register_request(self, dc_id: int, handler) -> None:
        self.endpoints[dc_id].request_handler = handler

    def address_of(self, dc_id: int) -> Tuple[str, int]:
        return self.addresses[dc_id]

    def advertised_of(self, dc_id: int) -> Tuple[str, int]:
        """The address to export in a Descriptor (public_host for local
        endpoints, the learned address for remote ones)."""
        return self.advertised.get(dc_id, self.addresses[dc_id])

    def connect_remote(self, dc_id: int, host: str, port: int) -> None:
        """Learn a remote (possibly other-process) DC's endpoint."""
        self.addresses[dc_id] = (host, port)
        self.advertised[dc_id] = (host, port)

    # -- endpoint crash/revive (chaos drivers + operators) --------------
    def kill_endpoint(self, dc_id: int) -> None:
        """Crash one DC's listening endpoint: the server and every
        subscriber stream die, exactly what a process kill does to the
        socket layer.  Peers' reconnect loops take over from here."""
        self.endpoints[dc_id].close()

    def restart_endpoint(self, dc_id: int) -> None:
        """Rebind a killed endpoint on its old port with its old
        handlers; reconnecting subscribers find it at the same address."""
        old = self.endpoints[dc_id]
        ep = _Endpoint(self, dc_id, self.host, old.port)
        ep.query_handler = old.query_handler
        ep.request_handler = old.request_handler
        self.endpoints[dc_id] = ep
        self.addresses[dc_id] = (ep.host, ep.port)
        self.advertised[dc_id] = (self.public_host or ep.host, ep.port)

    # -- subscriptions ---------------------------------------------------
    def subscribe(self, subscriber_dc: int, publisher_dc: int,
                  on_message) -> None:
        deliver = self._make_deliver(publisher_dc, subscriber_dc,
                                     on_message)
        sock = self._dial_sub(subscriber_dc, publisher_dc)
        self._attach(sock, subscriber_dc, publisher_dc, deliver)

    def _dial_sub(self, subscriber_dc: int, publisher_dc: int,
                  timeout: float = 5.0) -> socket.socket:
        """Open one subscription stream: connect, K_SUB, await the ack.
        Raises ConnectionError/OSError on any failure (reconnect loops
        catch and back off)."""
        host, port = self.addresses[publisher_dc]
        sock = socket.create_connection((host, port), timeout=timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send(sock, K_SUB, subscriber_dc)
            # wait for the registration ack before handing the socket to
            # the reader — subscribe() returning means the stream is live
            kind, _ = _recv(sock)
            if kind != K_REPLY:
                raise ConnectionError(f"bad subscription ack kind {kind}")
            sock.settimeout(None)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        return sock

    def _np_enter(self):
        """Pin the native pump for one call; None if closed/absent."""
        with self._np_cv:
            np_pump = self._np
            if np_pump is not None:
                self._np_users += 1
            return np_pump

    def _np_exit(self) -> None:
        with self._np_cv:
            self._np_users -= 1
            if self._np_users == 0:
                self._np_cv.notify_all()

    def _np_alloc_tag(self) -> int:
        """Mint a subscription tag under the cv lock: two concurrent
        subscribes (overlapping ctl_wire re-wires) must never share a
        tag, or one stream's deliver callback silently overwrites the
        other's."""
        with self._np_cv:
            tag = self._np_next
            self._np_next += 1
            return tag

    def _attach(self, sock: socket.socket, subscriber_dc: int,
                publisher_dc: int, deliver) -> None:
        np_pump = self._np_enter()
        if np_pump is not None:
            # native plane: hand the raw fd to the epoll pump (pinned:
            # close() must not free it mid-add)
            try:
                tag = self._np_alloc_tag()
                self._np_tags[tag] = (deliver, subscriber_dc, publisher_dc)
                np_pump.add(sock.detach(), tag)
            finally:
                self._np_exit()
            return
        t = threading.Thread(
            target=self._reader_loop,
            args=(sock, subscriber_dc, publisher_dc, deliver), daemon=True,
            name=f"sub:{subscriber_dc}<-{publisher_dc}")
        t.start()
        self._readers.append(t)

    def _reader_loop(self, sock, subscriber_dc: int, publisher_dc: int,
                     deliver) -> None:
        """Python-plane reader: drain frames; on disconnect, re-dial
        with backoff instead of dying (the stream heals, the opid-gap
        catch-up replays whatever the outage lost)."""
        while True:
            try:
                while True:
                    kind, body = _recv(sock)
                    if kind == K_PUSH:
                        try:
                            self.inbox.put_nowait((deliver, bytes(body)))
                        except queue.Full:
                            # pump saturated: shed, the chain gap heals
                            # via catch-up once the pump drains
                            net_metrics().ingress_shed.inc()
            except (ConnectionError, OSError):
                pass
            try:
                sock.close()
            except OSError:
                pass
            sock = self._reconnect(subscriber_dc, publisher_dc)
            if sock is None:
                return

    def _reconnect(self, subscriber_dc: int,
                   publisher_dc: int) -> Optional[socket.socket]:
        """Re-dial a dropped subscription with jittered exponential
        backoff.  Returns a live (acked) socket, or None when the fabric
        closed / reconnect is disabled / the retry budget ran out.
        Messages published during the outage are NOT lost: the replica's
        chain-gap detection queries the publisher's log from the last
        delivered opid once the stream is back."""
        if not self.reconnect or self._closed:
            return None
        link = f"{publisher_dc}->{subscriber_dc}"
        attempt = 0
        while not self._closed:
            if (self.max_reconnect_tries is not None
                    and attempt >= self.max_reconnect_tries):
                log.error("subscription %s: reconnect budget (%d) "
                          "exhausted; stream stays down", link, attempt)
                return None
            delay = min(self.backoff_max,
                        self.backoff_base * (2 ** min(attempt, 16)))
            # full jitter in [0.5x, 1.5x): concurrent reconnects from
            # many subscribers must not stampede a reborn endpoint
            time.sleep(delay * (0.5 + self._rng.random()))
            attempt += 1
            net_metrics().reconnect_attempts.inc(link=link)
            try:
                sock = self._dial_sub(subscriber_dc, publisher_dc)
            except (ConnectionError, OSError):
                continue
            net_metrics().reconnects.inc(link=link)
            log.warning("subscription %s: reconnected after %d attempt(s)",
                        link, attempt)
            return sock
        return None

    def _make_deliver(self, publisher_dc: int, subscriber_dc: int, cb):
        """Wrap a subscriber callback with the link's fault filter.
        Runs on the pump (control) thread, so seeded decisions are
        deterministic for a given delivery order."""

        def deliver(data: bytes) -> None:
            inj = faults.get_injector()
            if inj is not None:
                if inj.is_severed(publisher_dc, subscriber_dc):
                    return
                d = inj.hit("interdc.deliver",
                            key=(publisher_dc, subscriber_dc))
                if d is not None:
                    if d.action == "drop":
                        return
                    if d.action == "delay":
                        # redeliver in a later pump round (reordering);
                        # the rule decides again on the retry.  A full
                        # inbox degrades the delay to a drop — both are
                        # faults the chain repair already covers
                        try:
                            self.inbox.put_nowait((deliver, data))
                        except queue.Full:
                            net_metrics().ingress_shed.inc()
                        return
                    if d.action == "truncate":
                        data = data[: int(d.arg or 4)]
                    elif d.action == "dup":
                        cb(data)
                    elif d.action == "error":
                        raise RuntimeError(
                            "injected fault: interdc.deliver "
                            f"{publisher_dc}->{subscriber_dc}")
            cb(data)

        return deliver

    def publish(self, from_dc: int, data: bytes) -> None:
        self.endpoints[from_dc].push(data)

    #: per-attempt deadline for query-channel calls: a wedged peer must
    #: not hang a catch-up forever (the reference's REQ sockets carry
    #: ?COMM_TIMEOUT, /root/reference/include/antidote.hrl)
    QUERY_TIMEOUT_S = 30.0

    def _rpc(self, target_dc: int, kind: int, body):
        src = next(iter(self.endpoints), None)
        inj = faults.get_injector()
        if inj is not None:
            if src is not None and inj.is_severed(src, target_dc):
                raise ConnectionError(
                    f"injected partition: dc{src} <-> dc{target_dc}")
            d = inj.hit("interdc.rpc", key=(src, target_dc))
            if d is not None:
                if d.action == "delay" and d.arg:
                    time.sleep(float(d.arg))
                elif d.action in ("drop", "error"):
                    raise ConnectionError(
                        f"injected fault: interdc.rpc -> dc{target_dc}")
        last: Optional[Exception] = None
        for _attempt in range(2):
            with self._query_lock:
                key = (threading.get_ident(), target_dc)
                sock = self._query_conns.get(key)
                if sock is None:
                    host, port = self.addresses[target_dc]
                    try:
                        sock = socket.create_connection(
                            (host, port), timeout=self.QUERY_TIMEOUT_S)
                    except OSError as e:
                        last = e
                        continue
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    self._query_conns[key] = sock
            try:
                _send(sock, kind, body)
            except (ConnectionError, OSError) as e:
                # SEND failed — a cached conn gone stale (the peer's
                # endpoint was killed and reborn on the same port): the
                # request never got out, so redialing and resending is
                # always safe
                self._drop_query_conn(key, sock)
                last = e
                net_metrics().rpc_retries.inc()
                continue
            try:
                rkind, reply = _recv(sock)
            except socket.timeout as e:
                # reply deadline: the remote MAY be executing the request
                # — re-sending could double-apply a state-mutating K_REQ
                # (bcounter grants), so surface instead of retrying
                self._drop_query_conn(key, sock)
                raise ConnectionError(
                    f"query to dc{target_dc} exceeded "
                    f"{self.QUERY_TIMEOUT_S}s deadline") from e
            except (ConnectionError, OSError) as e:
                # reply LOST after a complete send: the remote may have
                # executed a state-mutating K_REQ — at-most-once, no
                # blind resend (K_LOGQ catch-up reads retry on the next
                # chain ping instead)
                self._drop_query_conn(key, sock)
                raise ConnectionError(
                    f"query to dc{target_dc}: connection died awaiting "
                    "the reply (remote may have executed)") from e
            if rkind == K_ERR:
                raise RuntimeError(
                    f"remote error from dc{target_dc}: {reply}")
            return reply
        raise ConnectionError(
            f"query channel to dc{target_dc} failed") from last

    def _drop_query_conn(self, key, sock) -> None:
        with self._query_lock:
            self._query_conns.pop(key, None)
        try:
            sock.close()
        except OSError:
            pass

    def query_log(self, target_dc: int, shard: int, origin: int,
                  from_opid: int) -> List[bytes]:
        return [bytes(m) for m in self._rpc(
            target_dc, K_LOGQ,
            {"shard": shard, "origin": origin, "from": from_opid},
        )]

    def request(self, target_dc: int, kind: str, payload):
        return self._rpc(target_dc, K_REQ,
                         {"kind": kind, "payload": payload})

    def register_tick(self, dc_id: int, fn) -> None:
        """Tick callback run at each pump — replicas flush deferred
        heartbeats here (see LoopbackHub.register_tick)."""
        self._ticks[dc_id] = fn

    def pump(self, max_rounds: int = 100_000, timeout: float = 0.5) -> int:
        """Deliver queued stream messages on the calling thread until the
        fabric is quiescent.

        Quiescence contract: "no traffic beyond two rounds of tick
        output" — ticks (deferred-heartbeat flushes) run at entry, at
        the first idle, and ONCE MORE at return (so a commit made by a
        server thread mid-pump still flushes its safe time before this
        pump returns), but tick-generated frames past the budget wait
        for the next pump: with the native receive plane our own pings
        arrive fast enough that an unbounded drain-ticks loop would
        never terminate."""
        n = 0
        # ticks may PUBLISH (heartbeat pings), and with the native
        # receive plane our own pings come back fast enough to keep the
        # loop busy forever — bound the flushes per pump() call so
        # "quiescent" means "no traffic beyond two rounds of tick
        # output", the LoopbackHub contract
        tick_budget = 2
        for fn in list(self._ticks.values()):
            fn()
        tick_budget -= 1
        while n < max_rounds:
            try:
                cb, data = self._get_message(timeout)
            except queue.Empty:
                if tick_budget <= 0:
                    # final flush WITHOUT re-draining: safe times of
                    # commits made mid-pump still reach the wire before
                    # we return (the documented invariant); any frames
                    # they generate are the next pump's work
                    for fn in list(self._ticks.values()):
                        fn()
                    return n
                tick_budget -= 1
                for fn in list(self._ticks.values()):
                    fn()
                try:
                    cb, data = self._get_message(0.05)
                except queue.Empty:
                    # same final-flush invariant as the exhausted-budget
                    # branch: safe times of mid-pump commits reach the
                    # wire before returning
                    for fn in list(self._ticks.values()):
                        fn()
                    return n
            # take the local handler locks so server threads (queries,
            # bcounter grants) never interleave with gate processing
            with self._local_locks():
                cb(data)
            self.delivered += 1
            n += 1
        return n

    def _get_message(self, timeout: float):
        """Next (deliver, data) from the Python inbox or the native
        pump, whichever has one first; raises queue.Empty on timeout.
        Native frames arrive in BATCHES (one ctypes crossing drains up
        to 512) and carry the raw wire payload — unpack here.  A
        K_CONN_DROP sentinel (kind 0, queued by pump.cc when a stream
        dies) triggers an off-thread resubscribe instead of a
        delivery."""
        if self._np is None:
            return self.inbox.get(timeout=timeout)
        if self._np_ready:
            return self._np_ready.popleft()
        deadline = time.monotonic() + timeout
        while True:
            try:
                # native mode feeds the inbox only with DELAYED
                # redeliveries (fault filter) — drain those first
                return self.inbox.get_nowait()
            except queue.Empty:
                pass
            rem = deadline - time.monotonic()
            wait_ms = max(1, int(rem * 1000)) if rem > 0 else 1
            # pin the handle per iteration (r5 Weak #5): close() waits
            # out in-flight calls, and a pump that loses the race just
            # goes idle — never an AttributeError or use-after-free.
            # Concurrent pumpers still poll concurrently (no mutex held
            # across the blocking native wait).
            np_pump = self._np_enter()
            if np_pump is None:  # fabric closed mid-pump: go idle
                raise queue.Empty
            try:
                batch = np_pump.take_batch(wait_ms)
            finally:
                self._np_exit()
            for tag, kind, payload in batch:
                ent = self._np_tags.get(tag)
                if ent is None:
                    continue
                deliver, sub_dc, pub_dc = ent
                if kind == K_CONN_DROP:
                    self._on_native_drop(tag, sub_dc, pub_dc)
                    continue
                if kind == K_PUSH:
                    body = msgpack.unpackb(payload, raw=False,
                                           strict_map_key=False)
                    self._np_ready.append((deliver, bytes(body)))
            if self._np_ready:
                return self._np_ready.popleft()
            if rem <= 0:
                raise queue.Empty

    def _on_native_drop(self, tag: int, subscriber_dc: int,
                        publisher_dc: int) -> None:
        """A native-plane subscription died (sentinel from pump.cc's
        close path — EOF, read error, or an over-cap/corrupt frame).
        Resubscribe off-thread with backoff; the epoll loop keeps
        serving the other streams meanwhile."""
        if self._closed or not self.reconnect:
            self._np_tags.pop(tag, None)
            return
        log.warning("subscription %s->%s: native stream dropped; "
                    "resubscribing", publisher_dc, subscriber_dc)

        def resub():
            sock = self._reconnect(subscriber_dc, publisher_dc)
            if sock is None:
                self._np_tags.pop(tag, None)
                return
            np_pump = self._np_enter()
            if np_pump is not None:
                try:
                    np_pump.add(sock.detach(), tag)  # same tag: same deliver
                finally:
                    self._np_exit()
                return
            # fabric torn down while we were backing off
            try:
                sock.close()
            except OSError:
                pass

        threading.Thread(target=resub, daemon=True,
                         name=f"resub:{subscriber_dc}<-{publisher_dc}"
                         ).start()

    def _local_locks(self):
        """A context manager holding every local endpoint's handler lock."""
        eps = list(self.endpoints.values())

        class _Multi:
            def __enter__(self):
                for e in eps:
                    e.lock.acquire()

            def __exit__(self, *exc):
                for e in reversed(eps):
                    e.lock.release()
                return False

        return _Multi()

    @staticmethod
    def interconnect(fabrics: List["TcpFabric"]) -> None:
        """Share endpoint addresses between per-DC fabrics (the in-process
        stand-in for exchanging descriptors between deployments)."""
        for a in fabrics:
            for b in fabrics:
                for dc, addr in b.addresses.items():
                    a.addresses.setdefault(dc, addr)

    def close(self) -> None:
        self._closed = True  # stops reconnect loops before sockets die
        # unpublish the handle, then wait out every pinned native call
        # before freeing: a pump blocked in take_batch finishes its
        # bounded poll, exits the refcount, and the next _np_enter sees
        # None and goes idle — never a use-after-free
        with self._np_cv:
            np_pump, self._np = self._np, None
            while self._np_users > 0:
                self._np_cv.wait(timeout=1.0)
        if np_pump is not None:
            np_pump.close()
        for ep in self.endpoints.values():
            ep.close()
        with self._query_lock:
            for s in self._query_conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._query_conns.clear()
