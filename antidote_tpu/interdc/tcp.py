"""TCP inter-DC fabric — real sockets between replicas.

The reference's two inter-DC channels (SURVEY §5) were ZeroMQ sockets:
PUB/SUB for the txn stream (port 8086, /root/reference/src/inter_dc_pub.erl)
and REQ/XREP for log catch-up + bcounter transfers (port 8085,
/root/reference/src/inter_dc_query.erl).  ``TcpFabric`` reproduces both
over plain TCP with the same length-prefixed framing as the client
protocol: each DC runs one endpoint socket; peers open one connection for
the subscription stream (server pushes frames) and one for synchronous
queries.

Interface-compatible with ``LoopbackHub``: incoming stream messages are
queued and delivered on ``pump()`` so replica state is only touched from
the control thread; query/request handlers run on server threads under the
DC's handler lock (the same single-writer discipline the vnode processes
gave the reference).
"""

from __future__ import annotations

import collections
import queue
import socket
import socketserver
import time
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

_HDR = struct.Struct(">IB")
K_SUB, K_PUSH, K_LOGQ, K_REQ, K_REPLY, K_ERR = 1, 2, 3, 4, 5, 6


def _send(sock, kind: int, body) -> None:
    payload = msgpack.packb(body, use_bin_type=True)
    sock.sendall(_HDR.pack(len(payload) + 1, kind) + payload)


def _recv(sock) -> Tuple[int, object]:
    hdr = _read_exact(sock, _HDR.size)
    n, kind = _HDR.unpack(hdr)
    payload = _read_exact(sock, n - 1)
    return kind, msgpack.unpackb(payload, raw=False, strict_map_key=False)


def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class _Endpoint:
    """One DC's listening side: accepts subscriber streams and queries."""

    def __init__(self, fabric: "TcpFabric", dc_id: int, host: str, port: int):
        self.fabric = fabric
        self.dc_id = dc_id
        self.lock = threading.RLock()          # guards handler invocations
        self.query_handler: Optional[Callable] = None
        self.request_handler: Optional[Callable] = None
        #: (socket, per-connection write lock) — the write lock serializes
        #: frames on one stream; _subs_lock guards only list membership
        self._subs: List[Tuple[socket.socket, threading.Lock]] = []
        self._subs_lock = threading.Lock()
        ep = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    kind, body = _recv(self.request)
                except (ConnectionError, OSError):
                    return
                if kind == K_SUB:
                    # a send-only timeout (SO_SNDTIMEO) bounds how long one
                    # stalled subscriber can hold its write lock; reads
                    # (the park loop below) are unaffected
                    self.request.setsockopt(
                        socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        struct.pack("ll", 10, 0),
                    )
                    # register + ack while holding this connection's write
                    # lock: a concurrent push that snapshots the list right
                    # after registration blocks on the lock until the ack
                    # frame is fully out — so the ack is always the stream's
                    # first frame, and once subscribe() returns every later
                    # publish sees the socket (observe_dcs_sync semantics,
                    # /root/reference/src/inter_dc_manager.erl:209-230)
                    wlock = threading.Lock()
                    entry = (self.request, wlock)
                    with wlock:
                        with ep._subs_lock:
                            ep._subs.append(entry)
                        try:
                            _send(self.request, K_REPLY, "subscribed")
                        except OSError:
                            with ep._subs_lock:
                                if entry in ep._subs:
                                    ep._subs.remove(entry)
                            return
                    # park until the peer closes (reads detect EOF)
                    try:
                        while self.request.recv(1):
                            pass
                    except OSError:
                        pass
                    with ep._subs_lock:
                        if entry in ep._subs:
                            ep._subs.remove(entry)
                    return
                # query connection: serve request/reply until EOF
                while True:
                    try:
                        reply = ep._serve(kind, body)
                        _send(self.request, K_REPLY, reply)
                        kind, body = _recv(self.request)
                    except (ConnectionError, OSError):
                        return
                    except Exception as e:
                        try:
                            _send(self.request, K_ERR, repr(e))
                            kind, body = _recv(self.request)
                        except (ConnectionError, OSError):
                            return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"interdc:{dc_id}:{self.port}",
        )
        self._thread.start()

    def _serve(self, kind: int, body):
        if kind == K_LOGQ:
            # read-only (scans the replica's sent chain): lock-free, so a
            # catch-up query from a peer that is itself mid-pump can never
            # join a cross-DC lock cycle
            msgs = self.query_handler(
                body["shard"], body["origin"], body["from"]
            )
            return [bytes(m) for m in msgs]
        if kind == K_REQ:
            # mutates node state (e.g. a bcounter grant commits a txn):
            # excluded against this DC's pump by the handler lock
            with self.lock:
                return self.request_handler(body["kind"], body["payload"])
        raise ValueError(f"unknown frame kind {kind}")

    def push(self, data: bytes) -> None:
        with self._subs_lock:
            conns = list(self._subs)
        for entry in conns:
            c, wlock = entry
            try:
                with wlock:  # one writer per stream; frames never interleave
                    _send(c, K_PUSH, data)
            except OSError:  # dead or stalled past SO_SNDTIMEO: drop it
                with self._subs_lock:
                    if entry in self._subs:
                        self._subs.remove(entry)
                try:
                    c.close()
                except OSError:
                    pass

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._subs_lock:
            for c, _ in self._subs:
                try:
                    c.close()
                except OSError:
                    pass
            self._subs.clear()


class TcpFabric:
    """LoopbackHub-compatible transport over real sockets.

    In-process it behaves like the hub (tests run 2-3 DCs on localhost);
    across processes, exchange ``address_of`` endpoints via descriptors and
    call ``connect_remote`` (the descriptor exchange of
    inter_dc_manager:observe_dcs_sync,
    /root/reference/src/inter_dc_manager.erl:67-109).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 public_host: Optional[str] = None):
        self.host = host
        #: fixed listen port for the FIRST registered endpoint (0 =
        #: ephemeral).  Deployments binding 0.0.0.0 need a publishable
        #: port — an ephemeral one can't be mapped through a container
        #: boundary or firewall
        self._bind_port = port
        #: address advertised in connection descriptors; a 0.0.0.0 bind
        #: address is meaningless to a REMOTE DC (it would connect to
        #: itself), so operators set the reachable name here
        self.public_host = public_host
        self.endpoints: Dict[int, _Endpoint] = {}
        #: dc_id -> tick callback (deferred-heartbeat flush at pump)
        self._ticks: Dict[int, Callable] = {}
        #: dc_id -> (host, port) for remote DCs
        self.addresses: Dict[int, Tuple[str, int]] = {}
        #: subscriber-side inbox: (on_message, data) pairs await pump()
        self.inbox: "queue.Queue" = queue.Queue()
        self._readers: List[threading.Thread] = []
        #: native receive plane (cpp/pump.cc): ONE epoll thread owns all
        #: subscription sockets — kernel reads + framing in C++ (the
        #: libzmq io-thread role, SURVEY §2.9); None = build/load
        #: failed, per-subscription Python readers take over
        from antidote_tpu.interdc.native_pump import NativePump

        self._np = NativePump.create()
        self._np_tags: Dict[int, Callable] = {}
        self._np_next = 1
        #: decoded frames awaiting delivery (batch drains outpace pump)
        self._np_ready: "collections.deque" = collections.deque()
        self._query_conns: Dict[Tuple[int, int], socket.socket] = {}
        self._query_lock = threading.Lock()
        self.delivered = 0

    # -- LoopbackHub interface -----------------------------------------
    def register(self, dc_id: int, on_message, query_handler) -> None:
        # the fixed port (if any) goes to the first endpoint; in-process
        # multi-DC tests register several per fabric and keep ephemeral
        port = self._bind_port if not self.endpoints else 0
        ep = _Endpoint(self, dc_id, self.host, port)
        ep.query_handler = query_handler
        self.endpoints[dc_id] = ep
        self.addresses[dc_id] = (self.public_host or ep.host, ep.port)

    def register_request(self, dc_id: int, handler) -> None:
        self.endpoints[dc_id].request_handler = handler

    def address_of(self, dc_id: int) -> Tuple[str, int]:
        return self.addresses[dc_id]

    def connect_remote(self, dc_id: int, host: str, port: int) -> None:
        """Learn a remote (possibly other-process) DC's endpoint."""
        self.addresses[dc_id] = (host, port)

    def subscribe(self, subscriber_dc: int, publisher_dc: int,
                  on_message) -> None:
        host, port = self.addresses[publisher_dc]
        sock = socket.create_connection((host, port))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send(sock, K_SUB, subscriber_dc)
        # wait for the registration ack before handing the socket to the
        # reader — subscribe() returning means the stream is live
        kind, _ = _recv(sock)
        assert kind == K_REPLY, kind
        if self._np is not None:
            # native plane: hand the raw fd to the epoll pump
            tag = self._np_next
            self._np_next += 1
            self._np_tags[tag] = on_message
            self._np.add(sock.detach(), tag)
            return

        def reader():
            try:
                while True:
                    kind, body = _recv(sock)
                    if kind == K_PUSH:
                        self.inbox.put((on_message, bytes(body)))
            except (ConnectionError, OSError):
                return

        t = threading.Thread(target=reader, daemon=True,
                             name=f"sub:{subscriber_dc}<-{publisher_dc}")
        t.start()
        self._readers.append(t)

    def publish(self, from_dc: int, data: bytes) -> None:
        self.endpoints[from_dc].push(data)

    def _rpc(self, target_dc: int, kind: int, body):
        with self._query_lock:
            key = (threading.get_ident(), target_dc)
            sock = self._query_conns.get(key)
            if sock is None:
                host, port = self.addresses[target_dc]
                sock = socket.create_connection((host, port))
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._query_conns[key] = sock
        _send(sock, kind, body)
        rkind, reply = _recv(sock)
        if rkind == K_ERR:
            raise RuntimeError(f"remote error from dc{target_dc}: {reply}")
        return reply

    def query_log(self, target_dc: int, shard: int, origin: int,
                  from_opid: int) -> List[bytes]:
        return [bytes(m) for m in self._rpc(
            target_dc, K_LOGQ,
            {"shard": shard, "origin": origin, "from": from_opid},
        )]

    def request(self, target_dc: int, kind: str, payload):
        return self._rpc(target_dc, K_REQ,
                         {"kind": kind, "payload": payload})

    def register_tick(self, dc_id: int, fn) -> None:
        """Tick callback run at each pump — replicas flush deferred
        heartbeats here (see LoopbackHub.register_tick)."""
        self._ticks[dc_id] = fn

    def pump(self, max_rounds: int = 100_000, timeout: float = 0.5) -> int:
        """Deliver queued stream messages on the calling thread until the
        fabric is quiescent.

        Quiescence contract: "no traffic beyond two rounds of tick
        output" — ticks (deferred-heartbeat flushes) run at entry, at
        the first idle, and ONCE MORE at return (so a commit made by a
        server thread mid-pump still flushes its safe time before this
        pump returns), but tick-generated frames past the budget wait
        for the next pump: with the native receive plane our own pings
        arrive fast enough that an unbounded drain-ticks loop would
        never terminate."""
        n = 0
        # ticks may PUBLISH (heartbeat pings), and with the native
        # receive plane our own pings come back fast enough to keep the
        # loop busy forever — bound the flushes per pump() call so
        # "quiescent" means "no traffic beyond two rounds of tick
        # output", the LoopbackHub contract
        tick_budget = 2
        for fn in list(self._ticks.values()):
            fn()
        tick_budget -= 1
        while n < max_rounds:
            try:
                cb, data = self._get_message(timeout)
            except queue.Empty:
                if tick_budget <= 0:
                    # final flush WITHOUT re-draining: safe times of
                    # commits made mid-pump still reach the wire before
                    # we return (the documented invariant); any frames
                    # they generate are the next pump's work
                    for fn in list(self._ticks.values()):
                        fn()
                    return n
                tick_budget -= 1
                for fn in list(self._ticks.values()):
                    fn()
                try:
                    cb, data = self._get_message(0.05)
                except queue.Empty:
                    # same final-flush invariant as the exhausted-budget
                    # branch: safe times of mid-pump commits reach the
                    # wire before returning
                    for fn in list(self._ticks.values()):
                        fn()
                    return n
            # take the local handler locks so server threads (queries,
            # bcounter grants) never interleave with gate processing
            with self._local_locks():
                cb(data)
            self.delivered += 1
            n += 1
        return n

    def _get_message(self, timeout: float):
        """Next (on_message, data) from the Python inbox or the native
        pump, whichever has one first; raises queue.Empty on timeout.
        Native frames arrive in BATCHES (one ctypes crossing drains up
        to 512) and carry the raw wire payload — unpack here."""
        if self._np is None:
            return self.inbox.get(timeout=timeout)
        # native mode: the inbox is never fed (subscribe hands every fd
        # to the pump), so block straight on the native queue
        if self._np_ready:
            return self._np_ready.popleft()
        deadline = time.monotonic() + timeout
        while True:
            rem = deadline - time.monotonic()
            wait_ms = max(1, int(rem * 1000)) if rem > 0 else 1
            for tag, kind, payload in self._np.take_batch(wait_ms):
                cb = self._np_tags.get(tag)
                if cb is not None and kind == K_PUSH:
                    body = msgpack.unpackb(payload, raw=False,
                                           strict_map_key=False)
                    self._np_ready.append((cb, bytes(body)))
            if self._np_ready:
                return self._np_ready.popleft()
            if rem <= 0:
                raise queue.Empty

    def _local_locks(self):
        """A context manager holding every local endpoint's handler lock."""
        eps = list(self.endpoints.values())

        class _Multi:
            def __enter__(self):
                for e in eps:
                    e.lock.acquire()

            def __exit__(self, *exc):
                for e in reversed(eps):
                    e.lock.release()
                return False

        return _Multi()

    @staticmethod
    def interconnect(fabrics: List["TcpFabric"]) -> None:
        """Share endpoint addresses between per-DC fabrics (the in-process
        stand-in for exchanging descriptors between deployments)."""
        for a in fabrics:
            for b in fabrics:
                for dc, addr in b.addresses.items():
                    a.addresses.setdefault(dc, addr)

    def close(self) -> None:
        if self._np is not None:
            self._np.close()
            self._np = None
        for ep in self.endpoints.values():
            ep.close()
        with self._query_lock:
            for s in self._query_conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._query_conns.clear()
