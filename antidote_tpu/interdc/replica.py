"""DCReplica — inter-DC replication endpoint for one replica.

Combines the reference's egress and ingress pipelines (SURVEY §3.4):

  egress:  local commit → per-shard TxnMessage with (shard, origin) opid
           chaining → transport publish
           (inter_dc_log_sender_vnode + inter_dc_pub)
  ingress: message → per-(origin, shard) chain check: eq→deliver,
           gt→buffer + log catch-up query, lt→drop duplicate
           (inter_dc_sub_buf, /root/reference/src/inter_dc_sub_buf.erl:98-142)
           → causal dependency gate: apply once the shard clock dominates
           the txn's snapshot VC with the origin lane zeroed
           (inter_dc_dep_vnode:try_store,
           /root/reference/src/inter_dc_dep_vnode.erl:128-154)
  heartbeats: empty txns carrying the origin's safe time so remote stable
           snapshots advance when idle
           (/root/reference/src/inter_dc_log_sender_vnode.erl:133-143)
"""

from __future__ import annotations

import collections
import itertools
import logging
import time
from typing import Dict, List, Tuple

import numpy as np

log = logging.getLogger(__name__)

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.interdc.messages import Descriptor, TxnMessage
from antidote_tpu.interdc.transport import LoopbackHub
from antidote_tpu.store.kv import effect_from_rec


class DCReplica:
    #: recent egress messages kept in memory per shard; catch-up below the
    #: window is served from the WAL (the reference serves ALL catch-up
    #: from its disk log, /root/reference/src/inter_dc_query_response.erl:97-126)
    SENT_WINDOW = 256
    #: heartbeat cadence: at most one flush per interval on the commit
    #: path (the reference's 1 s ?HEARTBEAT_PERIOD timer,
    #: /root/reference/include/antidote.hrl:55), or every N commits,
    #: whichever first; pumps flush lazily whenever commits are pending
    HEARTBEAT_INTERVAL_S = 1.0
    HEARTBEAT_EVERY_COMMITS = 64
    #: ingress high-water marks (PR 4).  GATE_HWM caps one (origin,
    #: shard) chain's causal-gate queue: past it, delivery is SHED
    #: without advancing ``last_seen`` — the chain gap that opens is
    #: exactly what the opid catch-up repairs, so pressure converts into
    #: repair traffic instead of memory.  PENDING_HWM caps the
    #: out-of-order buffer the same way (anything dropped is above
    #: ``last_seen`` and gets refetched).
    GATE_HWM = 1024
    PENDING_HWM = 256
    #: follower liveness (ISSUE 9): a follower whose last report is
    #: older than this is DOWN; one whose applied own-lane clock trails
    #: the owner's commit counter by more than REPLICA_LAG_OPS is
    #: LAGGING (both surface typed in node status / console)
    REPLICA_DOWN_S = 5.0
    REPLICA_LAG_OPS = 1024
    #: image-shipping chunk for ckpt_fetch (one request per chunk; the
    #: ckpt.ship fault site is consulted per chunk)
    CKPT_SHIP_CHUNK = 4 << 20

    def __init__(self, node: AntidoteNode, hub: LoopbackHub, name: str = "",
                 shards=None, fabric_id: int = None):
        self.node = node
        self.hub = hub
        self.name = name or f"dc{node.dc_id}"
        self.dc_id = node.dc_id
        #: shards this endpoint owns.  A single-node DC owns all of them;
        #: a multi-node DC's members each publish/ingest only their own
        #: shards' chains (one publisher per (origin, shard), like the
        #: reference's per-partition log senders)
        # any iterable-with-membership works — cluster members pass a
        # LIVE view so the endpoint tracks ownership through live
        # membership moves (a frozen copy kept heartbeating shards that
        # had moved away)
        self.shards = (set(range(node.cfg.n_shards)) if shards is None
                       else shards)
        #: id this endpoint registers under on the fabric — cluster
        #: members of one DC need distinct endpoints (dc_id stays the
        #: semantic origin in every message)
        self.fabric_id = self.dc_id if fabric_id is None else fabric_id
        #: (origin_dc, shard) -> fabric id serving that chain's catch-up
        #: queries (identity for single-node DCs).  Only a FALLBACK for
        #: chains whose ownership was never gossiped: learned
        #: ``shard_route`` entries (below) take precedence.
        self.route_query = lambda origin, shard: origin
        #: (origin_dc, shard) -> (owner member id, ownership epoch)
        #: learned from publisher gossip (TxnMessage.owner/oepoch): the
        #: live view of WHICH member of a clustered origin serves each
        #: chain.  Strictly-newer epochs win, so a stale boot-time
        #: router (or a replayed frame) can never point catch-up back at
        #: a previous owner — membership change at the origin re-routes
        #: here without any reconnect.
        self.shard_route: Dict[Tuple[int, int], Tuple[int, int]] = {}
        #: shard -> (member id, ownership epoch) stamped into egress
        #: messages; attach_interdc installs the member-backed form.
        #: None = single-member origin, nothing to gossip.
        self.owner_info = None
        p = node.cfg.n_shards
        #: egress opid chain per shard (my origin)
        self.pub_opid = np.zeros(p, np.int64)
        #: bounded recent-message window per shard (fast catch-up path);
        #: guarded by _sent_lock — the TCP fabric serves catch-up queries
        #: on server threads while the control thread appends, and deque
        #: iteration under concurrent append raises
        self.sent: List[collections.deque] = [
            collections.deque(maxlen=self.SENT_WINDOW) for _ in range(p)
        ]
        import threading

        self._sent_lock = threading.Lock()
        #: held (AFTER the commit lock — the documented cross-plane
        #: order) around the batched device apply.  ``apply_effects`` is
        #: a read-modify-REASSIGN with buffer donation, so a reader
        #: gathering from the live heads concurrently observes deleted
        #: jax buffers.  ``attach_interdc`` re-points this at the
        #: cluster member's lock — the lock ``m_read_values`` reads
        #: under — closing the ingress-vs-reader race (own commits were
        #: already excluded via the commit lock).
        self.store_lock = threading.RLock()
        self._commits_since_hb = 0
        self._last_hb = time.monotonic()
        #: per-shard safe time last pinged (drives the tick-path flush)
        self._published_safe: Dict[int, int] = {}
        #: ingress: last delivered opid per (origin, shard)
        self.last_seen: Dict[Tuple[int, int], int] = {}
        #: ingress: out-of-order buffer per (origin, shard)
        # bounded-by: PENDING_HWM (checked at every insert in _on_message)
        self.pending: Dict[Tuple[int, int], List[TxnMessage]] = (
            collections.defaultdict(list)
        )
        #: causal gate FIFO per (origin, shard)
        # bounded-by: GATE_HWM (shed-at-accept in _accept/_flush_pending)
        self.gate: Dict[Tuple[int, int], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        hub.register(self.fabric_id, self._on_message, self._serve_log_query)
        hub.register_request(self.fabric_id, self._serve_request)
        if hasattr(hub, "register_tick"):
            hub.register_tick(self.fabric_id, self.maybe_heartbeat)
        node.txm.commit_listeners.append(self._on_local_commit)
        node.txm.on_clock_wait = self._on_clock_wait
        # bcounter rights requests ride the query channel (?BCOUNTER_REQUEST)
        node.txm.bcounters.request_transfer = self._request_transfer
        # batched twin (ISSUE 19): one tick's asks against the same
        # granter share ONE round trip
        node.txm.bcounters.request_transfer_many = self._request_transfer_many
        #: clustered DCs install an intra-DC router here (attach_interdc)
        self.transfer_handler = None
        #: follower registry (ISSUE 9): name -> {addr, applied, state,
        #: at (monotonic of last report), boots}; reports arrive on the
        #: request channel (follower_report), operators pre-register /
        #: decommission via the wire REPLICA_ADMIN op
        self.followers: Dict[str, dict] = {}
        #: decommissioned follower names whose reports are ignored
        self._removed_followers: set = set()
        self._followers_lock = threading.Lock()

    # ------------------------------------------------------------------
    # restart (check_node_restart, /root/reference/src/inter_dc_manager.erl:156-206)
    # ------------------------------------------------------------------
    def _wal_txn_groups(self, shard: int, my_effects_after: int = 0,
                        snap: "Tuple[int, int] | None" = None):
        """One shard's WAL records grouped into transactions, in apply
        order.  Grouping key is the (origin, commit VC) IDENTITY over the
        whole replay — commit VCs are unique per origin — never record
        adjacency: handoff/reshard re-chaining interleaves a multi-shard
        txn's records, and adjacency grouping would split such a txn and
        desync the opid chain (r1 advisor medium (c)).

        Returns [[origin, vc_tuple, effects]].  Effects are materialized
        only for my own chain and only for groups whose chain opid
        exceeds ``my_effects_after`` — a catch-up query slightly below
        the window must not pay effect decoding for the whole chain
        prefix it will discard.  My-chain opids are numbered from the
        log's CHAIN FLOOR (ISSUE 8): a checkpoint-truncated WAL holds
        only the tail groups, and the floor records how many own-origin
        groups the image covers, so numbering stays continuous across
        compaction."""
        store = self.node.store
        index: Dict[Tuple[int, tuple], int] = {}
        out: List[list] = []
        my_opid: Dict[int, int] = {}
        # (base, floor) snapshot under the commit lock: a checkpoint
        # publish updates both together there, and a torn read would
        # shift this response's opid numbering against the chain.
        # Callers that ALSO number against the base (catch-up serving)
        # pass their own snapshot so both sides agree.
        if snap is None:
            with self.node.txm.commit_lock:
                snap = (store.log.chain_base(shard, self.dc_id),
                        int(store.log.floor_seqs[shard]))
        my_count, floor = snap
        for rec in store.log.replay_shard(shard, floor=floor):
            ident = (int(rec["o"]), tuple(int(x) for x in rec["vc"]))
            at = index.get(ident)
            if at is None:
                index[ident] = at = len(out)
                out.append([ident[0], ident[1], []])
                if ident[0] == self.dc_id:
                    my_count += 1
                    my_opid[at] = my_count
            if ident[0] == self.dc_id and my_opid[at] > my_effects_after:
                out[at][2].append(effect_from_rec(rec))
        return out

    def _owner_stamp(self, shard: int) -> tuple:
        """(owner member id, ownership epoch) for egress gossip, or
        (None, None) for single-member origins."""
        if self.owner_info is None:
            return (None, None)
        ow, oe = self.owner_info(shard)
        return (int(ow), int(oe))

    def _chain_message(self, shard: int, opid: int, vc: tuple,
                       effects: list) -> TxnMessage:
        """My-origin chain message #opid (1-based) for a shard."""
        cvc = np.asarray(vc, np.int32)
        svc = cvc.copy()
        svc[self.dc_id] = 0
        ow, oe = self._owner_stamp(shard)
        return TxnMessage(
            origin=self.dc_id, shard=shard, prev_opid=opid - 1,
            last_opid=opid, commit_vc=cvc, snapshot_vc=svc,
            effects=effects, timestamp=int(cvc[self.dc_id]),
            owner=ow, oepoch=oe,
        )

    def restore_from_log(self) -> None:
        """Rebuild replication chains after a node restart from its WAL.

        Egress: my own-origin records regroup into per-shard TxnMessages
        with fresh sequential opids, so peers' catch-up queries keep
        working (the reference re-reads its disk log for this,
        /root/reference/src/inter_dc_query_response.erl:97-126).
        Ingress: each remote (origin, shard) chain's delivered-txn count
        IS the publisher's opid (one opid per txn per shard, delivered
        exactly once in order), so ``last_seen`` reseeds from the log
        (inter_dc_sub_buf restart seeding,
        /root/reference/src/inter_dc_sub_buf.erl:58-76).
        """
        store = self.node.store
        assert store.log is not None, "restore_from_log needs a WAL"
        for shard in sorted(self.shards):
            # chain positions resume at the checkpoint's chain floor
            # (groups the image covers but the truncated WAL no longer
            # holds) + whatever the tail replays on top; with no
            # checkpoint the floors are zero and this is the classic
            # whole-log reseed
            counts: Dict[int, int] = collections.defaultdict(int)
            for origin in range(self.node.cfg.max_dcs):
                base = store.log.chain_base(shard, origin)
                if base:
                    counts[origin] = base
            self.pub_opid[shard] = store.log.chain_base(shard, self.dc_id)
            for origin, vc, effs in self._wal_txn_groups(shard):
                counts[origin] += 1
                if origin != self.dc_id:
                    continue
                self.pub_opid[shard] += 1
                with self._sent_lock:
                    self.sent[shard].append(self._chain_message(
                        shard, int(self.pub_opid[shard]), vc, effs
                    ))
            for origin, n in counts.items():
                if origin != self.dc_id:
                    self.last_seen[(origin, shard)] = n

    # ------------------------------------------------------------------
    # live shard moves (the ownership-handoff seam attach_interdc wires):
    # a shard's replication chain state travels WITH the shard, so the
    # new owner continues the (origin, shard) opid chain where the old
    # one stopped — remote subscribers never see a chain restart.  Both
    # run under the member's cross-plane lock, excluded vs the drain.
    # ------------------------------------------------------------------
    def adopt_shard(self, shard: int, extras=None) -> None:
        """Install a moved-in shard's chain state from the handoff
        package extras: egress opid + the recent sent window (catch-up
        keeps serving through the move) and the remote-chain ingress
        positions (gap detection resumes where the old owner stood).

        Without extras (pre-extras package from a rolling upgrade, or
        no inter-DC plane at the source) the EGRESS opid is recomputed
        from the imported WAL the way :meth:`restore_from_log` does —
        resuming at 0 would make every remote subscriber drop the new
        owner's first N commits as chain duplicates (prev < their
        last_seen), a silent permanent loss.  Ingress positions restart
        at 0 in that case, which only costs a catch-up replay (the
        chain-clock duplicate suppression makes it idempotent)."""
        shard = int(shard)
        extras = (extras or {}).get("interdc", {})
        if "pub_opid" in extras:
            opid = int(extras["pub_opid"])
        elif self.node.store.log is not None:
            # count my own-origin txn groups in the (just-imported) WAL
            # chain on top of any compaction-floor base; a huge
            # my_effects_after skips effect materialization
            opid = self.node.store.log.chain_base(shard, self.dc_id) + sum(
                1 for origin, _vc, _effs in self._wal_txn_groups(
                    shard, my_effects_after=1 << 62)
                if origin == self.dc_id)
        else:
            opid = 0  # WAL-less + extras-less: test-only configuration
        # MONOTONE: adopt_shard re-runs on duplicate import deliveries
        # (a driver retry after a mid-hook failure).  If commits already
        # landed here since the first delivery, the chain advanced past
        # the package's opid — rewinding it (or reinstalling the old
        # window over newer messages) would corrupt the chain; anything
        # a partial first run left out of the window is served from the
        # WAL instead.
        if opid > int(self.pub_opid[shard]):
            self.pub_opid[shard] = opid
            with self._sent_lock:
                self.sent[shard].clear()
                for data in extras.get("sent", ()):
                    self.sent[shard].append(
                        TxnMessage.from_bytes(bytes(data)))
        for o, v in extras.get("last_seen", ()):
            key = (int(o), shard)
            if int(v) > self.last_seen.get(key, 0):
                self.last_seen[key] = int(v)
        self._published_safe.pop(shard, None)

    def export_shard_state(self, shard: int) -> dict:
        """The extras counterpart of :meth:`adopt_shard` — captured by
        the member under both locks, so it is exactly consistent with
        the handoff package (no commit or remote apply in between).

        The exported ingress position is the APPLIED-safe one, not the
        delivered one: ``last_seen`` advances at delivery, but a
        dep-blocked txn can sit in the causal gate (and ``pending``)
        without its effects being in the table slice the package
        carries.  Exporting the delivered position would make the new
        owner skip straight past those txns (no gap ⇒ no catch-up) —
        a permanently lost effect.  So the position is clamped below
        the earliest still-queued txn on each chain; the new owner's
        catch-up refetches the suffix and re-gates it."""
        shard = int(shard)
        with self._sent_lock:
            sent = [m.to_bytes() for m in self.sent[shard]]
        last_seen = []
        for (o, s), v in self.last_seen.items():
            if s != shard:
                continue
            safe = int(v)
            for m in self.gate.get((o, s), ()):
                if not m.is_ping:
                    safe = min(safe, int(m.prev_opid))
                    break  # gate is FIFO in chain order
            # (pending entries sit ABOVE a gap, i.e. past last_seen —
            # dropping them is safe, catch-up refetches from last_seen)
            last_seen.append([int(o), safe])
        return {"interdc": {"pub_opid": int(self.pub_opid[shard]),
                            "sent": sent, "last_seen": last_seen}}

    def release_shard(self, shard: int) -> None:
        """Clear a relinquished shard's chain state at the OLD owner:
        its egress chain now lives at the importer, and any queued
        remote txns must never apply to the dropped table slice (the
        new owner replays them through catch-up instead)."""
        shard = int(shard)
        self.pub_opid[shard] = 0
        with self._sent_lock:
            self.sent[shard].clear()
        self._published_safe.pop(shard, None)
        for key in [k for k in self.last_seen if k[1] == shard]:
            del self.last_seen[key]
        for key in [k for k in self.pending if k[1] == shard]:
            del self.pending[key]
        for key in [k for k in self.gate if k[1] == shard]:
            del self.gate[key]

    # ------------------------------------------------------------------
    def ingress_barrier(self):
        """A lock excluding fabric-thread mutations (TCP request handlers
        committing bcounter grants) for the duration of a reshard — the
        stand-in for riak_core blocking vnode commands during ownership
        handoff.  The single-threaded LoopbackHub needs no lock."""
        eps = getattr(self.hub, "endpoints", None)
        if eps and self.fabric_id in eps:
            return eps[self.fabric_id].lock
        import contextlib

        return contextlib.nullcontext()

    def descriptor(self) -> Descriptor:
        """Shareable connection descriptor
        (inter_dc_manager:get_descriptor,
        /root/reference/src/inter_dc_manager.erl:49-61).  Carries the
        transport endpoint when the hub has one (TcpFabric), so another
        process/deployment can subscribe from the descriptor alone."""
        addr = None
        # exported descriptors carry the ADVERTISED address (public_host
        # substituted); local dialing keeps using the bind address
        address_of = getattr(self.hub, "advertised_of",
                             getattr(self.hub, "address_of", None))
        if address_of is not None:
            try:
                addr = tuple(address_of(self.fabric_id))
            except KeyError:
                addr = None
        return Descriptor(self.dc_id, self.name, self.node.cfg.n_shards,
                          addr, self.fabric_id)

    def observe_dc(self, remote: "DCReplica") -> None:
        """Subscribe to a remote DC's txn stream
        (inter_dc_manager:observe_dcs_sync,
        /root/reference/src/inter_dc_manager.erl:67-109)."""
        self.hub.subscribe(self.fabric_id, remote.fabric_id, self._on_message)

    def observe_descriptor(self, desc) -> None:
        """Subscribe from a wire descriptor (dict or Descriptor) — the
        cross-process form of :meth:`observe_dc`
        (antidote_dc_manager:subscribe_updates_from,
        /root/reference/src/antidote_dc_manager.erl:83-87).  Learns the
        remote endpoint, opens the stream subscription; the opid-gap
        catch-up machinery fetches anything missed before connecting."""
        if isinstance(desc, dict):
            desc = Descriptor.from_wire(desc)
        remote_fid = desc.fabric_id if desc.fabric_id is not None else desc.dc_id
        if remote_fid == self.fabric_id:
            return  # self-descriptor: nothing to subscribe to
        if desc.address is not None:
            connect = getattr(self.hub, "connect_remote", None)
            if connect is not None:
                connect(remote_fid, desc.address[0], int(desc.address[1]))
        self.hub.subscribe(self.fabric_id, remote_fid, self._on_message)

    @staticmethod
    def connect_all(replicas: List["DCReplica"]) -> None:
        for a in replicas:
            for b in replicas:
                if a is not b:
                    a.observe_dc(b)

    # ------------------------------------------------------------------
    # egress
    # ------------------------------------------------------------------
    def _on_local_commit(self, effects, commit_vc, origin) -> None:
        by_shard: Dict[int, list] = {}
        for eff in effects:
            _, shard, _ = self.node.store.locate(eff.key, eff.type_name,
                                                 eff.bucket)
            by_shard.setdefault(shard, []).append(eff)
        snapshot_vc = np.asarray(commit_vc, np.int32).copy()
        snapshot_vc[origin] = 0
        for shard, effs in by_shard.items():
            prev = int(self.pub_opid[shard])
            self.pub_opid[shard] += 1
            ow, oe = self._owner_stamp(shard)
            msg = TxnMessage(
                origin=origin, shard=shard, prev_opid=prev,
                last_opid=prev + 1,
                commit_vc=np.asarray(commit_vc, np.int32),
                snapshot_vc=snapshot_vc, effects=effs,
                timestamp=int(commit_vc[origin]),
                owner=ow, oepoch=oe,
            )
            with self._sent_lock:
                self.sent[shard].append(msg)
            self.hub.publish(self.fabric_id, msg.to_bytes())
        # idle-shard safe times are NOT broadcast per commit — that would
        # be O(n_shards) fabric messages per txn (r2 VERDICT weak #5).
        # They flush on the interval/commit-count thresholds below and at
        # every fabric pump (maybe_heartbeat via the tick), mirroring the
        # reference's 1 s timer.
        self._commits_since_hb += 1
        if getattr(self.node.txm, "_publishing_group", False):
            # mid-group publish: later group members' counters are
            # already minted but their messages are not on the stream
            # yet, so a safe-time ping here would make subscribers skip
            # them as duplicates (lost effects).  The tick-path flush
            # (maybe_heartbeat at every pump) or the next commit sends
            # the deferred ping instead.
            return
        if (self._commits_since_hb >= self.HEARTBEAT_EVERY_COMMITS
                or time.monotonic() - self._last_hb
                >= self.HEARTBEAT_INTERVAL_S):
            self.heartbeat()

    def maybe_heartbeat(self) -> None:
        """Flush deferred safe-time pings iff something new is worth
        publishing: commits since the last flush, or a shard's safe time
        advancing past what was last pinged (cluster members' safe times
        move with the DC sequencer even when this member saw no commit).
        Tick path: called at every fabric pump, so a peer blocked on my
        lane is unblocked promptly without any per-commit broadcast."""
        if self._commits_since_hb > 0:
            self.heartbeat()
            return
        for shard in self.shards:
            if int(self.safe_time(shard)) > self._published_safe.get(shard, 0):
                self.heartbeat()
                return
        # LIVENESS: re-ping on the wall-clock interval even with nothing
        # new — a LOST final ping (or txn) is only ever detected by a
        # later message on the same chain; the reference's unconditional
        # 1 s timer provides exactly this re-send
        # (/root/reference/src/inter_dc_log_sender_vnode.erl:133-143)
        if time.monotonic() - self._last_hb >= self.HEARTBEAT_INTERVAL_S:
            self.heartbeat()

    def safe_time(self, shard: int) -> int:
        """Largest own-lane ts such that no future local commit on
        ``shard`` can carry a smaller one — AND every commit at or below
        it has already been published to the stream (taken under the
        manager's commit lock: a counter read mid-commit would mint a
        ping that outruns its own txn on the wire, and the subscriber's
        chain-clock duplicate suppression would drop the txn as
        already-applied).  Single-node DCs mint commits
        from one monotone counter applied synchronously, so the counter
        itself is safe for every shard.  Cluster members override this
        (their safe time is the sequencer frontier, gated on outstanding
        prepared txns)."""
        txm = self.node.txm
        lock = getattr(txm, "commit_lock", None)
        if lock is None:
            return txm.commit_counter
        with lock:
            return txm.commit_counter

    def heartbeat(self, exclude=frozenset()) -> None:
        """Broadcast per-shard safe times (the reference's per-partition
        min-prepared heartbeat,
        /root/reference/src/inter_dc_log_sender_vnode.erl:133-143).  Also
        advances MY lane on idle local shards: without it, a remote txn
        whose snapshot depends on my lane would gate forever on shards I
        never wrote to."""
        self._commits_since_hb = 0
        self._last_hb = time.monotonic()
        vc = self.node.store.applied_vc
        lock = self.node.txm.commit_lock
        for shard in sorted(self.shards):
            # stamp under the cross-plane lock and RE-CHECK membership:
            # the tick-path heartbeat races a live relinquish, and a
            # stale iteration could otherwise publish a ping stamped
            # (old owner, already-bumped epoch) — subscribers would
            # adopt it and then reject the REAL new owner's equal-epoch
            # stamps forever, permanently mis-routing catch-up
            with lock:
                if shard not in self.shards:
                    continue
                safe = int(self.safe_time(shard))
                vc[shard, self.dc_id] = max(vc[shard, self.dc_id], safe)
                self._published_safe[shard] = safe
                prev = int(self.pub_opid[shard])
                ow, oe = self._owner_stamp(shard)
            if shard in exclude:
                continue
            msg = TxnMessage(
                origin=self.dc_id, shard=shard, prev_opid=prev,
                last_opid=prev,  # pings do not advance the chain
                commit_vc=np.zeros(self.node.cfg.max_dcs, np.int32),
                snapshot_vc=np.zeros(self.node.cfg.max_dcs, np.int32),
                effects=[], timestamp=safe,
                owner=ow, oepoch=oe,
            )
            self.hub.publish(self.fabric_id, msg.to_bytes())

    def _serve_request(self, kind: str, payload) -> object:
        """Generic query-channel dispatch (inter_dc_query_receive_socket,
        /root/reference/src/inter_dc_query_receive_socket.erl:111-139)."""
        if kind == "bcounter":
            return self._grant_one(payload)
        if kind == "bcounter_many":
            # batched rights requests (ISSUE 19): each entry keeps the
            # single-key grant discipline — the SAME fault site keyed
            # per key (a plan can starve one key of the batch), the
            # same clustered routing, the same granter counter.  Per-
            # entry faults fail THAT entry (grant 0), not the frame:
            # the requester's at-most-once throttle already covers each
            # (key, target) independently, so a half-served batch must
            # not un-serve the grants that committed.
            out = []
            for key, bucket, amount in payload["entries"]:
                try:
                    out.append(self._grant_one({
                        "key": key, "bucket": bucket, "amount": amount,
                        "to_dc": payload["to_dc"],
                    }))
                except (ConnectionError, OSError):
                    out.append(0)
            return out
        if kind == "check_up":
            return True
        # follower-replica plane (ISSUE 9)
        if kind == "ckpt_meta":
            return self._serve_ckpt_meta(payload)
        if kind == "ckpt_fetch":
            return self._serve_ckpt_fetch(payload)
        if kind == "shard_digest":
            return self._serve_shard_digest(payload)
        # Merkle-split divergence plane (ISSUE 13)
        if kind == "merkle_root":
            return self._serve_merkle_root(payload)
        if kind == "merkle_node":
            return self._serve_merkle_node(payload)
        if kind == "merkle_leaf":
            return self._serve_merkle_leaf(payload)
        if kind == "peer_origins":
            return self._serve_peer_origins()
        if kind == "follower_report":
            return self._serve_follower_report(payload)
        raise ValueError(f"unknown request kind {kind!r}")

    def _grant_one(self, payload) -> int:
        """Serve ONE rights request at granter entry: consult the
        ``bcounter.transfer`` fault site (keyed per key, so chaos plans
        can starve/stretch grant traffic — wide enough to SIGKILL a
        granter mid-transfer), route clustered DCs through the key
        owner's coordinator, else commit the grant locally."""
        import errno as _errno

        from antidote_tpu import faults as _faults

        d = _faults.hit("bcounter.transfer",
                        key=(payload.get("key"), self.dc_id))
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action == "drop":
                raise ConnectionError(
                    "injected fault: bcounter.transfer dropped")
            elif d.action in ("error", "io_error", "enospc"):
                raise OSError(
                    _errno.EIO,
                    f"injected fault: bcounter.transfer "
                    f"{payload.get('key')!r}")
        if self.transfer_handler is not None:
            # clustered DC: route to the key's owner member, whose
            # coordinator commits the grant through the sequencer
            grant = self.transfer_handler(payload)
        else:
            grant = self.node.txm.bcounters.process_transfer(
                self.node.txm, payload["key"], payload["bucket"],
                payload["amount"], payload["to_dc"],
            )
        m = getattr(self.node, "metrics", None)
        if grant and m is not None:
            m.escrow_grants.inc(role="granter")
        return grant

    def _request_transfer_many(self, dc: int, entries) -> None:
        """Batched rights requests: every shortfall key asking the same
        granter DC this tick rides ONE query-channel round trip
        (``bcounter_many``) instead of one RPC per key — a flash-sale
        tick with hundreds of starved keys was paying hundreds of
        sequential cross-DC round trips before any grant landed.

        The at-most-once discipline is unchanged and PER ENTRY: the
        requester's grace throttle was set before the send for each
        (key, target) pair, so a reply-phase failure of the batch holds
        every member's throttle — no blind resend of any entry inside
        the grace window, exactly as if each had been its own RPC."""
        m = getattr(self.node, "metrics", None)
        t0 = time.monotonic()
        try:
            grants = self.hub.request(
                dc, "bcounter_many",
                {"entries": [[k, b, a] for k, b, a in entries],
                 "to_dc": self.dc_id},
            )
        except Exception as e:
            log.warning(
                "bcounter batched transfer request to dc%d (%d keys) "
                "failed typed (%s); grace throttle holds — no blind "
                "resend", dc, len(entries), e)
            if m is not None:
                m.escrow_grants.inc(role="failed")
            return
        if m is not None:
            m.escrow_transfer_seconds.observe(time.monotonic() - t0)
            for g in (grants or ()):
                if g:
                    m.escrow_grants.inc(role="requester")

    def _request_transfer(self, dc: int, key, bucket: str,
                          amount: int) -> None:
        """One rights request over the AT-MOST-ONCE query channel.

        Grants are non-idempotent commits on the granter, so a reply-
        phase failure (timeout after the request left the socket,
        connection lost before the reply) means the grant MAY have
        committed remotely — this surfaces typed in the log + metrics
        and relies on the grace throttle (set BEFORE the send in
        transfer_periodic) instead of blind-resending; the next tick
        past the grace window re-reads state, so an arrived grant
        retires the shortfall instead of being asked for twice."""
        m = getattr(self.node, "metrics", None)
        t0 = time.monotonic()
        try:
            grant = self.hub.request(
                dc, "bcounter", {"key": key, "bucket": bucket,
                                 "amount": amount, "to_dc": self.dc_id},
            )
        except Exception as e:
            log.warning(
                "bcounter transfer request to dc%d for %r failed typed "
                "(%s); grace throttle holds — no blind resend",
                dc, key, e)
            if m is not None:
                m.escrow_grants.inc(role="failed")
            return
        if m is not None:
            m.escrow_transfer_seconds.observe(time.monotonic() - t0)
            if grant:
                m.escrow_grants.inc(role="requester")

    def bcounter_tick(self) -> int:
        """Run one round of the rights-transfer loop (transfer_periodic,
        /root/reference/src/bcounter_mgr.erl:131-146)."""
        from antidote_tpu.crdt import get_type

        ty = get_type("counter_b")
        txm = self.node.txm

        def read_state(key, bucket):
            # under the commit lock: the write plane grows/reallocates
            # the device tables while committing, and an unsynchronized
            # read_latest from this loop's thread can hit a donated
            # buffer mid-growth
            with txm.commit_lock:
                return txm.store.read_states(
                    [(key, "counter_b", bucket)], txm.store.dc_max_vc()
                )[0]

        sent = txm.bcounters.transfer_periodic(read_state, ty)
        m = getattr(self.node, "metrics", None)
        if m is not None:
            m.escrow_shortfall.set(txm.bcounters.shortfall())
        return sent

    def start_escrow_loop(self, base_s: float = None,
                          seed: int = None) -> "object":
        """The supervised background rights-transfer loop (ISSUE 18;
        bcounter_mgr's ?TRANSFER_FREQ timer) — same ThreadLoop
        discipline as the clock-gossip/pump loops: crashes end the
        thread loudly and the supervisor restarts it.  The interval is
        JITTERED around the base while demand is queued (two DCs'
        loops must not phase-lock their grant traffic) and backs off
        up to 5x base when the queue is empty, snapping back on the
        first refusal."""
        import random

        from antidote_tpu.supervise import ThreadLoop
        from antidote_tpu.txn.bcounter import TRANSFER_FREQ

        base = float(base_s) if base_s is not None else TRANSFER_FREQ
        rng = random.Random(seed if seed is not None else self.dc_id)
        loop = ThreadLoop(lambda: None, interval_s=base,
                          name=f"escrow-pump-{self.name}")

        def tick():
            self.bcounter_tick()
            if self.node.txm.bcounters.pending:
                loop.interval_s = base * (0.5 + rng.random())
            else:
                loop.interval_s = min(loop.interval_s * 1.5 + 1e-3,
                                      base * 5.0)

        loop.fn = tick
        return loop.start()

    def _serve_log_query(self, shard: int, origin: int,
                         from_opid: int) -> List[bytes]:
        """Serve a catch-up read of my own chain
        (inter_dc_query_response:get_entries,
        /root/reference/src/inter_dc_query_response.erl:97-126).

        The bounded in-memory window serves recent requests; anything
        below it is regrouped from the durable log, exactly like the
        reference — so catch-up correctness survives both long uptimes
        (the window caps memory) and restarts."""
        assert origin == self.dc_id
        with self._sent_lock:
            window = self.sent[shard]
            covered = not window or window[0].prev_opid <= from_opid
            if covered:
                return [
                    m.to_bytes() for m in window if m.last_opid > from_opid
                ]
            window_start = window[0].prev_opid
        if self.node.store.log is not None:
            wlog = self.node.store.log
            with self.node.txm.commit_lock:
                base = wlog.chain_base(shard, self.dc_id)
                floor_snap = (base, int(wlog.floor_seqs[shard]))
            if from_opid < base:
                # the requested prefix was checkpoint-compacted away:
                # serving from base would leave an unfillable gap at the
                # subscriber (its chain check only accepts contiguous
                # opids), so refuse loudly — the operator remedy is a
                # fresh subscription / state transfer, and the
                # prevention is retention sized above the slowest peer
                raise RuntimeError(
                    f"catch-up from opid {from_opid} on shard {shard} is "
                    f"below the compaction floor ({base}): that chain "
                    "prefix was checkpoint-truncated and only lives in "
                    "the checkpoint image"
                )
            out = []
            opid = base
            for origin_g, vc, effs in self._wal_txn_groups(
                shard, my_effects_after=from_opid, snap=floor_snap
            ):
                if origin_g != self.dc_id:
                    continue
                opid += 1
                if opid > from_opid:
                    out.append(
                        self._chain_message(shard, opid, vc, effs).to_bytes()
                    )
            return out
        raise RuntimeError(
            f"catch-up from opid {from_opid} on shard {shard} is below the "
            f"in-memory window (starts at {window_start}) and no WAL "
            "is attached to serve it"
        )

    def _ingest_own_origin(self) -> bool:
        """Whether this endpoint applies messages of its OWN dc lane —
        False for peer replicas (they minted that chain), True for
        follower replicas (interdc/follower.py), whose whole data plane
        is the owner's own-origin chain."""
        return False

    # ------------------------------------------------------------------
    # follower replica plane (ISSUE 9): image shipping, digests,
    # liveness registry — all served on the existing request channel
    # ------------------------------------------------------------------
    def _serve_ckpt_meta(self, payload=None) -> "dict | None":
        """Newest published checkpoint image's shippable metadata, or
        None (no durable log / nothing published yet — the follower
        falls back to a whole-chain WAL catch-up).  ``before_id`` in the
        payload restricts to strictly older retained images (follower
        fallback past a corrupt newest).  The reply carries this
        endpoint's CURRENTLY owned shard set: a follower composing a
        clustered owner's store installs each member's image restricted
        to exactly those shards (ISSUE 11)."""
        from antidote_tpu.log import checkpoint as _ckpt

        wlog = self.node.store.log
        if wlog is None:
            return None
        before = (payload or {}).get("before_id")
        meta = _ckpt.latest_image_meta(wlog.dir, before_id=before)
        if meta is not None:
            meta["shards"] = sorted(int(s) for s in self.shards)
        return meta

    def _serve_ckpt_fetch(self, payload) -> dict:
        """One chunk of a published image (``{id, off, n}`` ->
        ``{data, eof}``) — the image-shipping RPC that closes the
        compaction-floor residual: a peer below the floor installs the
        image instead of being refused.  Fault site ``ckpt.ship`` is
        consulted per chunk (chaos holds/kills the shipper mid-image)."""
        import errno as _errno

        from antidote_tpu import faults as _faults
        from antidote_tpu.log import checkpoint as _ckpt

        wlog = self.node.store.log
        assert wlog is not None, "ckpt_fetch on a log-less node"
        ckpt_id = int(payload["id"])
        d = _faults.hit("ckpt.ship", key=f"ckpt_{ckpt_id}")
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action in ("error", "io_error", "enospc"):
                raise OSError(_errno.EIO,
                              f"injected fault: ckpt.ship ckpt_{ckpt_id}")
        if payload.get("file") == "cold":
            # the cold sidecar of a beyond-RAM owner: a follower must
            # ship it alongside the image (its cold keys' state lives
            # only there)
            path = _ckpt.cold_path(wlog.dir, ckpt_id)
        else:
            path = _ckpt.image_path(wlog.dir, ckpt_id)
        off = int(payload.get("off", 0))
        n = int(payload.get("n", self.CKPT_SHIP_CHUNK))
        with open(path, "rb") as f:
            size = f.seek(0, 2)
            f.seek(off)
            data = f.read(max(0, n))
        return {"data": data, "eof": off + len(data) >= size}

    def _serve_shard_digest(self, payload) -> dict:
        """One shard's (applied clock, content digest) under the commit
        lock — the comparable cut a follower checks its own digest
        against.  Equal clocks ⇒ equal applied prefixes ⇒ the digests
        must match; anything else is silent corruption."""
        from antidote_tpu.store.kv import shard_digest

        shard = int(payload["shard"])
        store = self.node.store
        with self.node.txm.commit_lock:
            return {
                "vc": [int(x) for x in store.applied_vc[shard]],
                "digest": shard_digest(store, shard),
                "origins": self._known_origins(),
            }

    def _known_origins(self) -> List[int]:
        """Origin dc lanes this endpoint actually carries chains for —
        the follower's evidence for typing a lag as ``unsubscribed``
        (it was never given that peer's descriptor) instead of
        indefinitely ``skipped``."""
        return sorted({o for (o, _s) in self.last_seen} | {self.dc_id})

    def _serve_peer_origins(self) -> dict:
        return {"origins": self._known_origins()}

    def _serve_merkle_root(self, payload) -> dict:
        """One shard's Merkle root at its applied clock (ISSUE 13): the
        O(1) comparison a follower starts a divergence check with; the
        walk descends through ``merkle_node`` only on a mismatch."""
        from antidote_tpu.store.merkle import get_merkle

        shard = int(payload["shard"])
        store = self.node.store
        with self.node.txm.commit_lock:
            mk = get_merkle(store)
            # a root served for divergence detection must re-read the
            # data: corruption bypasses the incremental marks
            mk.rescan(shard)
            return {
                "vc": [int(x) for x in store.applied_vc[shard]],
                "root": mk.root(shard),
                "leaves": mk.n_leaves,
                "fanout": mk.fanout,
                "depth": mk.depth(),
                "origins": self._known_origins(),
            }

    def _serve_merkle_node(self, payload) -> dict:
        """Child hashes of one tree node — the O(log n) walk step."""
        from antidote_tpu.store.merkle import get_merkle

        shard = int(payload["shard"])
        store = self.node.store
        with self.node.txm.commit_lock:
            mk = get_merkle(store)
            return {
                "vc": [int(x) for x in store.applied_vc[shard]],
                "hashes": mk.children(shard, int(payload["level"]),
                                      int(payload["index"])),
            }

    def _serve_merkle_leaf(self, payload) -> dict:
        """One leaf's raw key states — the range-restricted heal fetch:
        the follower replaces EXACTLY the diverged leaf's rows instead
        of re-installing the whole store.  Served under the commit lock
        so the states are one cut with the returned clock."""
        from antidote_tpu.store.merkle import get_merkle

        shard = int(payload["shard"])
        leaf = int(payload["leaf"])
        store = self.node.store
        with self.node.txm.commit_lock:
            mk = get_merkle(store)
            rows = []
            for key, bucket in sorted(mk.leaf_keys(shard, leaf), key=repr):
                ent = store.directory.get((key, bucket))
                if ent is None and store.cold is not None \
                        and store.cold.is_cold((key, bucket)):
                    ent = store.cold.fault_in((key, bucket), admit=False)
                if ent is None:
                    continue
                tname, _s, row = ent
                t = store.table(tname)
                heads = {}
                for f, x in t.head.items():
                    arr = np.asarray(x[shard, row])
                    heads[f] = {"b": arr.tobytes(), "dt": str(arr.dtype),
                                "sh": list(arr.shape)}
                rows.append([
                    key, bucket, tname, int(t.slots_ub[shard, row]),
                    [int(v) for v in np.asarray(t.head_vc[shard, row])],
                    heads,
                ])
            return {
                "vc": [int(x) for x in store.applied_vc[shard]],
                "keys": rows,
            }

    def _serve_follower_report(self, payload) -> dict:
        """A follower's periodic liveness/lag report.  Decommissioned
        names are refused (``accepted: False``) so a removed replica
        can't silently re-register."""
        name = str(payload.get("name", ""))
        with self._followers_lock:
            if name in self._removed_followers:
                return {"accepted": False}
            ent = self.followers.setdefault(name, {"boots": 0})
            ent["applied"] = [int(x) for x in payload.get("applied") or []]
            ent["addr"] = payload.get("addr")
            ent["state"] = payload.get("state", "serving")
            ent["boots"] = int(payload.get("boots", ent.get("boots", 0)))
            ent["at"] = time.monotonic()
            n_followers = len(self.followers)
        m = getattr(self.node, "metrics", None)
        if m is not None:
            m.fleet_followers.set(n_followers)
        if m is not None and len(ent["applied"]) > self.dc_id:
            lag = max(0, int(self.node.txm.commit_counter)
                      - int(ent["applied"][self.dc_id]))
            m.follower_lag.set(lag, follower=name)
        # piggyback the registry's serving-fleet snapshot on the ACK
        # (ISSUE 17): every follower learns membership + typed states
        # from the report round trip it already makes, feeding its
        # server-side proxy plane's health table.  Computed OUTSIDE the
        # followers lock (replica_status takes it; it is not reentrant).
        fleet = {
            fname: {"addr": fent.get("addr"), "state": fent["state"]}
            for fname, fent in self.replica_status()["followers"].items()
        }
        return {"accepted": True,
                "commit_counter": int(self.node.txm.commit_counter),
                "fleet": fleet}

    def replica_status(self) -> dict:
        """The node-status / console ``replica status`` block: every
        known follower with its typed liveness state (ok | lagging |
        down | its self-reported bootstrap state) and applied-VC lag."""
        now = time.monotonic()
        counter = int(self.node.txm.commit_counter)
        out: Dict[str, dict] = {}
        with self._followers_lock:
            snap = {k: dict(v) for k, v in self.followers.items()}
        for name, ent in sorted(snap.items()):
            at = ent.get("at", 0.0)
            applied = ent.get("applied") or []
            lag = (max(0, counter - int(applied[self.dc_id]))
                   if len(applied) > self.dc_id else None)
            if not at or now - at > self.REPLICA_DOWN_S:
                state = "down"
            elif ent.get("state") not in (None, "serving"):
                state = str(ent["state"])  # bootstrapping / healing
            elif lag is not None and lag > self.REPLICA_LAG_OPS:
                state = "lagging"
            else:
                state = "ok"
            out[name] = {
                "state": state,
                "lag": lag,
                "age_s": round(now - at, 2) if at else None,
                "addr": ent.get("addr"),
                "boots": ent.get("boots", 0),
            }
        return {"role": "owner", "followers": out}

    def replica_admin(self, body: dict) -> dict:
        """The wire REPLICA_ADMIN op (console replica add/remove/
        status): add pre-registers an expected follower (shows "down"
        until its first report and clears any decommission tombstone);
        remove decommissions the name (its future reports are refused);
        status returns :meth:`replica_status`."""
        op = body.get("op", "status")
        if op == "status":
            return self.replica_status()
        name = str(body["name"])
        if op == "add":
            with self._followers_lock:
                self._removed_followers.discard(name)
                ent = self.followers.setdefault(name, {"boots": 0})
                if body.get("addr"):
                    ent["addr"] = list(body["addr"])
            return self.replica_status()
        if op == "remove":
            with self._followers_lock:
                self.followers.pop(name, None)
                self._removed_followers.add(name)
            m = getattr(self.node, "metrics", None)
            if m is not None:
                m.follower_lag.set(0, follower=name)
            return self.replica_status()
        raise ValueError(f"unknown replica admin op {op!r}")

    # ------------------------------------------------------------------
    # ingress
    # ------------------------------------------------------------------
    def _on_message(self, data: bytes) -> None:
        try:
            msg = TxnMessage.from_bytes(data)
        except Exception:
            # a frame corrupted in transit (truncation, bit rot) must not
            # kill the pump: discard it — if it carried a txn, the chain
            # gap surfaces on the next message and catch-up replays it
            # from the publisher's log
            from antidote_tpu.obs.metrics import net_metrics

            net_metrics().corrupt_frames.inc()
            log.warning("discarding undecodable inter-DC frame (%d bytes)",
                        len(data))
            return
        if msg.origin == self.dc_id and not self._ingest_own_origin():
            # a peer DC never applies its own origin's chain (it minted
            # it); a FOLLOWER of this dc_id does — that chain IS its data
            return
        # INGRESS STATE DISCIPLINE: last_seen/pending/gate mutate only
        # under the node's commit lock — the same lock the gate drain
        # and (via the member's cross-plane lock) live shard
        # export/import/relinquish hold.  Without it, a relinquish can
        # clear a shard's chain state while this handler is mid-flight
        # and the resurrected entries would apply remote effects to the
        # dropped slice.  The catch-up NETWORK call stays outside the
        # lock (a dead endpoint's 30 s timeout must not freeze local
        # commits); ownership is re-checked after it returns.
        lock = self.node.txm.commit_lock
        key = (msg.origin, msg.shard)
        catchup_from = None
        with lock:
            self._learn_route(msg)
            if msg.shard not in self.shards:
                return
            last = self.last_seen.get(key, 0)
            if msg.is_ping:
                if msg.last_opid <= last:
                    self._queue(msg)
                    self._drain_gates()
                    return
                # the ping reveals lost txns: catch up before trusting it
                catchup_from = last
            elif msg.prev_opid == last:
                self._accept(key, msg)
                self._drain_gates()
                return
            elif msg.prev_opid > last:
                # gap: buffer and query the origin's log reader (the
                # catch-up's pending flush integrates this message).
                # BOUNDED: past the high-water mark the message is shed —
                # it sits above last_seen, so the still-open gap makes
                # catch-up refetch it once the buffer drains
                if len(self.pending[key]) >= self.PENDING_HWM:
                    self._shed_ingress(key, "pending")
                else:
                    self.pending[key].append(msg)
                catchup_from = last
            else:
                return  # duplicate — drop
        self._catch_up(key, catchup_from)
        with lock:
            if msg.shard not in self.shards:
                return  # relinquished while we were catching up
            if msg.is_ping:
                if msg.last_opid > self.last_seen.get(key, 0):
                    # the catch-up could NOT close the gap (severed query
                    # channel, stale route to a dead old owner): trusting
                    # the ping would advance the chain clock past the
                    # undelivered txns, and the duplicate suppression
                    # would then drop their eventual replay forever — a
                    # permanently lost effect.  Drop the PING instead;
                    # the publisher re-sends on its 1 s cadence and the
                    # next one retries the catch-up.
                    return
                self._queue(msg)
            self._drain_gates()

    def _learn_route(self, msg: TxnMessage) -> None:
        """Adopt a publisher's shard-ownership gossip: strictly newer
        epochs re-point this chain's catch-up route at the new owner
        (replayed/stale frames can never resurrect a previous one)."""
        if msg.owner is None:
            return
        rk = (msg.origin, msg.shard)
        oe = int(msg.oepoch or 0)
        cur = self.shard_route.get(rk)
        if cur is not None and oe <= cur[1]:
            return
        self.shard_route[rk] = (int(msg.owner), oe)
        if cur is not None and cur[0] != int(msg.owner):
            from antidote_tpu.obs.metrics import net_metrics

            net_metrics().route_updates.inc()
            log.info("chain %s: catch-up re-routed to member %d "
                     "(epoch %d, was member %d)", rk, msg.owner, oe, cur[0])

    def _route(self, origin: int, shard: int) -> int:
        """Fabric id serving a chain's catch-up: the newest gossiped
        owner when one is known, else the configured fallback router."""
        ent = self.shard_route.get((origin, shard))
        if ent is not None:
            from antidote_tpu.cluster import fabric_id_of

            return fabric_id_of(origin, ent[0])
        return self.route_query(origin, shard)

    def _catch_up(self, key, from_opid) -> None:
        origin, shard = key
        target = self._route(origin, shard)
        try:
            msgs = self.hub.query_log(target, shard, origin, from_opid)
        except (ConnectionError, OSError, KeyError) as e:
            # the query channel is down (partition, endpoint restart) or
            # the routed endpoint's address is not yet known (KeyError —
            # gossip can outrun the operator's descriptor wiring for a
            # just-joined member): keep the out-of-order buffer and
            # return — every later ping on this chain re-reveals the gap
            # and retries the catch-up, so healing the link (or wiring
            # the endpoint) heals the chain with no operator action
            from antidote_tpu.obs.metrics import net_metrics

            net_metrics().catchup_failures.inc()
            log.warning("catch-up query to dc%s for chain %s failed (%r); "
                        "will retry on the next chain message", target, key, e)
            return
        # the replayed suffix lands under the commit lock (same ingress
        # discipline as _on_message), with ownership re-checked: the
        # shard may have been relinquished while the query was in flight
        with self.node.txm.commit_lock:
            if shard not in self.shards:
                return
            for data in msgs:
                m = TxnMessage.from_bytes(data)
                if not m.is_ping and m.prev_opid == self.last_seen.get(key, 0):
                    self._accept(key, m)
            self._flush_pending(key)

    def _shed_ingress(self, key, where: str) -> None:
        """Count + (throttled) log one shed ingress message.  The shed
        NEVER advances ``last_seen``, so it is indistinguishable from a
        lossy link: the publisher's next chain message re-reveals the
        gap and catch-up replays the loss once pressure drains — shed is
        deferral into the repair path, not data loss.  The publisher
        sees the pressure as catch-up queries against its log (plus the
        antidote_interdc_ingress_shed_total counter here)."""
        from antidote_tpu.obs.metrics import net_metrics

        net_metrics().ingress_shed.inc()
        now = time.monotonic()
        if now - getattr(self, "_last_shed_log", 0.0) > 5.0:
            self._last_shed_log = now
            log.warning("ingress gate for chain %s past its %s high-water "
                        "mark; shedding (catch-up will refill)", key, where)

    def _gate_full(self, key) -> bool:
        q = self.gate.get(key)
        return q is not None and len(q) >= self.GATE_HWM

    def _accept(self, key, msg: TxnMessage) -> None:
        # BOUNDED gate: a chain at its high-water mark (dep-blocked head
        # under a delivery storm) sheds instead of queueing — last_seen
        # stays put, so the skipped suffix returns through catch-up
        if self._gate_full(key):
            self._shed_ingress(key, "gate")
            return
        self.last_seen[key] = msg.last_opid
        self._queue(msg)
        self._flush_pending(key)

    def _flush_pending(self, key) -> None:
        """Drain the out-of-order buffer: one pass over the buffer sorted
        by chain position (the old repeated-rescan was O(n²), r2 VERDICT
        weak #6).  Stops flushing (keeps the tail buffered) once the gate
        hits its high-water mark — same bound as _accept."""
        buf = self.pending.get(key)
        if not buf:
            return
        buf.sort(key=lambda m: m.prev_opid)
        keep: List[TxnMessage] = []
        for m in buf:
            last = self.last_seen.get(key, 0)
            if m.prev_opid == last and not self._gate_full(key):
                self.last_seen[key] = m.last_opid
                self._queue(m)
            elif m.last_opid > last:
                keep.append(m)  # still a gap ahead of it (or gate full)
            # else: duplicate — drop
        self.pending[key] = keep

    # ------------------------------------------------------------------
    # causal dependency gate
    # ------------------------------------------------------------------
    def _queue(self, msg: TxnMessage) -> None:
        q = self.gate[(msg.origin, msg.shard)]
        if msg.is_ping and q and q[-1].is_ping:
            # coalesce trailing pings: per-chain ping timestamps are
            # monotone and the drain only reads the LAST one, so a
            # blocked head accumulates at most one parked ping instead
            # of one per heartbeat interval
            q[-1] = msg
            return
        q.append(msg)
        # gate-depth gauge refresh is the drain path's job (_drain_gates
        # runs on every delivery pump): an O(#chains) sum per enqueued
        # message would tax the hot ingress path for a gauge

    def _drain_gates(self) -> None:
        """Apply every gated txn whose dependencies are satisfied; loop
        until no queue makes progress (process_all_queues,
        /root/reference/src/inter_dc_dep_vnode.erl:96-103).

        Ready txns are BATCHED into one ``apply_effects`` device launch
        per drain round: readiness cascades are evaluated against a
        simulated clock copy, and the real partition clocks only advance
        after the whole batch applied (the stable snapshot must never
        dominate unapplied ops — including ping advances, which are
        deferred the same way so a ping queued behind a txn cannot claim
        its ts early).

        SERIALIZATION: the whole drain runs under the transaction
        manager's commit lock.  A server-thread commit applies effects
        via the same ``KVStore.apply_effects`` read-modify-reassign of
        the device tables (``t.ops_a = t.ops_a.at[...].set(...)``); two
        concurrent appliers can silently drop a whole batch, and the
        chain-clock duplicate suppression then makes the loss permanent
        (r5 advisor high).  The lock is reentrant and taken in the same
        order everywhere (endpoint handler lock → commit lock), so the
        remote-ingress plane and the local-commit plane are mutually
        exclusive writers, mirroring how bcounter grants are already
        excluded via the endpoint lock."""
        with self.node.txm.commit_lock:
            self._drain_gates_locked()
            if self.node.metrics is not None:
                self.node.metrics.interdc_gate_depth.set(
                    sum(len(g) for g in self.gate.values()))

    def _drain_gates_locked(self) -> None:
        store = self.node.store
        while True:
            sim = store.applied_vc.copy()
            batch: list = []  # ready txns, dependency-respecting order
            advances: list = []  # (shard, origin, ts) after apply
            taken: Dict[tuple, int] = {}  # gate key -> msgs consumed
            progressed = True
            while progressed:
                progressed = False
                for gk, q in self.gate.items():
                    origin, shard = gk
                    i = taken.get(gk, 0)
                    for msg in itertools.islice(q, i, None):
                        if msg.is_ping:
                            ts = msg.timestamp
                            if sim[shard, origin] < ts:
                                sim[shard, origin] = ts
                                advances.append((shard, origin, ts))
                            i += 1
                            progressed = True
                            continue
                        # duplicate suppression: per-chain origin
                        # timestamps are strictly monotone, and the chain
                        # clock only advances past ts once the txn
                        # carrying ts was applied (or a catch-up replayed
                        # it) — so ts ≤ clock ⟺ already applied.  Makes
                        # re-delivery (restart catch-up from a
                        # conservative opid) idempotent.
                        ts = int(msg.commit_vc[origin])
                        if ts <= int(sim[shard, origin]):
                            i += 1
                            progressed = True
                            continue
                        local = sim[shard].copy()
                        local[origin] = 0
                        if not (local >= msg.snapshot_vc).all():
                            # dep-blocked head.  Pings QUEUED BEHIND it
                            # may still advance this lane up to ts-1:
                            # everything below the head's ts is applied
                            # (chain order), so duplicate suppression
                            # survives — and without this, two chains
                            # can deadlock after message loss (each
                            # head's unblocking ping trapped behind the
                            # other's blocked head; the reference's
                            # heartbeats advance clocks outside the
                            # txn queue for the same reason,
                            # /root/reference/src/inter_dc_dep_vnode.erl:122-125)
                            # per-chain ping timestamps are monotone:
                            # the LAST ping in the queue carries the max
                            best = 0
                            for m2 in reversed(q):
                                if m2.is_ping:
                                    best = m2.timestamp
                                    break
                            adv = min(best, ts - 1)
                            if adv > sim[shard, origin]:
                                sim[shard, origin] = adv
                                advances.append((shard, origin, adv))
                                progressed = True
                            break
                        batch.append((msg, origin))
                        sim[shard, origin] = ts
                        advances.append((shard, origin, ts))
                        i += 1
                        progressed = True
                    taken[gk] = i
            if not batch and not advances:
                # still consume the examined prefix (duplicates, stale
                # pings): leaving it queued forever is a leak AND makes
                # every later drain rescan it
                for gk, n in taken.items():
                    q = self.gate[gk]
                    for _ in range(n):
                        q.popleft()
                return
            if batch:
                effects, vcs, origins = [], [], []
                for msg, origin in batch:
                    vc = np.asarray(msg.commit_vc, np.int32)
                    for eff in msg.effects:
                        effects.append(eff)
                        vcs.append(vc)
                        origins.append(origin)
                # messages are consumed from the queues only AFTER the
                # apply succeeds — an exception leaves everything queued
                # for the next drain instead of silently dropping txns
                with self.store_lock:
                    store.apply_effects(effects, vcs, origins)
            for gk, n in taken.items():
                q = self.gate[gk]
                for _ in range(n):
                    q.popleft()
            for shard, origin, ts in advances:
                self._advance_clock(shard, origin, ts)

    def _advance_clock(self, shard: int, origin: int, ts: int) -> None:
        vc = self.node.store.applied_vc
        if vc[shard, origin] < ts:
            vc[shard, origin] = ts

    # ------------------------------------------------------------------
    def _on_clock_wait(self) -> None:
        """Called by the txn manager while waiting for the stable snapshot
        to catch up to a client clock (the wait_for_clock spin,
        /root/reference/src/clocksi_interactive_coord.erl:915-926).  An
        idle pump sleeps a moment so the spin paces real time — cluster
        peers' safe times advance on wall-clock cadences (sequencer cache,
        heartbeat timers), not on our loop iterations."""
        if self.hub.pump() == 0:
            time.sleep(0.002)
