"""DCReplica — inter-DC replication endpoint for one replica.

Combines the reference's egress and ingress pipelines (SURVEY §3.4):

  egress:  local commit → per-shard TxnMessage with (shard, origin) opid
           chaining → transport publish
           (inter_dc_log_sender_vnode + inter_dc_pub)
  ingress: message → per-(origin, shard) chain check: eq→deliver,
           gt→buffer + log catch-up query, lt→drop duplicate
           (inter_dc_sub_buf, /root/reference/src/inter_dc_sub_buf.erl:98-142)
           → causal dependency gate: apply once the shard clock dominates
           the txn's snapshot VC with the origin lane zeroed
           (inter_dc_dep_vnode:try_store,
           /root/reference/src/inter_dc_dep_vnode.erl:128-154)
  heartbeats: empty txns carrying the origin's safe time so remote stable
           snapshots advance when idle
           (/root/reference/src/inter_dc_log_sender_vnode.erl:133-143)
"""

from __future__ import annotations

import collections
from typing import Dict, List, Tuple

import numpy as np

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.interdc.messages import Descriptor, TxnMessage
from antidote_tpu.interdc.transport import LoopbackHub
from antidote_tpu.store.kv import effect_from_rec


class DCReplica:
    def __init__(self, node: AntidoteNode, hub: LoopbackHub, name: str = ""):
        self.node = node
        self.hub = hub
        self.name = name or f"dc{node.dc_id}"
        self.dc_id = node.dc_id
        p = node.cfg.n_shards
        #: egress opid chain per shard (my origin)
        self.pub_opid = np.zeros(p, np.int64)
        #: sent messages per shard, for catch-up queries (reference reads
        #: these back from its op log; kept in memory here, WAL-backed later)
        self.sent: List[List[TxnMessage]] = [[] for _ in range(p)]
        #: ingress: last delivered opid per (origin, shard)
        self.last_seen: Dict[Tuple[int, int], int] = {}
        #: ingress: out-of-order buffer per (origin, shard)
        self.pending: Dict[Tuple[int, int], List[TxnMessage]] = (
            collections.defaultdict(list)
        )
        #: causal gate FIFO per (origin, shard)
        self.gate: Dict[Tuple[int, int], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        hub.register(self.dc_id, self._on_message, self._serve_log_query)
        hub.register_request(self.dc_id, self._serve_request)
        node.txm.commit_listeners.append(self._on_local_commit)
        node.txm.on_clock_wait = self._on_clock_wait
        # bcounter rights requests ride the query channel (?BCOUNTER_REQUEST)
        node.txm.bcounters.request_transfer = (
            lambda dc, key, bucket, n: self.hub.request(
                dc, "bcounter", {"key": key, "bucket": bucket, "amount": n,
                                 "to_dc": self.dc_id},
            )
        )

    # ------------------------------------------------------------------
    # restart (check_node_restart, /root/reference/src/inter_dc_manager.erl:156-206)
    # ------------------------------------------------------------------
    def restore_from_log(self) -> None:
        """Rebuild replication chains after a node restart from its WAL.

        Egress: my own-origin records regroup into per-shard TxnMessages
        with fresh sequential opids, so peers' catch-up queries keep
        working (the reference re-reads its disk log for this,
        /root/reference/src/inter_dc_query_response.erl:97-126).
        Ingress: each remote (origin, shard) chain's delivered-txn count
        IS the publisher's opid (one opid per txn per shard, delivered
        exactly once in order), so ``last_seen`` reseeds from the log
        (inter_dc_sub_buf restart seeding,
        /root/reference/src/inter_dc_sub_buf.erl:58-76).
        """
        store = self.node.store
        assert store.log is not None, "restore_from_log needs a WAL"
        for shard in range(self.node.cfg.n_shards):
            groups: List[Tuple[int, tuple, list]] = []  # (origin, vc, effs)
            for rec in store.log.replay_shard(shard):
                vc = tuple(int(x) for x in rec["vc"])
                mine = int(rec["o"]) == self.dc_id
                # effects are only materialized for my own chain (egress
                # rebuild); remote groups just count toward last_seen
                if groups and groups[-1][0] == rec["o"] and groups[-1][1] == vc:
                    if mine:
                        groups[-1][2].append(effect_from_rec(rec))
                else:
                    groups.append((
                        int(rec["o"]), vc,
                        [effect_from_rec(rec)] if mine else [],
                    ))
            counts: Dict[int, int] = collections.defaultdict(int)
            for origin, vc, effs in groups:
                counts[origin] += 1
                if origin != self.dc_id:
                    continue
                prev = int(self.pub_opid[shard])
                self.pub_opid[shard] += 1
                cvc = np.asarray(vc, np.int32)
                svc = cvc.copy()
                svc[origin] = 0
                self.sent[shard].append(TxnMessage(
                    origin=origin, shard=shard, prev_opid=prev,
                    last_opid=prev + 1, commit_vc=cvc, snapshot_vc=svc,
                    effects=effs, timestamp=int(cvc[origin]),
                ))
            for origin, n in counts.items():
                if origin != self.dc_id:
                    self.last_seen[(origin, shard)] = n

    # ------------------------------------------------------------------
    def descriptor(self) -> Descriptor:
        return Descriptor(self.dc_id, self.name, self.node.cfg.n_shards)

    def observe_dc(self, remote: "DCReplica") -> None:
        """Subscribe to a remote DC's txn stream
        (inter_dc_manager:observe_dcs_sync,
        /root/reference/src/inter_dc_manager.erl:67-109)."""
        self.hub.subscribe(self.dc_id, remote.dc_id, self._on_message)

    @staticmethod
    def connect_all(replicas: List["DCReplica"]) -> None:
        for a in replicas:
            for b in replicas:
                if a is not b:
                    a.observe_dc(b)

    # ------------------------------------------------------------------
    # egress
    # ------------------------------------------------------------------
    def _on_local_commit(self, effects, commit_vc, origin) -> None:
        by_shard: Dict[int, list] = {}
        for eff in effects:
            _, shard, _ = self.node.store.locate(eff.key, eff.type_name,
                                                 eff.bucket)
            by_shard.setdefault(shard, []).append(eff)
        snapshot_vc = np.asarray(commit_vc, np.int32).copy()
        snapshot_vc[origin] = 0
        for shard, effs in by_shard.items():
            prev = int(self.pub_opid[shard])
            self.pub_opid[shard] += 1
            msg = TxnMessage(
                origin=origin, shard=shard, prev_opid=prev,
                last_opid=prev + 1,
                commit_vc=np.asarray(commit_vc, np.int32),
                snapshot_vc=snapshot_vc, effects=effs,
                timestamp=int(commit_vc[origin]),
            )
            self.sent[shard].append(msg)
            self.hub.publish(self.dc_id, msg.to_bytes())
        # advance idle shards remotely (reference: 1 s heartbeat timer;
        # in-process we piggyback on commits and explicit heartbeat())
        self.heartbeat(exclude=set(by_shard))

    def heartbeat(self, exclude=frozenset()) -> None:
        """Broadcast the origin's safe time for every shard: no future local
        commit will carry a smaller origin timestamp (commits are minted
        from a monotone counter)."""
        safe = self.node.txm.commit_counter
        # advance MY lane on idle local shards too: local commits apply
        # synchronously, so every own-lane op ≤ safe is already applied on
        # every shard — without this, a remote txn whose snapshot depends
        # on my lane would gate forever on shards I never wrote to (the
        # reference's per-partition safe time does the same job,
        # /root/reference/src/inter_dc_log_sender_vnode.erl:133-143)
        vc = self.node.store.applied_vc
        np.maximum(vc[:, self.dc_id], safe, out=vc[:, self.dc_id])
        for shard in range(self.node.cfg.n_shards):
            if shard in exclude:
                continue
            prev = int(self.pub_opid[shard])
            msg = TxnMessage(
                origin=self.dc_id, shard=shard, prev_opid=prev,
                last_opid=prev,  # pings do not advance the chain
                commit_vc=np.zeros(self.node.cfg.max_dcs, np.int32),
                snapshot_vc=np.zeros(self.node.cfg.max_dcs, np.int32),
                effects=[], timestamp=safe,
            )
            self.hub.publish(self.dc_id, msg.to_bytes())

    def _serve_request(self, kind: str, payload) -> object:
        """Generic query-channel dispatch (inter_dc_query_receive_socket,
        /root/reference/src/inter_dc_query_receive_socket.erl:111-139)."""
        if kind == "bcounter":
            return self.node.txm.bcounters.process_transfer(
                self.node.txm, payload["key"], payload["bucket"],
                payload["amount"], payload["to_dc"],
            )
        if kind == "check_up":
            return True
        raise ValueError(f"unknown request kind {kind!r}")

    def bcounter_tick(self) -> int:
        """Run one round of the rights-transfer loop (transfer_periodic,
        /root/reference/src/bcounter_mgr.erl:131-146)."""
        from antidote_tpu.crdt import get_type

        ty = get_type("counter_b")
        txm = self.node.txm

        def read_state(key, bucket):
            return txm.store.read_states(
                [(key, "counter_b", bucket)], txm.store.dc_max_vc()
            )[0]

        return txm.bcounters.transfer_periodic(read_state, ty)

    def _serve_log_query(self, shard: int, origin: int,
                         from_opid: int) -> List[bytes]:
        """Serve a catch-up read of my own chain
        (inter_dc_query_response:get_entries,
        /root/reference/src/inter_dc_query_response.erl:97-126)."""
        assert origin == self.dc_id
        return [
            m.to_bytes() for m in self.sent[shard] if m.last_opid > from_opid
        ]

    # ------------------------------------------------------------------
    # ingress
    # ------------------------------------------------------------------
    def _on_message(self, data: bytes) -> None:
        msg = TxnMessage.from_bytes(data)
        if msg.origin == self.dc_id:
            return
        key = (msg.origin, msg.shard)
        last = self.last_seen.get(key, 0)
        if msg.is_ping:
            if msg.last_opid > last:
                # the ping reveals lost txns: catch up before trusting it
                self._catch_up(key, last)
            self._queue(msg)
            self._drain_gates()
            return
        if msg.prev_opid == self.last_seen.get(key, 0):
            self._accept(key, msg)
        elif msg.prev_opid > self.last_seen.get(key, 0):
            # gap: buffer and query the origin's log reader
            self.pending[key].append(msg)
            self._catch_up(key, self.last_seen.get(key, 0))
        # else: duplicate — drop
        self._drain_gates()

    def _catch_up(self, key, from_opid) -> None:
        origin, shard = key
        for data in self.hub.query_log(origin, shard, origin, from_opid):
            m = TxnMessage.from_bytes(data)
            if not m.is_ping and m.prev_opid == self.last_seen.get(key, 0):
                self._accept(key, m)
        self._flush_pending(key)

    def _accept(self, key, msg: TxnMessage) -> None:
        self.last_seen[key] = msg.last_opid
        self._queue(msg)
        self._flush_pending(key)

    def _flush_pending(self, key) -> None:
        progressed = True
        while progressed:
            progressed = False
            for m in list(self.pending[key]):
                if m.prev_opid == self.last_seen.get(key, 0):
                    self.pending[key].remove(m)
                    self.last_seen[key] = m.last_opid
                    self._queue(m)
                    progressed = True
                elif m.last_opid <= self.last_seen.get(key, 0):
                    self.pending[key].remove(m)  # duplicate
                    progressed = True

    # ------------------------------------------------------------------
    # causal dependency gate
    # ------------------------------------------------------------------
    def _queue(self, msg: TxnMessage) -> None:
        self.gate[(msg.origin, msg.shard)].append(msg)

    def _drain_gates(self) -> None:
        """Apply every gated txn whose dependencies are satisfied; loop
        until no queue makes progress (process_all_queues,
        /root/reference/src/inter_dc_dep_vnode.erl:96-103)."""
        progressed = True
        while progressed:
            progressed = False
            for (origin, shard), q in self.gate.items():
                while q:
                    msg = q[0]
                    if msg.is_ping:
                        self._advance_clock(shard, origin, msg.timestamp)
                        q.popleft()
                        progressed = True
                        continue
                    # duplicate suppression: per-chain origin timestamps are
                    # strictly monotone, and the chain clock only advances
                    # past ts once the txn carrying ts was applied (or a
                    # catch-up replayed it) — so ts ≤ clock ⟺ already
                    # applied.  Makes re-delivery (restart catch-up from a
                    # conservative opid) idempotent.
                    if (int(msg.commit_vc[origin])
                            <= int(self.node.store.applied_vc[shard, origin])):
                        q.popleft()
                        progressed = True
                        continue
                    local = self.node.store.applied_vc[shard].copy()
                    local[origin] = 0
                    dep_ok = (local >= msg.snapshot_vc).all()
                    if not dep_ok:
                        break
                    self.node.txm.apply_remote(
                        msg.effects, msg.commit_vc, origin
                    )
                    self._advance_clock(shard, origin,
                                        int(msg.commit_vc[origin]))
                    q.popleft()
                    progressed = True

    def _advance_clock(self, shard: int, origin: int, ts: int) -> None:
        vc = self.node.store.applied_vc
        if vc[shard, origin] < ts:
            vc[shard, origin] = ts

    # ------------------------------------------------------------------
    def _on_clock_wait(self) -> None:
        """Called by the txn manager while waiting for the stable snapshot
        to catch up to a client clock (the wait_for_clock spin,
        /root/reference/src/clocksi_interactive_coord.erl:915-926)."""
        self.hub.pump()
