"""ctypes binding for the native inter-DC stream pump (cpp/pump.cc).

One C++ epoll thread owns every subscription socket: kernel reads and
frame assembly happen in native code (the role libzmq's io threads play
for the reference, /root/reference/src/inter_dc_sub.erl); Python drains
whole frames.  Compiled on first use like the WAL and router; loading
failure falls back to the per-subscription Python reader threads.
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
from typing import Optional, Tuple

from antidote_tpu import faults

log = logging.getLogger(__name__)


def _fallback(reason: Optional[str]) -> None:
    """Count + log a native-plane fallback; returns None (the create()
    contract for "use the Python readers")."""
    if reason is not None:
        log.warning("native pump unavailable (%s); falling back to "
                    "Python reader threads", reason)
    try:
        from antidote_tpu.obs.metrics import net_metrics

        net_metrics().pump_fallback.inc()
    except Exception:
        pass
    return None


_DIR = pathlib.Path(__file__).parent / "cpp"
_SRC = _DIR / "pump.cc"
_SO = _DIR / "_pump.so"

_lib = None
_lib_tried = False


def _load_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        # pinned-flag build through the shared helper (make native /
        # make native-check provenance: the .so embeds its source sha)
        from antidote_tpu import native_build

        native_build.ensure(_SRC, _SO)
        lib = ctypes.CDLL(str(_SO))
        lib.pump_new.restype = ctypes.c_void_p
        lib.pump_new.argtypes = []
        lib.pump_add.restype = ctypes.c_int
        lib.pump_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_long]
        lib.pump_take.restype = ctypes.c_long
        lib.pump_take.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_long), ctypes.c_int,
        ]
        lib.pump_take_batch.restype = ctypes.c_long
        lib.pump_take_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_int,
        ]
        lib.pump_queued.restype = ctypes.c_long
        lib.pump_queued.argtypes = [ctypes.c_void_p]
        lib.pump_free.restype = None
        lib.pump_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


class NativePump:
    """Owns detached socket fds; yields (tag, kind, payload) frames."""

    _BATCH = 512

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.pump_new()
        self._buf = ctypes.create_string_buffer(1 << 20)
        self._descs = (ctypes.c_long * (3 * self._BATCH))()

    #: frame kind queued by the native loop when a subscription socket
    #: drops (EOF/read error/corrupt frame) — carries the tag, empty
    #: payload.  The fabric resubscribes with backoff on seeing it.
    K_CONN_DROP = 0

    @staticmethod
    def create() -> Optional["NativePump"]:
        if os.environ.get("ANTIDOTE_NATIVE_PUMP", "on") == "off":
            return None
        if faults.hit("native_pump.load") is not None:
            return _fallback(None)  # injected load failure (chaos tests)
        lib = _load_lib()
        if lib is None:
            return _fallback("compile/load failed")
        p = NativePump(lib)
        if p._h is None:
            # pump_new() failed (NULL → ctypes None — fd exhaustion or a
            # blocked epoll/eventfd syscall).  A pump with no epoll loop
            # would close every detached fd handed to add(), silently
            # blackholing each subscription; report the failure so
            # TcpFabric.subscribe keeps the Python reader threads.
            return _fallback("pump_new returned NULL")
        return p

    def add(self, fd: int, tag: int) -> None:
        """Register a connected socket fd; the pump OWNS it from here
        (pass ``sock.detach()``)."""
        if self._h is None:
            os.close(fd)  # closed pump: don't leak the detached fd
            return
        self._lib.pump_add(self._h, fd, tag)

    def take(self, timeout_ms: int) -> Optional[Tuple[int, int, bytes]]:
        if self._h is None:
            return None  # closed concurrently (fabric teardown)
        tag = ctypes.c_long()
        kind = ctypes.c_int()
        need = ctypes.c_long()
        n = self._lib.pump_take(self._h, self._buf,
                                len(self._buf), ctypes.byref(tag),
                                ctypes.byref(kind), ctypes.byref(need),
                                int(timeout_ms))
        if n == -2:
            # frame larger than the scratch buffer: grow and retake
            self._buf = ctypes.create_string_buffer(int(need.value) + 1024)
            return self.take(timeout_ms)
        if n < 0:
            return None
        return (int(tag.value), int(kind.value),
                ctypes.string_at(self._buf, n))

    def take_batch(self, timeout_ms: int) -> list:
        """Drain up to _BATCH frames in one native crossing —
        [(tag, kind, payload)], [] after timeout."""
        if self._h is None:
            return []  # closed concurrently (fabric teardown)
        n = self._lib.pump_take_batch(self._h, self._buf, len(self._buf),
                                      self._descs, self._BATCH,
                                      int(timeout_ms))
        if n <= 0:
            # nothing, or the head frame alone exceeds the scratch
            # buffer — the single-frame path grows the buffer
            if n == 0 and self.queued() > 0:
                f = self.take(0)
                return [f] if f is not None else []
            return []
        d = self._descs
        total = sum(d[i * 3 + 2] for i in range(n))
        # copy only the bytes actually written, not the whole scratch
        # buffer (it only ever grows)
        raw = ctypes.string_at(self._buf, total)
        out = []
        off = 0
        for i in range(n):
            ln = d[i * 3 + 2]
            out.append((int(d[i * 3]), int(d[i * 3 + 1]),
                        raw[off:off + ln]))
            off += ln
        return out

    def queued(self) -> int:
        if self._h is None:
            return 0
        return int(self._lib.pump_queued(self._h))

    def close(self) -> None:
        if self._h is not None:
            self._lib.pump_free(self._h)
            self._h = None
