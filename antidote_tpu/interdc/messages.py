"""Inter-DC wire format.

The analogue of ``#interdc_txn{}`` (/root/reference/include/inter_dc_repl.hrl:16-25)
— per-shard transaction messages with ``prev_log_opid`` chaining for loss
detection — serialized with msgpack instead of ``term_to_binary``
(/root/reference/src/inter_dc_txn.erl:95-105).  Blob payloads referenced by
the effects ride along so the receiving DC can resolve value handles.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import msgpack
import numpy as np

from antidote_tpu.store.kv import Effect, freeze_key


@dataclasses.dataclass
class TxnMessage:
    """One origin-DC transaction's effects for ONE shard (or a heartbeat
    when ``effects`` is empty — inter_dc_txn:is_ping,
    /root/reference/src/inter_dc_txn.erl:63-71)."""

    origin: int                    # origin DC lane
    shard: int                     # target shard
    prev_opid: int                 # last opid of this (shard, origin) chain
    last_opid: int                 # opid of this message's final effect
    commit_vc: np.ndarray          # i32[D]
    snapshot_vc: np.ndarray        # i32[D] — causal deps (origin lane = 0)
    effects: List[Effect]
    #: heartbeat safe time: no future txn from origin will commit below this
    timestamp: int = 0
    #: publisher-side shard ownership gossip (clustered origins): the
    #: member id currently owning this shard's chain and its ownership
    #: EPOCH (bumped per live move).  Subscribers re-route catch-up
    #: queries to the newest-epoch owner — the inter_dc_sub re-wiring of
    #: /root/reference/src/inter_dc_sub.erl:123-145, without a
    #: reconnect.  None (absent on the wire) for single-member origins.
    owner: Optional[int] = None
    oepoch: Optional[int] = None

    @property
    def is_ping(self) -> bool:
        return not self.effects

    def to_bytes(self) -> bytes:
        extra = {}
        if self.owner is not None:
            extra["ow"] = int(self.owner)
            extra["oe"] = int(self.oepoch or 0)
        return msgpack.packb({
            "o": self.origin,
            "p": self.shard,
            "prev": self.prev_opid,
            "last": self.last_opid,
            "cvc": [int(x) for x in np.asarray(self.commit_vc)],
            "svc": [int(x) for x in np.asarray(self.snapshot_vc)],
            "ts": self.timestamp,
            **extra,
            "effs": [
                {
                    "k": e.key, "t": e.type_name, "b": e.bucket,
                    "a": np.asarray(e.eff_a, np.int64).tobytes(),
                    "eb": np.asarray(e.eff_b, np.int32).tobytes(),
                    "bl": [(int(h), bytes(d)) for h, d in e.blob_refs],
                }
                for e in self.effects
            ],
        }, use_bin_type=True)

    @staticmethod
    def from_bytes(data: bytes) -> "TxnMessage":
        m = msgpack.unpackb(data, raw=False, strict_map_key=False)
        return TxnMessage(
            origin=m["o"], shard=m["p"], prev_opid=m["prev"],
            last_opid=m["last"],
            commit_vc=np.asarray(m["cvc"], np.int32),
            snapshot_vc=np.asarray(m["svc"], np.int32),
            timestamp=m["ts"],
            owner=m.get("ow"), oepoch=m.get("oe"),
            effects=[
                Effect(
                    freeze_key(e["k"]), e["t"], e["b"],
                    np.frombuffer(e["a"], np.int64),
                    np.frombuffer(e["eb"], np.int32),
                    [(h, d) for h, d in e["bl"]],
                )
                for e in m["effs"]
            ],
        )


@dataclasses.dataclass
class Descriptor:
    """DC membership descriptor (#descriptor{},
    /root/reference/src/inter_dc_manager.erl:49-61)."""

    dc_id: int
    name: str
    n_shards: int
    address: Optional[Tuple[str, int]] = None  # TCP transport endpoint
    #: fabric endpoint identity — equals dc_id for single-member DCs;
    #: cluster members advertise distinct fabric ids on one dc_id
    fabric_id: Optional[int] = None

    def to_wire(self) -> dict:
        return {"dc_id": self.dc_id, "name": self.name,
                "n_shards": self.n_shards,
                "address": list(self.address) if self.address else None,
                "fabric_id": self.fabric_id}

    @staticmethod
    def from_wire(d: dict) -> "Descriptor":
        addr = d.get("address")
        return Descriptor(
            int(d["dc_id"]), d.get("name", ""), int(d["n_shards"]),
            (addr[0], int(addr[1])) if addr else None,
            None if d.get("fabric_id") is None else int(d["fabric_id"]),
        )
