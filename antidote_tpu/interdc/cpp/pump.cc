// Native inter-DC stream pump: one epoll thread owns every subscription
// socket, parses the length-prefixed frames, and queues complete frames
// for the Python control thread to drain.
//
// This is the receive half of the reference's erlzmq/libzmq data plane
// (SURVEY §2.9; /root/reference/src/inter_dc_sub.erl — libzmq's io
// threads do exactly this: kernel reads + framing in native code, the
// application drains whole messages).  The send half stays on the
// publisher's sendall path (one syscall per frame already).
//
// Framing (interdc/tcp.py): 5-byte header = uint32 BE length (including
// the kind byte) + 1 kind byte, then (length-1) payload bytes.
//
// Backpressure: when the queue holds more than QUEUE_CAP frames the
// loop stops reading (sockets stay readable, TCP flow control pushes
// back on the publisher) — the same strategy as a bounded ZMQ HWM.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread pump.cc -o _pump.so

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <errno.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

constexpr size_t QUEUE_CAP = 65536;

struct Conn {
    long tag;
    std::vector<uint8_t> buf;  // partial frame bytes
};

struct Frame {
    long tag;
    uint8_t kind;
    std::string payload;
};

struct Pump {
    int epfd = -1;
    int wakefd = -1;  // eventfd: add/stop notifications
    std::thread thr;
    std::atomic<bool> stop{false};

    std::mutex mu;
    std::condition_variable cv;
    std::deque<Frame> queue;
    std::unordered_map<int, Conn> conns;  // guarded by mu (adds vs loop)
    std::deque<int> pending_adds;

    void loop();
};

// Dropping a connection (EOF, read error, corrupt frame) queues a
// sentinel frame (kind=0, empty payload) carrying the subscription tag,
// so Python LEARNS of the drop and can log + resubscribe with backoff —
// a silent close would permanently stall replication from that
// publisher (the failure mode the Python reader threads never had).
constexpr uint8_t K_CONN_DROP = 0;

void close_conn(Pump* p, int fd) {
    epoll_ctl(p->epfd, EPOLL_CTL_DEL, fd, nullptr);
    ::close(fd);
    long tag = -1;
    {
        std::lock_guard<std::mutex> g(p->mu);
        auto it = p->conns.find(fd);
        if (it != p->conns.end()) {
            tag = it->second.tag;
            p->conns.erase(it);
        }
        if (tag >= 0) {
            Frame f;
            f.tag = tag;
            f.kind = K_CONN_DROP;
            p->queue.push_back(std::move(f));
        }
    }
    if (tag >= 0) p->cv.notify_one();
}

// parse complete frames out of c.buf, push to queue
void drain_buf(Pump* p, int fd, Conn& c) {
    size_t off = 0;
    for (;;) {
        if (c.buf.size() - off < 5) break;
        uint32_t n = (uint32_t(c.buf[off]) << 24) |
                     (uint32_t(c.buf[off + 1]) << 16) |
                     (uint32_t(c.buf[off + 2]) << 8) |
                     uint32_t(c.buf[off + 3]);
        if (n < 1 || n > (64u << 20)) {  // corrupt length: drop conn
            close_conn(p, fd);
            return;
        }
        if (c.buf.size() - off < 4 + n) break;
        Frame f;
        f.tag = c.tag;
        f.kind = c.buf[off + 4];
        f.payload.assign(reinterpret_cast<char*>(c.buf.data()) + off + 5,
                         n - 1);
        {
            std::lock_guard<std::mutex> g(p->mu);
            p->queue.push_back(std::move(f));
        }
        p->cv.notify_one();
        off += 4 + n;
    }
    if (off) c.buf.erase(c.buf.begin(), c.buf.begin() + off);
}

void Pump::loop() {
    epoll_event evs[64];
    uint8_t rdbuf[1 << 16];
    while (!stop.load(std::memory_order_relaxed)) {
        {   // register freshly added fds
            std::lock_guard<std::mutex> g(mu);
            while (!pending_adds.empty()) {
                int fd = pending_adds.front();
                pending_adds.pop_front();
                epoll_event ev{};
                ev.events = EPOLLIN;
                ev.data.fd = fd;
                epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
            }
        }
        {   // backpressure: let TCP push back while Python catches up
            std::unique_lock<std::mutex> g(mu);
            if (queue.size() > QUEUE_CAP) {
                g.unlock();
                std::this_thread::sleep_for(std::chrono::milliseconds(1));
                continue;
            }
        }
        int nev = epoll_wait(epfd, evs, 64, 100);
        if (nev < 0) {
            if (errno == EINTR) continue;
            break;
        }
        for (int i = 0; i < nev; i++) {
            int fd = evs[i].data.fd;
            if (fd == wakefd) {
                uint64_t x;
                (void)!read(wakefd, &x, sizeof(x));
                continue;
            }
            Conn* c;
            {
                std::lock_guard<std::mutex> g(mu);
                auto it = conns.find(fd);
                if (it == conns.end()) continue;
                c = &it->second;
            }
            bool eof = false;
            for (;;) {
                ssize_t r = ::recv(fd, rdbuf, sizeof(rdbuf), MSG_DONTWAIT);
                if (r > 0) {
                    c->buf.insert(c->buf.end(), rdbuf, rdbuf + r);
                    if (r < (ssize_t)sizeof(rdbuf)) break;
                } else if (r == 0) {
                    eof = true;
                    break;
                } else {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                    if (errno == EINTR) continue;
                    eof = true;
                    break;
                }
            }
            // deliver complete frames ALREADY received before acting on
            // EOF — the stream's last frames must not die in the buffer
            drain_buf(this, fd, *c);
            {
                std::lock_guard<std::mutex> g(mu);
                if (conns.find(fd) == conns.end()) continue;
            }
            if (eof) close_conn(this, fd);
        }
    }
}

}  // namespace

extern "C" {

void* pump_new() {
    auto* p = new Pump();
    p->epfd = epoll_create1(0);
    p->wakefd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = p->wakefd;
    if (p->epfd < 0 || p->wakefd < 0
        || epoll_ctl(p->epfd, EPOLL_CTL_ADD, p->wakefd, &ev) < 0) {
        // syscall failure (fd exhaustion, seccomp): report it so the
        // caller falls back to Python readers instead of blackholing
        // every subscription handed to a dead loop
        if (p->epfd >= 0) ::close(p->epfd);
        if (p->wakefd >= 0) ::close(p->wakefd);
        delete p;
        return nullptr;
    }
    p->thr = std::thread([p] { p->loop(); });
    return p;
}

// takes OWNERSHIP of fd (caller must have detached it)
int pump_add(void* h, int fd, long tag) {
    auto* p = static_cast<Pump*>(h);
    {
        std::lock_guard<std::mutex> g(p->mu);
        p->conns[fd] = Conn{tag, {}};
        p->pending_adds.push_back(fd);
    }
    uint64_t one = 1;
    (void)!write(p->wakefd, &one, sizeof(one));
    return 0;
}

// drain one frame: returns payload length (>=0) and sets *tag/*kind;
// -1 = nothing within timeout_ms; -2 = payload larger than cap (frame
// stays queued; call again with a bigger buffer of *len_out bytes)
long pump_take(void* h, char* out, long cap, long* tag_out, int* kind_out,
               long* len_out, int timeout_ms) {
    auto* p = static_cast<Pump*>(h);
    std::unique_lock<std::mutex> g(p->mu);
    if (p->queue.empty()) {
        p->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                       [p] { return !p->queue.empty(); });
        if (p->queue.empty()) return -1;
    }
    Frame& f = p->queue.front();
    *tag_out = f.tag;
    *kind_out = f.kind;
    *len_out = (long)f.payload.size();
    if ((long)f.payload.size() > cap) return -2;
    memcpy(out, f.payload.data(), f.payload.size());
    long n = (long)f.payload.size();
    p->queue.pop_front();
    return n;
}

// drain up to max_n frames in ONE crossing: payloads packed back to
// back into out, (tag, kind, len) triples into descs.  Returns the
// number of frames (0 after timeout), stopping early when the next
// frame would overflow cap (it stays queued for the next call).
long pump_take_batch(void* h, char* out, long cap, long* descs,
                     long max_n, int timeout_ms) {
    auto* p = static_cast<Pump*>(h);
    std::unique_lock<std::mutex> g(p->mu);
    if (p->queue.empty()) {
        p->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                       [p] { return !p->queue.empty(); });
        if (p->queue.empty()) return 0;
    }
    long n = 0;
    long off = 0;
    while (n < max_n && !p->queue.empty()) {
        Frame& f = p->queue.front();
        if (off + (long)f.payload.size() > cap) break;
        memcpy(out + off, f.payload.data(), f.payload.size());
        descs[n * 3] = f.tag;
        descs[n * 3 + 1] = f.kind;
        descs[n * 3 + 2] = (long)f.payload.size();
        off += (long)f.payload.size();
        n++;
        p->queue.pop_front();
    }
    return n;
}

long pump_queued(void* h) {
    auto* p = static_cast<Pump*>(h);
    std::lock_guard<std::mutex> g(p->mu);
    return (long)p->queue.size();
}

void pump_free(void* h) {
    auto* p = static_cast<Pump*>(h);
    p->stop.store(true);
    uint64_t one = 1;
    (void)!write(p->wakefd, &one, sizeof(one));
    if (p->thr.joinable()) p->thr.join();
    std::vector<int> fds;
    {
        std::lock_guard<std::mutex> g(p->mu);
        for (auto& kv : p->conns) fds.push_back(kv.first);
        p->conns.clear();
        p->queue.clear();
    }
    for (int fd : fds) ::close(fd);
    ::close(p->wakefd);
    ::close(p->epfd);
    p->cv.notify_all();
    // The struct itself is deliberately quarantined (never deleted): a
    // concurrent pump()/take() on another thread may still be inside a
    // bounded cv wait on this handle, and freeing under it would be a
    // use-after-free.  One ~200-byte husk per fabric close, bounded by
    // fabric lifecycle count; the kernel resources above are released.
}

#ifndef ANTIDOTE_SRC_SHA
#define ANTIDOTE_SRC_SHA "unknown"
#endif

const char* pump_src_sha() { return ANTIDOTE_SRC_SHA; }

}  // extern "C"
