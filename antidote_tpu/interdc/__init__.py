from antidote_tpu.interdc.follower import FollowerReplica
from antidote_tpu.interdc.messages import Descriptor, TxnMessage
from antidote_tpu.interdc.replica import DCReplica
from antidote_tpu.interdc.transport import LoopbackHub

__all__ = ["Descriptor", "TxnMessage", "DCReplica", "FollowerReplica",
           "LoopbackHub"]
