"""Inter-DC transports.

Two-tier like the reference (SURVEY §5): a pub/sub stream for the txn feed
(ZeroMQ PUB/SUB in the reference, /root/reference/src/inter_dc_pub.erl /
inter_dc_sub.erl) and a request/response channel for log catch-up queries
(ZeroMQ REQ/XREP, /root/reference/src/inter_dc_query.erl).

``LoopbackHub`` is the in-process deterministic transport used by tests —
the analogue of the reference's many-BEAM-nodes-on-one-box Common Test
topology (/root/reference/test/utils/test_utils.erl:110-165).  Messages
enqueue; ``pump()`` drains until quiescent, so causality/buffering logic is
exercised deterministically.  It can also drop messages on demand to test
the gap/catch-up path.  A TCP transport drives the same replica callbacks
over sockets (see server.py) for real multi-process deployments.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Tuple


class LoopbackHub:
    """Deterministic in-process pub/sub + query fabric between replicas."""

    def __init__(self):
        #: dc_id -> subscriber callback (bytes -> None)
        self.subscribers: Dict[int, List[Callable[[bytes], None]]] = {}
        #: dc_id -> log-query handler (shard, origin, from_opid) -> [bytes]
        self.query_handlers: Dict[int, Callable] = {}
        #: dc_id -> generic request handler (kind, payload) -> reply
        self.request_handlers: Dict[int, Callable] = {}
        #: dc_id -> tick callback, run at every pump — replicas flush
        #: deferred heartbeats here (the in-process stand-in for the
        #: reference's 1 s timer,
        #: /root/reference/src/inter_dc_log_sender_vnode.erl:188-204)
        self.ticks: Dict[int, Callable[[], None]] = {}
        # bounded-by: test-only deterministic transport — pump() drains
        # to quiescence every round, no wire to fall behind
        self.queues: collections.deque = collections.deque()
        #: (from_dc, to_dc) pairs whose next N messages are dropped
        self.drop: Dict[Tuple[int, int], int] = {}
        self.delivered = 0
        self.dropped = 0

    def register(self, dc_id: int, on_message: Callable[[bytes], None],
                 query_handler: Callable) -> None:
        self.subscribers.setdefault(dc_id, [])
        self.query_handlers[dc_id] = query_handler

    def unregister(self, dc_id: int) -> None:
        """Forget a DC's handlers, subscriptions AND queued deliveries
        (node crash/restart: nothing may reach the ghost replica's
        callbacks — its dead node still holds the WAL files the reborn
        one appends to)."""
        self.query_handlers.pop(dc_id, None)
        self.request_handlers.pop(dc_id, None)
        self.ticks.pop(dc_id, None)
        self.subscribers.pop(dc_id, None)
        for pub, subs in self.subscribers.items():
            self.subscribers[pub] = [
                (to_dc, cb) for to_dc, cb in subs if to_dc != dc_id
            ]
        # bounded-by: rebuilt from the (test-only, pump-drained) queue
        self.queues = collections.deque(
            (to_dc, cb, data) for to_dc, cb, data in self.queues
            if to_dc != dc_id
        )

    def register_tick(self, dc_id: int, fn: Callable[[], None]) -> None:
        self.ticks[dc_id] = fn

    def register_request(self, dc_id: int, handler: Callable) -> None:
        """Attach a generic request handler ((kind, payload) -> reply) —
        the other message types of the REQ/XREP channel
        (?BCOUNTER_REQUEST / ?CHECK_UP_MSG,
        /root/reference/include/antidote_message_types.hrl:4-25)."""
        self.request_handlers[dc_id] = handler

    def request(self, target_dc: int, kind: str, payload) -> object:
        """Synchronous cross-DC RPC (inter_dc_query:perform_request,
        /root/reference/src/inter_dc_query.erl:76-79)."""
        return self.request_handlers[target_dc](kind, payload)

    def subscribe(self, subscriber_dc: int, publisher_dc: int,
                  on_message: Callable[[bytes], None]) -> None:
        self.subscribers.setdefault(publisher_dc, []).append(
            (subscriber_dc, on_message)
        )

    def publish(self, from_dc: int, data: bytes) -> None:
        for to_dc, cb in self.subscribers.get(from_dc, []):
            key = (from_dc, to_dc)
            if self.drop.get(key, 0) > 0:
                self.drop[key] -= 1
                self.dropped += 1
                continue
            self.queues.append((to_dc, cb, data))

    def query_log(self, target_dc: int, shard: int, origin: int,
                  from_opid: int) -> List[bytes]:
        """Synchronous catch-up query against a remote DC's log reader
        (?LOG_READ_MSG, /root/reference/src/inter_dc_query_response.erl:97-126)."""
        return self.query_handlers[target_dc](shard, origin, from_opid)

    def drop_next(self, from_dc: int, to_dc: int, n: int = 1) -> None:
        """Fault injection: lose the next n messages on a link."""
        self.drop[(from_dc, to_dc)] = self.drop.get((from_dc, to_dc), 0) + n

    def pump(self, max_rounds: int = 10_000) -> int:
        """Deliver queued messages until quiescent; returns count.

        Ticks run before each drain round so deferred heartbeats flush
        (and their deliveries may unblock causal gates in the same pump)."""
        n = 0
        while n < max_rounds:
            for fn in list(self.ticks.values()):
                fn()
            if not self.queues:
                break
            while self.queues and n < max_rounds:
                _, cb, data = self.queues.popleft()
                cb(data)
                self.delivered += 1
                n += 1
        return n
