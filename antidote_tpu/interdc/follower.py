"""FollowerReplica — a fault-first read replica of one owner node.

The follower read tier (ISSUE 9 / ROADMAP item 2): a follower is a full
store replica of ONE owner (same ``dc_id``, same deployment shape) that
subscribes to the owner's txn stream, applies effects through the same
chain/causal-gate machinery a geo peer uses — including the owner's
OWN-origin chain, which a peer DC skips — and serves epoch-plane
snapshot reads from its own wire server with zero owner load.  It is
built to *survive anything*:

  * **bootstrap from nothing** — a fresh follower ships the owner's
    newest checkpoint image over the request channel (``ckpt_meta`` /
    ``ckpt_fetch``, fault site ``ckpt.ship``), installs it, checkpoints
    it LOCALLY (so its own crash recovery is self-sufficient), then
    catches the WAL tail up through the ordinary opid-gap machinery;
  * **fall below the compaction floor and repair** — a catch-up refused
    with the owner's "below the compaction floor" error (PR 7's
    residual) no longer strands the replica: it re-bootstraps from the
    current image (mode ``delta``) instead of retrying forever;
  * **crash and rejoin fast** — a restarted follower recovers from its
    own WAL + local checkpoint images, re-derives its chain positions,
    and only replays the missed suffix (mode ``tail``);
  * **diverge and self-heal** — per-shard content digests are
    periodically compared against the owner at EQUAL applied clocks
    (equal clocks ⇒ equal applied prefixes ⇒ digests must match); a
    mismatch quarantines the replica (session reads get typed
    redirects, never the corrupt value) and re-bootstraps from the
    image;
  * **never lie to a session** — reads carrying a session token (the
    client's causal clock) are gated on the PER-SHARD applied clocks of
    the shards they touch: the follower parks briefly, then answers a
    typed :class:`~antidote_tpu.overload.ReplicaLagging` redirect so
    the client fails over (across followers, and back to the owner)
    with read-your-writes and monotonic reads intact.

Fleet scope (ISSUE 11): a follower can shadow a CLUSTERED (multi-member)
or GEO-REPLICATED owner.  :meth:`FollowerReplica.attach` accepts a list
of descriptors — every member of the owner DC, plus (for geo owners) the
peer DCs' endpoints — and opens one stream subscription per endpoint.
Per-shard request routing (catch-up, divergence digests, image fetches)
rides the epoch'd ownership gossip from PR 3 (``DCReplica.shard_route``:
every egress message carries the publishing member's (owner, epoch)
stamp), so a mid-fleet shard move re-points the follower's catch-up and
digest checks at the new owner with NO reconnect — the already-open
subscription to the new owner simply keeps delivering.  Bootstrap and
quarantine repair COMPOSE per-member checkpoint images: each member's
image installs restricted to the shards that member currently owns
(``install_image(shards=...)``), and the divergence digest compares each
shard against whichever member owns it at the compared clock.  A geo
owner's remote-origin chains replicate live through the follower's own
subscriptions to the peer DCs (give ``attach`` their descriptors too —
an unsubscribed peer lane shows up as a permanently ``skipped``
divergence check, never a mismatch).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.interdc.messages import Descriptor
from antidote_tpu.interdc.replica import DCReplica
from antidote_tpu.store.kv import KVStore, freeze_key, key_to_shard

log = logging.getLogger(__name__)


class FollowerReplica(DCReplica):
    """Read-only follower of one owner node (see module docstring)."""

    #: default fabric-id base for followers — far above any dc id, so a
    #: follower's endpoint never shadows a DC's on a shared fabric/test
    FID_BASE = 1 << 14
    #: session reads park at most this long for the applied clock to
    #: catch the token before the typed redirect
    PARK_S = 0.10
    #: liveness-report cadence to the owner (the follower half of the
    #: heartbeat/ping plane; the owner marks a silent follower DOWN
    #: after DCReplica.REPLICA_DOWN_S)
    REPORT_EVERY_S = 1.0
    #: backoff between retries of a FAILED quarantine repair (owner
    #: unreachable / image retired mid-ship) — retried from the tick
    HEAL_RETRY_S = 2.0

    def __init__(self, node: AntidoteNode, hub, name: str = "",
                 owner_client_addr=None, fabric_id: Optional[int] = None,
                 park_s: Optional[float] = None,
                 digest_every_s: float = 0.0):
        if fabric_id is None:
            import os

            fabric_id = self.FID_BASE + (os.getpid() % self.FID_BASE)
        super().__init__(node, hub, name or f"follower-{fabric_id}",
                         fabric_id=fabric_id)
        #: the owner's client-protocol endpoint (host, port) carried in
        #: every typed redirect, so session clients can re-route
        self.owner_client_addr = (tuple(owner_client_addr)
                                  if owner_client_addr else None)
        self.park_s = self.PARK_S if park_s is None else float(park_s)
        #: <= 0 disables the periodic divergence sweep (tests call
        #: :meth:`check_divergence` directly; console arms it)
        self.digest_every_s = float(digest_every_s)
        #: owner's fabric id — set by :meth:`attach` (for a clustered
        #: owner: the lowest-member-id endpoint, i.e. the sequencer)
        self.owner_fid: Optional[int] = None
        #: the whole subscribed fleet: fabric id -> Descriptor (owner-DC
        #: members AND geo peers); single-member owners have one entry
        self.fleet: Dict[int, "Descriptor"] = {}
        #: dc id -> sorted member fabric ids (the modular catch-up
        #: fallback before any ownership gossip arrives; learned
        #: ``shard_route`` entries take precedence via ``_route``)
        self.fleet_by_dc: Dict[int, List[int]] = {}
        #: owner-DC member fabric ids (liveness reports + image sources)
        self.member_fids: List[int] = []
        #: session-gate refusals since the last admitted read — the
        #: pressure signal behind the typed redirect's retry hint
        #: (PR 4's AdmissionGate discipline; benign under races)
        self._gate_streak = 0
        #: bootstrapping -> serving -> (healing -> serving)*; anything
        #: but "serving" redirects every session read
        self.state = "bootstrapping"
        #: completed bootstrap/repair cycles and the last cycle's mode
        self.boots = 0
        self.last_bootstrap_mode: Optional[str] = None
        self.divergence_counts: Dict[str, int] = {
            "ok": 0, "skipped": 0, "mismatch": 0}
        self._boot_lock = threading.RLock()
        self._in_heal = False
        #: a failed quarantine repair parks its mode here; the tick
        #: retries it on HEAL_RETRY_S backoff (never stuck "healing")
        self._heal_pending: Optional[str] = None
        self._heal_retry_at = 0.0
        self._last_report = 0.0
        self._last_digest = time.monotonic()
        self._digest_rr = 0
        #: serving-fleet snapshot learned from follower_report replies
        #: (ISSUE 17): name -> {addr, state}, the feed of the proxy
        #: plane's FleetHealth table.  The version counter lets the
        #: plane rebuild its ring only when a new snapshot landed.
        self.fleet_table: Dict[str, dict] = {}
        self.fleet_table_v = 0

    # -- identity overrides ---------------------------------------------
    def _ingest_own_origin(self) -> bool:
        return True  # the owner's own chain IS the follower's data plane

    def _on_local_commit(self, effects, commit_vc, origin) -> None:
        # a follower is read-only by contract (the wire server refuses
        # writes with typed not_owner): a local commit reaching this
        # listener means an embedder bypassed it — the effects applied
        # locally but are invisible to the owner and every other
        # follower, i.e. guaranteed divergence (which the digest sweep
        # will then catch).  Scream, don't publish.
        log.error("follower %s observed a LOCAL commit (%d effect(s)) — "
                  "followers are read-only; this state WILL diverge from "
                  "the owner until the next digest check heals it",
                  self.name, len(effects))

    def heartbeat(self, exclude=frozenset()) -> None:
        return  # followers never publish safe times — they consume them

    def maybe_heartbeat(self) -> None:
        """The follower's tick (runs at every fabric pump): periodic
        liveness/lag report to the owner, plus — when armed — the
        round-robin divergence sweep, one shard per cadence window."""
        now = time.monotonic()
        if now - self._last_report >= self.REPORT_EVERY_S:
            self._last_report = now
            self._send_report()
        if (self._heal_pending is not None and not self._in_heal
                and now >= self._heal_retry_at):
            mode, self._heal_pending = self._heal_pending, None
            self._heal(mode)  # re-parks itself on failure
        if (self.digest_every_s > 0 and self.state == "serving"
                and now - self._last_digest >= self.digest_every_s):
            self._last_digest = now
            shard = self._digest_rr % self.node.cfg.n_shards
            self._digest_rr += 1
            self.check_divergence([shard])

    # -- attach / bootstrap ---------------------------------------------
    def attach(self, desc) -> str:
        """Wire this follower to its owner FLEET from connection
        descriptor(s) (``GET_CONNECTION_DESCRIPTOR`` replies): one
        descriptor for a single-member owner, or a list covering every
        member of a clustered owner DC — plus, for a geo-replicated
        owner, the peer DCs' descriptors, so their origin chains
        replicate live through the follower's own subscriptions.  Learns
        the endpoints, bootstraps (per-member image composition / delta
        / tail), subscribes to every stream, and closes the
        bootstrap→subscribe window with one more catch-up.  Returns the
        bootstrap mode."""
        descs = list(desc) if isinstance(desc, (list, tuple)) else [desc]
        descs = [Descriptor.from_wire(d) if isinstance(d, dict) else d
                 for d in descs]
        fleet: Dict[int, Descriptor] = {}
        for d in descs:
            fid = d.fabric_id if d.fabric_id is not None else d.dc_id
            assert fid != self.fabric_id, \
                "follower fabric id collides with a fleet endpoint's"
            fleet[fid] = d
        by_dc: Dict[int, List[int]] = {}
        for fid, d in fleet.items():
            by_dc.setdefault(int(d.dc_id), []).append(fid)
        for fids in by_dc.values():
            # fabric_id_of is monotone in member id, so sorted fabric
            # ids == member-id order (member 0 keeps the bare dc id)
            fids.sort()
        if self.dc_id not in by_dc:
            raise ValueError(
                f"no descriptor for the owner DC (dc_id={self.dc_id}) "
                "in the fleet — a follower shadows that exact store")
        self.fleet = fleet
        self.fleet_by_dc = by_dc
        self.member_fids = list(by_dc[self.dc_id])
        self.owner_fid = self.member_fids[0]

        def route(origin: int, shard: int) -> int:
            # modular fallback over the origin's known members; the
            # gossip-learned shard_route (strictly-newer epochs win)
            # takes precedence in DCReplica._route, so live shard moves
            # re-point catch-up without touching this
            fids = by_dc.get(origin)
            if not fids:
                return origin
            return fids[shard % len(fids)]

        self.route_query = route
        connect = getattr(self.hub, "connect_remote", None)
        for fid, d in fleet.items():
            if d.address is not None and connect is not None:
                connect(fid, d.address[0], int(d.address[1]))
        mode = self.bootstrap()
        for fid in fleet:
            self.hub.subscribe(self.fabric_id, fid, self._on_message)
        with self._boot_lock:
            self._in_heal = True
            try:
                # the floor can advance inside the bootstrap→subscribe
                # window too (aggressive checkpoint cadences): this
                # catch-up repairs via image re-install like any other
                if self._catch_up_all_repairing():
                    self._finish_cycle("delta")
                    mode = "delta"
            finally:
                self._in_heal = False
        self._post_apply_publish(force=True)
        self._send_report()
        # --follower-peers sanity (ISSUE 13 satellite): ask the owner
        # which origin lanes it actually carries; any lane we hold no
        # descriptor for can never converge here — warn NOW, by name,
        # instead of letting its divergence checks read as eternally
        # "skipped"
        try:
            known = self.hub.request(self.owner_fid, "peer_origins", {})
            missing = sorted(set(int(o) for o in known["origins"])
                             - set(self.fleet_by_dc))
            if missing:
                log.warning(
                    "follower %s: the owner replicates origin lane(s) %s "
                    "but no descriptor for them was given — pass their "
                    "endpoints via --follower-peers, or divergence "
                    "checks on those lanes will report 'unsubscribed' "
                    "forever", self.name, missing)
        except Exception:
            pass  # older owners without the peer_origins kind
        return mode

    def bootstrap(self) -> str:
        """One bootstrap cycle: image install for a blank follower (when
        the owner has one), WAL catch-up otherwise; a catch-up refused
        below the owner's compaction floor repairs via image re-install
        (mode ``delta``).  Leaves the replica ``serving``."""
        with self._boot_lock:
            self._in_heal = True
            try:
                self.restore_from_log()
                have_local = bool(self.node.store.directory) or bool(
                    self.last_seen)
                mode = "tail"
                if not have_local:
                    metas = {fid: self._owner_image_meta(fid=fid)
                             for fid in self._image_fids()}
                    if any(m is not None for m in metas.values()):
                        self._reinstall(metas)
                        mode = "image"
                # a position below the owner's floor (long-partitioned /
                # blank-WAL follower — or the floor advancing again
                # mid-repair) re-installs the image and retries
                if self._catch_up_all_repairing() and mode != "image":
                    mode = "delta"
                self._finish_cycle(mode)
                return mode
            finally:
                self._in_heal = False

    def _finish_cycle(self, mode: str) -> None:
        self._post_apply_publish(force=True)
        self.boots += 1
        self.last_bootstrap_mode = mode
        m = getattr(self.node, "metrics", None)
        if m is not None:
            m.follower_bootstrap.inc(mode=mode)
        self.state = "serving"
        log.info("follower %s: bootstrap cycle complete (mode=%s, "
                 "applied=%s)", self.name, mode,
                 [int(x) for x in self.node.store.dc_max_vc()])

    def _heal(self, mode: str) -> None:
        """Quarantine-and-repair: stop serving sessions, re-install the
        owner's current image, catch the tail up, resume.

        A FAILED repair (owner unreachable mid-fetch, image retired by
        retention mid-ship, persistent verification failure) must not
        quarantine the replica forever OR crash the delivery pump: the
        failure is swallowed here, the replica stays ``healing`` (its
        store may be mid-wipe — sessions keep redirecting), and the
        tick retries the pending repair on a short backoff until the
        owner is reachable again."""
        with self._boot_lock:
            self.state = "healing"
            self._in_heal = True
            try:
                self._reinstall()
                self._catch_up_all_repairing()
                self._finish_cycle(mode)
                self._heal_pending = None
            except Exception:
                self._heal_pending = mode
                self._heal_retry_at = (time.monotonic()
                                       + self.HEAL_RETRY_S)
                log.exception(
                    "follower %s: repair (mode=%s) failed; staying "
                    "quarantined and retrying from the tick", self.name,
                    mode)
            finally:
                self._in_heal = False

    def _catch_up_all_repairing(self, attempts: int = 3) -> bool:
        """Catch every chain up, re-installing the owner's image
        whenever the position is below the compaction floor — which can
        happen AGAIN mid-repair (the owner keeps checkpointing).
        Returns True if any (re)install happened.  Caller holds
        ``_boot_lock`` with ``_in_heal`` set."""
        reinstalled = False
        last: Optional[BaseException] = None
        for _attempt in range(attempts):
            try:
                self._catch_up_all()
                return reinstalled
            except RuntimeError as e:
                if "compaction floor" not in str(e):
                    raise
                log.warning("follower %s below the owner's compaction "
                            "floor; repairing from the checkpoint image",
                            self.name)
                last = e
                self._reinstall()
                reinstalled = True
        raise last  # type: ignore[misc]

    def restore_from_log(self) -> None:
        """Reseed the CONSUMED chain positions from the local WAL +
        installed chain floors — the follower twin of the peer replica's
        restore (a follower tracks the owner's own-origin chain as a
        consumer too, and never rebuilds an egress window)."""
        store = self.node.store
        if store.log is None:
            return
        for shard in sorted(self.shards):
            counts: Dict[int, int] = {}
            for origin in range(self.node.cfg.max_dcs):
                base = store.log.chain_base(shard, origin)
                if base:
                    counts[origin] = base
            for origin, _vc, _effs in self._wal_txn_groups(
                    shard, my_effects_after=1 << 62):
                counts[origin] = counts.get(origin, 0) + 1
            for origin, n in counts.items():
                key = (origin, shard)
                if n > self.last_seen.get(key, 0):
                    self.last_seen[key] = n

    # -- image shipping --------------------------------------------------
    def _image_fids(self) -> List[int]:
        """Fabric ids to source checkpoint images from: every owner-DC
        member (their images compose the whole DC store); the single-
        member owner degenerates to ``[owner_fid]``."""
        return list(self.member_fids) or [self.owner_fid]

    def _owner_image_meta(self, before_id: Optional[int] = None,
                          fid: Optional[int] = None) -> Optional[dict]:
        body = {} if before_id is None else {"before_id": int(before_id)}
        return self.hub.request(self.owner_fid if fid is None else fid,
                                "ckpt_meta", body)

    def _fetch_file(self, meta: dict, fid: int, file: str,
                    size: int, crc: int) -> bytes:
        """Ship one published checkpoint file in chunks over the
        request channel and verify size + CRC — a truncated or
        bit-rotted ship must fail loudly, never install."""
        import zlib

        buf = bytearray()
        while len(buf) < size:
            req = {
                "id": int(meta["id"]), "off": len(buf),
                "n": DCReplica.CKPT_SHIP_CHUNK,
            }
            if file != "image":
                req["file"] = file
            r = self.hub.request(fid, "ckpt_fetch", req)
            data = bytes(r["data"])
            if not data:
                break
            buf.extend(data)
            if r.get("eof"):
                break
        data = bytes(buf)
        if len(data) != size or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            raise RuntimeError(
                f"shipped checkpoint {file} ckpt_{meta['id']} failed "
                f"verification ({len(data)}/{size} bytes)"
            )
        return data

    def _fetch_image(self, meta: dict,
                     fid: Optional[int] = None) -> dict:
        """Ship one member's image (and, for a beyond-RAM owner, its
        cold sidecar) and decode.  The sidecar bytes ride back on the
        returned image dict under ``"_cold_bytes"`` — staged locally by
        the reinstall so cold keys stay fault-able until the first
        local rebase persists them."""
        from antidote_tpu.store.handoff import unpack

        fid = self.owner_fid if fid is None else fid
        data = self._fetch_file(meta, fid, "image",
                                int(meta["image_bytes"]),
                                int(meta["image_crc32"]))
        image = unpack(data)
        if meta.get("cold_bytes") and meta.get("cold_keys"):
            # only worth shipping when the image actually has cold keys
            # (a budget-armed owner with everything resident publishes
            # an image-sized sidecar the follower has no use for)
            image["_cold_bytes"] = self._fetch_file(
                meta, fid, "cold", int(meta["cold_bytes"]),
                int(meta["cold_crc32"]))
            image["_cold_manifest"] = meta.get("cold_manifest")
        return image

    def _fetch_member_image(self, fid: int, meta: Optional[dict] = None):
        """Resolve + fetch one member's newest verifiable image with the
        bit-rot/retirement fallback: a failed fetch prefers the next
        OLDER retained image (owner-side recovery's discipline), else
        re-resolves the newest (a fresh one may have published
        mid-ship).  Returns ``(image, meta)`` or ``(None, None)`` when
        the member has nothing published (its shards then bootstrap via
        whole-chain WAL catch-up — a member without an image has never
        compacted, so its full chain is servable)."""
        last: Optional[BaseException] = None
        for _attempt in range(3):
            if meta is None:
                meta = self._owner_image_meta(fid=fid)
            if meta is None:
                return None, None
            try:
                return self._fetch_image(meta, fid=fid), meta
            except (RuntimeError, OSError) as e:
                log.warning("follower %s: image ckpt_%s fetch from "
                            "endpoint %d failed (%s); falling back to "
                            "an older retained image (else re-resolving "
                            "the newest)", self.name, meta.get("id"),
                            fid, e)
                last = e
                try:
                    meta = self._owner_image_meta(
                        before_id=meta.get("id"), fid=fid)
                except Exception:
                    meta = None
        raise RuntimeError(
            "checkpoint image shipping failed repeatedly"
        ) from last

    def _reinstall(self, metas: Optional[Dict[int, dict]] = None) -> None:
        """Discard local state and install the owner fleet's newest
        images — one per member, each restricted to the shards that
        member currently owns (``install_image(shards=...)``), composing
        the whole DC store; the single-member owner installs one
        unrestricted image exactly as before.

        The store is REPLACED (fresh tables, same LogManager): the old
        device state may be arbitrarily wrong (that's why we're here),
        local WAL records and local checkpoint images derived from it
        must not resurrect, and the epoch-id sequence continues so
        snapshot-cache stamps never repeat.  Finishes with a LOCAL
        checkpoint so the follower's own crash recovery covers the
        installed prefix (its WAL only ever holds the tail).

        ``metas``: already-resolved ``ckpt_meta`` replies by fabric id
        (bootstrap passes the ones it decided on, saving round trips).
        Every image is fetched BEFORE the store wipe, so a mid-fetch
        failure (owner unreachable, image retired, verification
        failure) leaves the local state untouched."""
        from antidote_tpu.log import checkpoint as _ckpt
        from antidote_tpu.log.checkpoint import install_image

        fids = self._image_fids()
        multi = len(fids) > 1
        images: List[tuple] = []  # (image, restrict-shards or None)
        for fid in fids:
            meta = (metas or {}).get(fid)
            image, meta = self._fetch_member_image(fid, meta)
            if image is None:
                continue
            images.append((image,
                           meta.get("shards") if multi else None))
        if not images:
            raise RuntimeError(
                "owner has no published checkpoint image to "
                "bootstrap from (run checkpoint-now on the owner, "
                "or size its --checkpoint-interval-s below the "
                "follower's outage)"
            )
        node, txm = self.node, self.node.txm
        cfg = node.cfg
        with txm.commit_lock:
            old = node.store
            logm = old.log
            assert logm is not None, \
                "a follower needs a durable log (log_dir) to bootstrap"
            _ckpt.discard_all(logm.dir)
            for shard in range(cfg.n_shards):
                logm.truncate_shard(shard)
            # adopt the OWNER's truncation epochs: ours were just bumped
            # by the truncations above, and install_image would drop
            # every imaged shard as stale against them.  Per-member
            # images contribute exactly their restricted shards' epochs.
            resets: Dict[int, int] = {}
            for image, restrict in images:
                allowed = (None if restrict is None
                           else {int(s) for s in restrict})
                for k, v in (image.get("shard_resets") or {}).items():
                    if allowed is None or int(k) in allowed:
                        resets[int(k)] = int(v)
            logm.adopt_shard_resets(resets)
            store = KVStore(cfg, sharding=old.sharding, log=logm)
            store.metrics = getattr(node, "metrics", None)
            if old.mesh is not None:
                # a mesh-placed follower stays mesh-placed across every
                # reinstall/heal: re-attach the plane so the fresh
                # store's stable time keeps routing through the pmin
                # collective (and the plane stops pinning the discarded
                # store's device arrays)
                old.mesh.attach(store)
            # epoch ids continue: a reader-pinned epoch of the old store
            # (or a stale snapshot-cache stamp) must never collide with
            # a fresh id
            store._serving_seq = old._serving_seq
            old.drop_serving_epoch()
            node.store = store
            txm.store = store
            txm.committed_keys = {}
            txm.commit_counter = 0
            txm.epoch_lag_counter = 0
            old_cold = old.cold
            staged: List[str] = []
            for idx, (image, restrict) in enumerate(images):
                summary = install_image(store, txm, image,
                                        shards=restrict)
                cold_entries = summary.get("cold_directory") or []
                if cold_entries:
                    # beyond-RAM owner: stage the shipped sidecar so the
                    # cold keys stay fault-able; the forced local REBASE
                    # below re-emits them into our own image, after
                    # which the staging file is swept
                    if store.cold is None:
                        budget = old_cold.budget if old_cold else 0
                        cap = (old_cold.fault_rate_cap
                               if old_cold else 0.0)
                        self.node.enable_cold_tier(budget, cap)
                    root = _ckpt.checkpoint_root(logm.dir)
                    import os as _os

                    _os.makedirs(root, exist_ok=True)
                    token = f"import.{idx}.{image['id']}"
                    path = _os.path.join(
                        root, f"tmp.{_os.getpid()}.{token}.bin")
                    with open(path, "wb") as f:
                        f.write(image.pop("_cold_bytes"))
                    staged.append(path)
                    store.cold.add_source(token, path,
                                          image.pop("_cold_manifest"))
                    store.cold.seed(cold_entries, token)
            if store.cold is None and old_cold is not None:
                self.node.enable_cold_tier(old_cold.budget,
                                           old_cold.fault_rate_cap)
            # follower floor fixup: the install stamped the OWNER's WAL
            # floors/seqs, but this WAL is freshly truncated — local
            # appends must mint q from 1 and local replay must skip
            # nothing (the image prefix is covered by the LOCAL
            # checkpoint below; chain_floor stays — it both numbers the
            # chains and keeps the compaction-horizon guard honest)
            logm.floor_seqs[:] = 0
            logm.seqs[:] = 0
            # chain positions restart at the image's floors; anything
            # gated/pending against the old store is garbage now
            self.last_seen.clear()
            self.pending.clear()
            self.gate.clear()
            for shard in range(cfg.n_shards):
                for origin in range(cfg.max_dcs):
                    base = logm.chain_base(shard, origin)
                    if base:
                        self.last_seen[(origin, shard)] = base
            self._sync_counter_locked()
        self._local_checkpoint()
        for path in staged:
            try:
                import os as _os

                _os.remove(path)  # reclaim-ok: staged import sidecar —
                # the local rebase just re-emitted its rows into our own
                # published image
            except OSError:
                pass

    def _local_checkpoint(self) -> None:
        """Checkpoint the freshly-installed state locally.  The node's
        checkpointer (if any) captured the PRE-swap store — rebuild it
        against the new one, keeping its cadence."""
        node = self.node
        cp = node.checkpointer
        interval, retain, rebase, scrub = 0.0, 2, 8, 0.0
        if cp is not None:
            interval, retain = cp.interval_s, cp.retain
            rebase, scrub = cp.rebase_every, cp.scrub_every_s
            cp.stop()
            node.checkpointer = None
        node.start_checkpointer(interval_s=interval, retain=retain,
                                rebase_every=rebase, scrub_every_s=scrub)
        node.checkpointer.checkpoint_now()

    # -- chain catch-up ---------------------------------------------------
    def _catch_up_all(self) -> None:
        """Pull every subscribed chain's suffix — the owner DC's
        own-origin chains (routed per shard to the owning member via
        the gossip-learned routes, modular fallback before any gossip)
        plus, for geo owners, every subscribed peer DC's chains —
        bootstrap's bulk path and the subscribe-window closer; steady
        state uses the ordinary ping-revealed gap machinery."""
        origins = (sorted(self.fleet_by_dc) if self.fleet_by_dc
                   else [self.dc_id])
        for shard in sorted(self.shards):
            for origin in origins:
                key = (origin, shard)
                super()._catch_up(key, self.last_seen.get(key, 0))
        # the replayed suffix sits in the causal gate: drain it NOW (the
        # steady-state drain runs on stream deliveries, which a replica
        # mid-bootstrap/heal has none of) — _drain_gates also republishes
        # the applied-stamped epoch via the override below
        self._drain_gates()

    def _catch_up(self, key, from_opid) -> None:
        """The runtime repair hook: a catch-up refused below the owner's
        compaction floor triggers a delta re-bootstrap instead of
        retrying (and failing) on every subsequent ping forever."""
        try:
            super()._catch_up(key, from_opid)
        except RuntimeError as e:
            if "compaction floor" not in str(e) or self._in_heal:
                raise
            log.warning("follower %s: catch-up for chain %s fell below "
                        "the owner's compaction floor; re-bootstrapping "
                        "from the image (%s)", self.name, key, e)
            self._heal("delta")

    # -- applied-VC-stamped serving epochs --------------------------------
    #: drain-path epoch publishes are rate-limited to one per window —
    #: each publish is a device re-freeze, and a follower fleet paying
    #: one per delivered write batch per replica was the dominant fixed
    #: cost at high fanout.  Freshness doesn't ride on it: the server's
    #: epoch ticker republishes every --epoch-tick-ms, and the session
    #: gate publishes ON DEMAND (bypassing this limit) whenever a
    #: token needs an epoch the current one can't prove it covers.
    EPOCH_PUBLISH_MIN_S = 0.025

    def _drain_gates(self) -> None:
        super()._drain_gates()
        self._post_apply_publish()

    def _post_apply_publish(self, force: bool = False) -> None:
        txm = self.node.txm
        with txm.commit_lock:
            self._sync_counter_locked()
            now = time.monotonic()
            if (force or now - getattr(self, "_last_epoch_pub", 0.0)
                    >= self.EPOCH_PUBLISH_MIN_S):
                self._last_epoch_pub = now
                self.publish_applied_epoch_locked()

    def _sync_counter_locked(self) -> None:
        """Slave the (otherwise unused) commit counter to the applied
        own-lane clock: the locked read path and `serving_epoch_vc`
        derive the own-lane snapshot from it, and a follower's truth is
        exactly what it has applied."""
        txm = self.node.txm
        own = int(self.node.store.dc_max_vc()[self.dc_id])
        if own > txm.commit_counter:
            txm.commit_counter = own

    def publish_applied_epoch_locked(self) -> str:
        """The ONLY sanctioned epoch-publish path on follower planes
        (tools/lint.py enforces it): commit_counter is slaved to the
        applied clock first, so the published epoch's VC claims exactly
        what this replica has applied — an epoch stamped ahead of the
        applied clock is a silent causal-violation machine."""
        txm = self.node.txm
        if not txm.serving_epochs:
            return "disabled"
        # vc-stamped: commit_counter == applied own lane (synced above),
        # so serving_epoch_vc IS the applied clock
        return txm._publish_serving_epoch_locked()

    # -- session gate ------------------------------------------------------
    def _gate_refused(self, msg: str, dialect: str,
                      floor_ms: int = 0) -> "ReplicaLagging":
        """Build one typed lagging redirect: counts the refusal, bumps
        the streak, and scales the retry hint with it (25..500 ms, the
        AdmissionGate discipline) — a parked fleet backs off harder the
        longer this replica has refused every read since its last
        admission, instead of hammering on a fixed hint."""
        from antidote_tpu.overload import ReplicaLagging, retry_hint_ms

        self._gate_streak += 1
        m = getattr(self.node, "metrics", None)
        if m is not None:
            m.session_redirects.inc(kind="lagging", dialect=dialect)
        return ReplicaLagging(
            msg, retry_after_ms=max(floor_ms,
                                    retry_hint_ms(self._gate_streak)),
            redirect=self.owner_client_addr,
        )

    def gate_read(self, objects, clock, deadline: Optional[float] = None,
                  dialect: str = "native") -> None:
        """Admission gate for session reads on this follower: park until
        the PER-SHARD applied clocks of every shard the read touches
        cover the token, then make sure the serving epoch cannot claim
        coverage it lacks; past the park window (or while not serving)
        answer a typed redirect instead — never a stale read.  Both wire
        dialects route here (``dialect`` labels the redirect metric);
        retry hints scale with the refusal streak since the last
        admitted read."""
        if self.state != "serving":
            raise self._gate_refused(
                f"follower {self.name} is {self.state}", dialect,
                floor_ms=250)
        if clock is None:
            self._gate_streak = 0
            return
        cfg = self.node.cfg
        vec = np.zeros(cfg.max_dcs, np.int64)
        cl = np.asarray(clock, np.int64)[:cfg.max_dcs]
        vec[:len(cl)] = cl
        shards = sorted({
            key_to_shard(freeze_key(k), b, cfg.n_shards)
            for (k, _t, b) in objects
        })
        end = time.monotonic() + self.park_s
        if deadline is not None:
            end = min(end, deadline)
        while True:
            store = self.node.store  # a heal may swap it mid-park
            if self.state != "serving":
                break
            if all((store.applied_vc[s] >= vec).all() for s in shards):
                self._ensure_epoch_covers(store, shards, vec, dialect)
                self._gate_streak = 0
                return
            if time.monotonic() >= end:
                break
            time.sleep(0.002)
        raise self._gate_refused(
            f"follower {self.name} applied clock is behind the session "
            f"token after a {int(self.park_s * 1e3)} ms park", dialect)

    def _ensure_epoch_covers(self, store, shards: List[int],
                             vec: np.ndarray,
                             dialect: str = "native") -> None:
        """The epoch-plane half of the gate: the live applied clocks
        cover the token, but the FROZEN serving epoch may predate the
        covering applies while its (cross-shard max) VC still claims the
        token — ping-skewed lanes make that possible.  Each epoch
        records the applied-clock cut it was captured at; when the
        current epoch would claim the token without covering it on the
        target shards, publish a fresh one (which captures the live,
        covering cut)."""
        for _attempt in range(2):
            ep = store.serving_epoch
            if ep is None:
                return  # no epoch: reads take the (live) locked path
            if not (vec[:len(ep.vc)] <= np.asarray(ep.vc, np.int64)).all():
                return  # epoch won't claim the token: locked path serves
            app = getattr(ep, "applied", None)
            if app is None or all((app[s] >= vec).all() for s in shards):
                return
            with self.node.txm.commit_lock:
                self.publish_applied_epoch_locked()
        raise self._gate_refused(
            f"follower {self.name} could not refresh its serving epoch "
            "to cover the session token (publish deferred)", dialect)

    # -- divergence detection ---------------------------------------------
    def _lag_result(self, mine_vc, owner_vc, origins) -> str:
        """Type a clock mismatch: ``unsubscribed`` when EVERY lane this
        replica trails on is a peer lane it was never given a
        descriptor for (--follower-peers), else ``skipped`` (replication
        in flight — retried next sweep).  An unsubscribed lane can never
        converge, so surfacing it typed (plus the attach-time warning)
        is the difference between a misconfiguration and a permanently
        green-looking check that never ran."""
        behind = [l for l in range(self.node.cfg.max_dcs)
                  if mine_vc[l] < owner_vc[l]]
        if not behind:
            return "skipped"  # ahead of the owner's cut: in-flight too
        subscribed = set(self.fleet_by_dc)
        origins = set(int(o) for o in (origins or []))
        unsub = [l for l in behind if l in origins
                 and l not in subscribed]
        if unsub and len(unsub) == len(behind):
            now = time.monotonic()
            if now - getattr(self, "_last_unsub_warn", 0.0) > 10.0:
                self._last_unsub_warn = now
                log.warning(
                    "follower %s: divergence checks trail on peer "
                    "lane(s) %s that this follower is NOT subscribed to "
                    "— pass the peer DC endpoint(s) via --follower-peers "
                    "or these checks can never converge", self.name,
                    unsub)
            return "unsubscribed"
        return "skipped"

    def check_divergence(self, shards=None) -> Dict[int, str]:
        """Compare per-shard Merkle roots against the owner at EQUAL
        applied clocks — each shard against WHICHEVER member owns it at
        the compared clock (the gossip-learned route; a mid-fleet shard
        move re-points the comparison with no reconnect).  ``skipped`` =
        clocks unequal (replication in flight — retried next sweep);
        ``unsubscribed`` = the lag is on a peer lane this follower has
        no descriptor for (typed misconfiguration, never silent);
        ``ok`` = roots match; ``mismatch`` = silent corruption — the
        follower quarantines, walks the tree in O(log n) hash
        comparisons to localize the diverged leaf range, and heals by
        fetching ONLY that range; a full image re-bootstrap remains the
        escalation when the range heal cannot converge."""
        m = getattr(self.node, "metrics", None)
        out: Dict[int, str] = {}
        for shard in (range(self.node.cfg.n_shards)
                      if shards is None else shards):
            shard = int(shard)
            try:
                reply = self.hub.request(
                    self._route(self.dc_id, shard), "merkle_root",
                    {"shard": shard})
            except Exception as e:
                log.warning("follower %s: divergence check for shard %d "
                            "unreachable (%r)", self.name, shard, e)
                out[shard] = "unreachable"
                continue
            from antidote_tpu.store.merkle import get_merkle

            store = self.node.store
            with self.node.txm.commit_lock:
                mine_vc = [int(x) for x in store.applied_vc[shard]]
                if mine_vc != [int(x) for x in reply["vc"]]:
                    result = self._lag_result(mine_vc, reply["vc"],
                                              reply.get("origins"))
                    mine = None
                else:
                    mk = get_merkle(store)
                    # detection must re-read the data (corruption
                    # bypasses the incremental marks); the walk and the
                    # leaf heal then reuse these fresh leaf hashes
                    mk.rescan(shard)
                    mine = mk.root(shard)
                    result = ("ok" if mine == reply["root"]
                              else "mismatch")
            self.divergence_counts[result] = (
                self.divergence_counts.get(result, 0) + 1)
            if m is not None:
                m.divergence_checks.inc(result=result)
            out[shard] = result
            if result == "mismatch":
                log.error(
                    "follower %s DIVERGED from the owner on shard %d at "
                    "applied clock %s (root %s != %s): quarantining and "
                    "healing the localized range",
                    self.name, shard, mine_vc, mine, reply["root"],
                )
                if not self._merkle_heal(shard):
                    log.error(
                        "follower %s: range heal for shard %d could not "
                        "converge; escalating to a full image "
                        "re-bootstrap", self.name, shard)
                    if m is not None:
                        m.divergence_heals.inc(mode="image")
                    self._heal("image")
                    break
        return out

    #: range-heal convergence attempts before escalating to a full
    #: image re-bootstrap (each attempt re-pins equal clocks)
    MERKLE_HEAL_ATTEMPTS = 8

    def _merkle_heal(self, shard: int) -> bool:
        """Localize + repair one shard's divergence: walk the owner's
        tree against ours (O(fanout·depth) hash comparisons per
        diverged leaf), fetch ONLY the diverged leaves' key states, and
        install them at equal applied clocks.  Quarantines for the
        duration (sessions get typed redirects), never wipes the store.
        Returns True once the roots agree again."""
        from antidote_tpu.store.merkle import get_merkle

        m = getattr(self.node, "metrics", None)
        prev_state, self.state = self.state, "healing"
        try:
            for _attempt in range(self.MERKLE_HEAL_ATTEMPTS):
                # re-resolve per attempt: a live shard move mid-heal
                # re-points at the new owning member (same discipline
                # as the sweep itself)
                target = self._route(self.dc_id, shard)
                store = self.node.store
                mk = get_merkle(store)
                try:
                    root = self.hub.request(target, "merkle_root",
                                            {"shard": shard})
                except Exception:
                    self._on_clock_wait()
                    continue
                try:
                    with self.node.txm.commit_lock:
                        mine_vc = [int(x)
                                   for x in store.applied_vc[shard]]
                        if mine_vc != [int(x) for x in root["vc"]]:
                            pass  # clocks moved: drain + retry below
                        elif mk.root(shard) == root["root"]:
                            if m is not None:
                                m.divergence_heals.inc(mode="range")
                            self._seal_heal(shard)
                            return True
                        else:
                            leaves = self._walk_diverged(
                                mk, shard, target, mine_vc)
                            if leaves is not None:
                                healed = all(
                                    self._heal_leaf(mk, shard, target,
                                                    leaf, mine_vc)
                                    for leaf in leaves)
                                if healed \
                                        and mk.root(shard) == root["root"]:
                                    if m is not None:
                                        m.divergence_heals.inc(
                                            mode="range")
                                    self._seal_heal(shard)
                                    return True
                except Exception:
                    # a mid-walk owner restart / cold-tier refusal must
                    # not crash the pump tick this check runs on: count
                    # the attempt and retry (escalating to the image
                    # re-bootstrap when the attempts run out)
                    log.warning(
                        "follower %s: merkle heal attempt for shard %d "
                        "failed mid-walk; retrying", self.name, shard,
                        exc_info=True)
                self._on_clock_wait()
            return False
        finally:
            if self.state == "healing":
                self.state = prev_state

    def _seal_heal(self, shard: int) -> None:
        """Make a range heal DURABLE: the corrupt bytes may already sit
        in a published image/link, and a delta cannot represent the
        phantom-row drops — force the next stamp to be a full rebase so
        a restart composes the healed state, not the diverged one."""
        cp = self.node.checkpointer
        if cp is not None:
            cp.force_rebase = True
            cp.request()

    def _walk_diverged(self, mk, shard: int, target: int, pin_vc):
        """Descend the tree from the root, following mismatching
        children only.  Returns the diverged leaf indices, or None when
        the owner's clock moved mid-walk (caller retries).  Runs under
        the commit lock (the local leaves must stay one cut)."""
        m = getattr(self.node, "metrics", None)
        frontier = [(0, 0)]
        depth = mk.depth()
        for level in range(depth):
            nxt = []
            for _lv, idx in frontier:
                reply = self.hub.request(target, "merkle_node", {
                    "shard": shard, "level": level, "index": idx})
                if [int(x) for x in reply["vc"]] != pin_vc:
                    return None  # owner moved on: retry the attempt
                mine = mk.children(shard, level, idx)
                if m is not None:
                    m.merkle_probe_hashes.inc(len(mine))
                for child, (a, b) in enumerate(zip(mine,
                                                   reply["hashes"])):
                    if a != b:
                        nxt.append((level + 1,
                                    idx * mk.fanout + child))
            frontier = nxt
            if not frontier:
                return []
        return [idx for _lv, idx in frontier]

    def _heal_leaf(self, mk, shard: int, target: int, leaf: int,
                   pin_vc) -> bool:
        """Replace one leaf's keys with the owner's states — the
        range-restricted fetch.  Runs under the commit lock; verifies
        the owner served the SAME applied cut (else a chain op could
        later double-apply over a newer head)."""
        reply = self.hub.request(target, "merkle_leaf",
                                 {"shard": shard, "leaf": leaf})
        if [int(x) for x in reply["vc"]] != pin_vc:
            return False
        store = self.node.store
        shipped = set()
        for key, bucket, tname, slots_ub, head_vc, heads in reply["keys"]:
            key = freeze_key(key)
            dk = (key, bucket)
            shipped.add(dk)
            self._install_healed_row(store, dk, tname, slots_ub,
                                     head_vc, heads)
            mk.mark(shard, dk)
        # keys we hold in this leaf that the owner does not: phantom
        # rows from the corruption — drop them (typed absence beats a
        # resurrecting ghost)
        for dk in mk.leaf_keys(shard, leaf) - shipped:
            ent = store.directory.get(dk)
            if ent is not None:
                t = store.table(ent[0])
                t.evict_rows(np.asarray([ent[1]]),  # evict-ok: Merkle
                             np.asarray([ent[2]]))  # range heal drops a
                # phantom row the owner's leaf does not contain
                store.directory.pop(dk, None)
            if store.cold is not None:
                store.cold.cold_set.discard(dk)
                store.cold.refs.pop(dk, None)
                s = store.cold.by_shard.get(shard)
                if s is not None:
                    s.discard(dk)
            store.drop_cached_value(dk)
            store.mark_epoch_fallback(dk)
            mk.mark(shard, dk)
        return True

    def _install_healed_row(self, store, dk, tname: str, slots_ub: int,
                            head_vc, heads) -> None:
        """Install one shipped key state: clear any existing row (even
        at another slot tier — promotion timing differs legitimately
        between replicas), then alloc + head install with a seeded
        snapshot version (same discipline as a cold fault-in)."""
        ent = store.directory.get(dk)
        if ent is None and store.cold is not None \
                and store.cold.is_cold(dk):
            # cold here, diverged at the owner: drop the cold ref and
            # install resident — the next rebase re-covers it
            ref = store.cold.refs.pop(dk, None)
            store.cold.cold_set.discard(dk)
            if ref is not None:
                s = store.cold.by_shard.get(ref.shard)
                if s is not None:
                    s.discard(dk)
        if ent is not None:
            t_old = store.table(ent[0])
            t_old.evict_rows(np.asarray([ent[1]]),  # evict-ok: Merkle
                             np.asarray([ent[2]]))  # range heal replaces
            # the (possibly corrupt) row with the owner's shipped state
            store.directory.pop(dk, None)
        t = store.table(tname)
        shard = int(ent[1]) if ent is not None else key_to_shard(
            dk[0], dk[1], store.cfg.n_shards)
        row = t.alloc_row(shard)
        head_rows = {}
        for f, spec in heads.items():
            head_rows[f] = np.frombuffer(
                spec["b"], np.dtype(spec["dt"])).reshape(
                spec["sh"])[None]
        t.install_rows(np.asarray([shard]), np.asarray([row]), head_rows,
                       np.asarray(head_vc, np.int32)[None])
        t.slots_ub[shard, row] = int(slots_ub)
        store.directory[dk] = (tname, shard, row)
        store.note_ckpt_dirty(dk)  # delta links must carry the heal
        store.drop_cached_value(dk)
        store.mark_epoch_fallback(dk)

    # -- liveness / status -------------------------------------------------
    def _send_report(self) -> None:
        if self.owner_fid is None:
            return
        body = {
            "name": self.name,
            "applied": [int(x) for x in self.node.store.dc_max_vc()],
            "addr": (list(self.client_addr)
                     if getattr(self, "client_addr", None) else None),
            "state": self.state,
            "boots": self.boots,
        }
        failed = 0
        # every owner-DC member keeps a registry, so replica-status
        # answers (and fleet-aware consoles work) against any of them
        fids = self.member_fids or [self.owner_fid]
        for fid in fids:
            try:
                reply = self.hub.request(fid, "follower_report", body)
            except Exception:
                failed += 1
                continue
            # the owner piggybacks its registry's serving-fleet snapshot
            # on the report ACK (ISSUE 17) — the proxy plane's health
            # table learns membership with zero extra round trips
            fleet = (reply or {}).get("fleet")
            if fleet is not None and fleet != self.fleet_table:
                self.fleet_table = fleet
                self.fleet_table_v += 1
        if failed == len(fids):
            # the whole owner DC is unreachable (partition / restart):
            # the subscription reconnect machinery owns the healing; the
            # owner meanwhile marks this follower DOWN by report age
            now = time.monotonic()
            if now - getattr(self, "_last_report_warn", 0.0) > 5.0:
                self._last_report_warn = now
                log.warning("follower %s: liveness report to the owner "
                            "failed; will keep retrying", self.name)

    def replica_status(self) -> dict:
        return {
            "role": "follower",
            "name": self.name,
            "state": self.state,
            "owner": (list(self.owner_client_addr)
                      if self.owner_client_addr else None),
            "applied": [int(x) for x in self.node.store.dc_max_vc()],
            "boots": self.boots,
            "last_bootstrap_mode": self.last_bootstrap_mode,
            "divergence": dict(self.divergence_counts),
            "fleet": {
                "owner_members": max(1, len(self.member_fids)),
                "peer_dcs": sorted(d for d in self.fleet_by_dc
                                   if d != self.dc_id),
            },
        }

    def replica_admin(self, body: dict) -> dict:
        if body.get("op", "status") == "status":
            return self.replica_status()
        raise RuntimeError(
            "replica add/remove are owner operations; this node is a "
            "follower"
        )


__all__ = ["FollowerReplica"]
