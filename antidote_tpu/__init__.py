"""antidote_tpu — a TPU-native transactional CRDT store.

A ground-up JAX/XLA rebuild of the capabilities of AntidoteDB
(reference: /root/reference, Erlang/riak_core): operation-based CRDTs,
causal+ snapshot transactions (Cure/ClockSI), per-key op logs, a batched
device materializer, vector-clock stable-snapshot computation, and
inter-replica causal replication.

Design stance (not a port):
  * vector clocks are dense ``i32[MAX_DCS]`` tensors, not dicts
    (reference: sparse dicts, /root/reference/include/antidote.hrl:187-188)
  * the per-key op-log fold (``clocksi_materializer:materialize/4``) is a
    batched masked scan over thousands of keys per device launch
  * the riak_core ring becomes a ``jax.sharding.Mesh`` over a ``shard`` axis
  * stable-snapshot = ``min`` collective over per-shard clock matrices
    (replaces meta_data_sender 1 s gossip rounds)
"""

import jax as _jax

# The framework stores 64-bit value handles / LWW timestamps in device
# arrays; without x64 jnp.int64 silently narrows to int32.
_jax.config.update("jax_enable_x64", True)

from antidote_tpu.config import AntidoteConfig  # noqa: E402

__version__ = "0.1.0"
__all__ = ["AntidoteConfig", "__version__"]
