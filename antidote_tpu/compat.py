"""jax version-compat shims.

The codebase targets the current jax API surface (``jax.shard_map``,
``jax.enable_x64``), but deployment images may carry an older 0.4.x jax
where those live under ``jax.experimental`` with slightly different
spellings (``shard_map(check_rep=...)``, ``enable_x64()``/
``disable_x64()`` context managers).  Every call site imports the shim
instead of probing ``jax`` itself, so the supported-version matrix is
encoded exactly once.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` when present, else the ``jax.experimental``
    form.  ``check_vma`` maps onto the old API's ``check_rep`` (both
    gate the replication/varying-manual-axes check)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def enable_x64(enable: bool = True):
    """Context manager toggling x64 mode: ``jax.enable_x64(flag)`` when
    present, else the paired ``jax.experimental.enable_x64()`` /
    ``disable_x64()`` managers of 0.4.x."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enable)
    from jax import experimental as _exp

    return _exp.enable_x64() if enable else _exp.disable_x64()
