"""Shared build + provenance helper for the native ``.so`` planes.

Every C++ module in the tree (interdc/cpp/pump.cc, proto/cpp/frontend.cc,
log/cpp/wal.cc) compiles through ONE pinned flag set, and every build
embeds the sha256 of its source as ``ANTIDOTE_SRC_SHA`` (each module
exports a ``<name>_src_sha()`` getter).  ``make native`` rebuilds all of
them; ``make native-check`` compares each checked-in binary's embedded
sha against the current source — the drift a hand-run g++ line can't
detect (the satellite of ISSUE 16: pump.cc's .so could silently diverge
from source before this existed).
"""

from __future__ import annotations

import ctypes
import hashlib
import pathlib
import subprocess
from typing import List, Optional, Tuple

#: the ONE compile line — loaders and `make native` must agree, or the
#: native-check comparison would chase flag drift instead of source drift
PINNED_FLAGS = ["-O2", "-shared", "-fPIC", "-std=c++17", "-pthread"]

_ROOT = pathlib.Path(__file__).parent

#: (source, checked-in .so, exported sha getter) for every native plane
#: that participates in `make native` / `make native-check`
MODULES: List[Tuple[pathlib.Path, pathlib.Path, str]] = [
    (_ROOT / "interdc" / "cpp" / "pump.cc",
     _ROOT / "interdc" / "cpp" / "_pump.so", "pump_src_sha"),
    (_ROOT / "proto" / "cpp" / "frontend.cc",
     _ROOT / "proto" / "cpp" / "_frontend.so", "frontend_src_sha"),
]


def src_sha(src: pathlib.Path) -> str:
    return hashlib.sha256(src.read_bytes()).hexdigest()


def build(src: pathlib.Path, out: pathlib.Path) -> str:
    """Compile ``src`` into ``out`` with the pinned flags, embedding the
    source sha; returns the sha."""
    sha = src_sha(src)
    subprocess.run(
        ["g++", *PINNED_FLAGS, f'-DANTIDOTE_SRC_SHA="{sha}"',
         str(src), "-o", str(out)],
        check=True, capture_output=True,
    )
    return sha


def ensure(src: pathlib.Path, so: pathlib.Path) -> pathlib.Path:
    """Rebuild ``so`` when missing or older than its source (the lazy
    first-use compile the loaders share)."""
    if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
        build(src, so)
    return so


def embedded_sha(so: pathlib.Path, getter: str) -> Optional[str]:
    """The source sha a built .so carries, or None when the binary
    predates the provenance scheme (no getter symbol)."""
    try:
        lib = ctypes.CDLL(str(so))
        fn = getattr(lib, getter)
    except (OSError, AttributeError):
        return None
    fn.restype = ctypes.c_char_p
    fn.argtypes = []
    out = fn()
    return out.decode() if out else None


def check() -> List[str]:
    """`make native-check`: one problem string per stale/missing binary
    (empty list = every checked-in .so matches its source)."""
    problems = []
    for src, so, getter in MODULES:
        if not so.exists():
            problems.append(f"{so.name}: missing (run `make native`)")
            continue
        want = src_sha(src)
        got = embedded_sha(so, getter)
        if got is None:
            problems.append(
                f"{so.name}: no embedded source sha — built outside "
                f"`make native` (rebuild to re-pin provenance)")
        elif got != want:
            problems.append(
                f"{so.name}: built from a different {src.name} "
                f"(embedded {got[:12]}…, source {want[:12]}…) — run "
                f"`make native`")
    return problems


def main() -> int:
    import sys

    if "--check" in sys.argv:
        problems = check()
        for p in problems:
            print(f"native-check: {p}")
        if not problems:
            print(f"native-check: {len(MODULES)} binaries match source")
        return 1 if problems else 0
    for src, so, _ in MODULES:
        sha = build(src, so)
        print(f"built {so.relative_to(_ROOT.parent)} ({sha[:12]}…)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
