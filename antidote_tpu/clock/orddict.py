"""Versioned-snapshot selection — the tensor analogue of ``vector_orddict``.

The reference keeps, per key, an ordered-by-VC list of up to 10 materialized
snapshots and serves a read from the newest entry whose VC is dominated by
the read VC (``vector_orddict:get_smaller/2``,
/root/reference/src/vector_orddict.erl:74-87).  Here each key has a fixed
ring of ``V`` snapshot versions: ``snap_vc[V, D]`` clocks plus a monotonically
increasing insertion sequence ``snap_seq[V]`` (0 = empty slot).  Selection is
a masked argmax over the version axis — one vectorized op per key instead of
a list walk.
"""

from __future__ import annotations

import jax.numpy as jnp

from antidote_tpu.clock import vector as vc


def get_smaller(snap_vc, snap_seq, read_vc):
    """Newest valid snapshot version dominated by ``read_vc``.

    Args:
      snap_vc:  ``i32[..., V, D]`` per-version clocks.
      snap_seq: ``i64[..., V]`` insertion sequence numbers; 0 marks an empty
                slot (matches "ignore" semantics of a missing orddict entry).
      read_vc:  ``i32[..., D]`` the read snapshot.

    Returns:
      ``(idx, found)`` — ``idx`` is ``i32[...]`` index into the version axis
      (0 when nothing matches) and ``found`` is a boolean mask.  A miss means
      the caller must fall back to folding from the bottom state (the
      reference falls back to a log replay,
      /root/reference/src/materializer_vnode.erl:415-419).
    """
    dominated = vc.le(snap_vc, read_vc[..., None, :])  # [..., V]
    valid = snap_seq > 0
    ok = dominated & valid
    score = jnp.where(ok, snap_seq, -1)
    idx = jnp.argmax(score, axis=-1).astype(jnp.int32)
    found = jnp.max(score, axis=-1) > -1
    return idx, found


def insert_slot(snap_seq):
    """Slot to overwrite for a new snapshot version: the oldest (min seq).

    Empty slots (seq 0) are naturally preferred.  Mirrors the ≤10-version
    ring with GC to ?SNAPSHOT_MIN (/root/reference/src/materializer_vnode.erl:513-563),
    collapsed to a fixed ring: inserting always evicts the oldest version.
    """
    return jnp.argmin(snap_seq, axis=-1).astype(jnp.int32)
