"""Vector clocks as dense integer tensors.

The reference represents vector clocks as sparse dicts keyed by dcid (the
``vectorclock`` dep; see /root/reference/include/antidote.hrl:187-188) and
compares them entry-wise (e.g. ``vectorclock:le`` used throughout
clocksi_materializer).  Here a VC is an ``i32[max_dcs]`` row — logical
per-DC commit counters — and every comparison is a vectorized lane op, so a
batch of VC comparisons is one fused XLA op rather than a dict fold per op
(/root/reference/src/clocksi_materializer.erl:214-268).

All functions broadcast: inputs may be ``[..., D]`` stacks of clocks.
"""

from __future__ import annotations

import jax.numpy as jnp

CLOCK_DTYPE = jnp.int32


def zero(max_dcs: int):
    """The bottom clock (vectorclock:new())."""
    return jnp.zeros((max_dcs,), dtype=CLOCK_DTYPE)


def le(a, b):
    """a ≤ b in the partial order (all entries ≤). vectorclock:le/2."""
    return jnp.all(a <= b, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def lt(a, b):
    """a ≤ b and a ≠ b (strict dominance)."""
    return le(a, b) & ~eq(a, b)


def concurrent(a, b):
    """Neither dominates (vector_orddict concurrency test,
    /root/reference/src/vector_orddict.erl:148-151)."""
    return ~le(a, b) & ~le(b, a)


def merge(a, b):
    """Entry-wise max (vectorclock:max)."""
    return jnp.maximum(a, b)


def vmin(a, b):
    """Entry-wise min (vectorclock:min) — the stable-snapshot merge
    (/root/reference/src/stable_time_functions.erl:51-85)."""
    return jnp.minimum(a, b)


def increment(vc, dc_index):
    """Bump one DC's entry by 1 (commit-counter advance)."""
    return vc.at[..., dc_index].add(1)


def dominates_ignoring(a, b, ignore_dc):
    """a ≥ b on every lane except ``ignore_dc``.

    Used by the inter-DC causal gate: a remote txn is applied once the local
    partition VC dominates the txn's snapshot VC with the origin entry
    zeroed (/root/reference/src/inter_dc_dep_vnode.erl:128-154).
    """
    d = a.shape[-1]
    lane_ok = a >= b
    ignore = jnp.arange(d) == ignore_dc
    return jnp.all(lane_ok | ignore, axis=-1)
