from antidote_tpu.clock.vector import (
    zero,
    le,
    lt,
    eq,
    concurrent,
    merge,
    vmin,
    increment,
    dominates_ignoring,
)

__all__ = [
    "zero",
    "le",
    "lt",
    "eq",
    "concurrent",
    "merge",
    "vmin",
    "increment",
    "dominates_ignoring",
]
