// wal.cc — per-shard append-only write-ahead log with group commit.
//
// Native replacement for the reference's OTP disk_log usage
// (/root/reference/src/logging_vnode.erl:896-919): one log file per shard,
// buffered appends, an explicit commit barrier, and an optional background
// fsync thread reproducing the sync_log=false default (async flush,
// /root/reference/src/antidote.app.src:44-48) without losing group-commit
// durability when sync_log=true.
//
// Record framing (read side is implemented in Python):
//   u32 magic 0xA17D07E1 | u32 payload_len | u32 crc32(payload) | payload
//
// C ABI for ctypes. Thread-safety: one writer per WAL handle (matches the
// single-commit-stream-per-shard architecture); the fsync thread only
// calls fdatasync on the fd.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <thread>

#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xA17D07E1;

uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static std::once_flag once;
  std::call_once(once, [] {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
  });
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Wal {
  int fd = -1;
  bool sync_on_commit = false;
  // group-commit state
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> appended_bytes{0};
  uint64_t synced_bytes = 0;
  std::thread syncer;
  int sync_interval_ms = 0;

  ~Wal() { close(); }

  void close() {
    if (syncer.joinable()) {
      stop.store(true);
      cv.notify_all();
      syncer.join();
    }
    if (fd >= 0) {
      ::fdatasync(fd);
      ::close(fd);
      fd = -1;
    }
  }
};

void sync_loop(Wal* w) {
  std::unique_lock<std::mutex> lk(w->mu);
  while (!w->stop.load()) {
    w->cv.wait_for(lk, std::chrono::milliseconds(w->sync_interval_ms));
    uint64_t cur = w->appended_bytes.load();
    if (cur != w->synced_bytes && w->fd >= 0) {
      ::fdatasync(w->fd);
      w->synced_bytes = cur;
    }
  }
}

}  // namespace

extern "C" {

// sync_on_commit: fdatasync inside every commit barrier (sync_log=true).
// sync_interval_ms > 0: background fsync thread (async durability).
void* wal_open(const char* path, int sync_on_commit, int sync_interval_ms) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  w->sync_on_commit = sync_on_commit != 0;
  w->sync_interval_ms = sync_interval_ms;
  if (sync_interval_ms > 0) w->syncer = std::thread(sync_loop, w);
  return w;
}

// Append one framed record; returns bytes written or -1.
int64_t wal_append(void* handle, const uint8_t* payload, uint32_t len) {
  Wal* w = static_cast<Wal*>(handle);
  uint32_t header[3] = {kMagic, len, crc32(payload, len)};
  struct iovec {
    const void* base;
    size_t len;
  };
  uint8_t frame[12];
  memcpy(frame, header, 12);
  // one writev-equivalent: build a single buffer for small records, two
  // writes otherwise (append-only fd keeps them contiguous)
  ssize_t n1 = ::write(w->fd, frame, 12);
  if (n1 != 12) return -1;
  size_t off = 0;
  while (off < len) {
    ssize_t n = ::write(w->fd, payload + off, len - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    off += static_cast<size_t>(n);
  }
  w->appended_bytes.fetch_add(12 + len);
  return static_cast<int64_t>(12 + len);
}

// Append a caller-framed buffer (one or many records already framed as
// magic|len|crc|payload by the Python side) in as few write() calls as
// the kernel allows.  This is the group-commit fast path: a merged
// commit batch becomes ONE buffer build + ONE write per touched
// segment instead of one ctypes round trip per record.  Returns bytes
// written or -1 (partial writes are the caller's to truncate away via
// wal_truncate — same contract as wal_append).
int64_t wal_append_raw(void* handle, const uint8_t* buf, uint64_t len) {
  Wal* w = static_cast<Wal*>(handle);
  uint64_t off = 0;
  while (off < len) {
    ssize_t n = ::write(w->fd, buf + off, len - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    off += static_cast<uint64_t>(n);
  }
  w->appended_bytes.fetch_add(len);
  return static_cast<int64_t>(len);
}

// Commit barrier: make everything appended so far durable if
// sync_on_commit; otherwise just a write barrier (group commit happens via
// the background syncer).
int wal_commit(void* handle) {
  Wal* w = static_cast<Wal*>(handle);
  if (w->sync_on_commit) {
    if (::fdatasync(w->fd) != 0) return -1;
    w->synced_bytes = w->appended_bytes.load();
  }
  return 0;
}

// Runtime toggle for fsync-on-commit (replicated sync_log flag flips,
// logging_vnode:set_sync_log).
void wal_set_sync(void* handle, int sync_on_commit) {
  Wal* w = static_cast<Wal*>(handle);
  w->sync_on_commit = sync_on_commit != 0;
}

int wal_sync(void* handle) {
  Wal* w = static_cast<Wal*>(handle);
  if (::fdatasync(w->fd) != 0) return -1;
  w->synced_bytes = w->appended_bytes.load();
  return 0;
}

int64_t wal_size(void* handle) {
  Wal* w = static_cast<Wal*>(handle);
  return static_cast<int64_t>(w->appended_bytes.load());
}

// Real end-of-file offset — includes any torn bytes a failed append left
// behind (appended_bytes only counts SUCCESSFUL appends this session),
// so a caller-saved tell() is a valid rollback point.
int64_t wal_tell(void* handle) {
  Wal* w = static_cast<Wal*>(handle);
  off_t end = ::lseek(w->fd, 0, SEEK_END);
  if (end < 0) return -1;
  return static_cast<int64_t>(end);
}

// Roll the file back to `off`: a failed group's records and any torn
// tail are discarded.  Shrinking allocates no blocks, so this works on
// the very full disk that made the append fail.
int wal_truncate(void* handle, int64_t off) {
  Wal* w = static_cast<Wal*>(handle);
  if (::ftruncate(w->fd, static_cast<off_t>(off)) != 0) return -1;
  return 0;
}

void wal_close(void* handle) { delete static_cast<Wal*>(handle); }

}  // extern "C"
