"""Write-ahead log: ctypes bindings to the C++ WAL + Python read side.

The durable per-shard op log replacing the reference's ``logging_vnode``
over disk_log (/root/reference/src/logging_vnode.erl:896-919): every
committed transaction's effects are framed and appended before the device
tables observe them; recovery and the incomplete-read fallback replay from
here (analogue of get_all / get_up_to_time,
/root/reference/src/logging_vnode.erl:185-228).

The native library is built lazily with g++ (shipped toolchain); a pure-
Python fallback keeps the API working where no compiler exists.
"""

from __future__ import annotations

import ctypes
import errno
import os
import struct
import subprocess
import time
import zlib
from pathlib import Path
from typing import Iterator
import msgpack

from antidote_tpu import faults

_MAGIC = 0xA17D07E1
_HDR = struct.Struct("<III")

_SRC = Path(__file__).parent / "cpp" / "wal.cc"
_SO = Path(__file__).parent / "cpp" / "_wal.so"

_lib = None
_lib_tried = False


def _load_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
                 str(_SRC), "-o", str(_SO)],
                check=True, capture_output=True,
            )
        # use_errno: a failed append/commit must surface WHICH OS error
        # (ENOSPC vs EIO vs ...) — the read-only degraded mode keys off it
        lib = ctypes.CDLL(str(_SO), use_errno=True)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.wal_append.restype = ctypes.c_int64
        lib.wal_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.wal_commit.restype = ctypes.c_int
        lib.wal_commit.argtypes = [ctypes.c_void_p]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_set_sync.restype = None
        lib.wal_set_sync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.wal_tell.restype = ctypes.c_int64
        lib.wal_tell.argtypes = [ctypes.c_void_p]
        lib.wal_truncate.restype = ctypes.c_int
        lib.wal_truncate.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


class ShardWAL:
    """Single-writer append log for one shard."""

    def __init__(self, path: str, sync_on_commit: bool = False,
                 sync_interval_ms: int = 100):
        self.path = path
        self.sync_on_commit = sync_on_commit
        lib = _load_lib()
        self._lib = lib
        self._h = None
        self._f = None
        if lib is not None:
            self._h = lib.wal_open(
                path.encode(), int(sync_on_commit), sync_interval_ms
            )
        if self._h is None:
            # pure-Python fallback
            self._f = open(path, "ab")

    @property
    def native(self) -> bool:
        return self._h is not None

    def _faulted_append(self) -> None:
        """Fault site "wal.append" (key = file basename): error/enospc/
        io_error raise before anything hits the file — the caller sees
        exactly what a full disk / dead device produces; delay sleeps in
        the append path (a stalling volume)."""
        d = faults.hit("wal.append", key=os.path.basename(self.path))
        if d is None:
            return
        if d.action == "enospc":
            raise OSError(
                errno.ENOSPC,
                f"injected fault: wal.append {self.path}: "
                "No space left on device",
            )
        if d.action == "io_error":
            raise OSError(
                errno.EIO,
                f"injected fault: wal.append {self.path}: "
                "Input/output error",
            )
        if d.action == "error":
            raise IOError(f"injected fault: wal.append {self.path}: {d.arg}")
        if d.action == "delay" and d.arg:
            time.sleep(float(d.arg))

    def append(self, record: dict) -> None:
        if faults.get_injector() is not None:
            self._faulted_append()
        payload = msgpack.packb(record, use_bin_type=True)
        start = self.tell()
        try:
            if self._h is not None:
                ctypes.set_errno(0)
                n = self._lib.wal_append(self._h, payload, len(payload))
                if n < 0:
                    raise self._native_oserror("wal_append")
            else:
                self._f.write(_HDR.pack(_MAGIC, len(payload),
                                        zlib.crc32(payload) & 0xFFFFFFFF))
                self._f.write(payload)
        except BaseException:
            # a partially-written frame must not stay on disk: replay
            # stops at the first torn record, so torn bytes followed by
            # LATER successful appends would silently hide those appends
            # from recovery.  Best-effort — shrinking needs no blocks.
            try:
                self.rollback_to(start)
            except OSError:
                pass
            raise

    def tell(self) -> int:
        """Current end-of-file offset (a rollback point for
        :meth:`rollback_to`)."""
        if self._h is not None:
            n = self._lib.wal_tell(self._h)
            if n < 0:
                raise self._native_oserror("wal_tell")
            return int(n)
        self._f.flush()
        return os.fstat(self._f.fileno()).st_size

    def rollback_to(self, off: int) -> None:
        """Discard everything appended past ``off`` (failed-group
        rollback; works on a full disk — truncation frees, never
        allocates)."""
        if self._h is not None:
            ctypes.set_errno(0)
            if self._lib.wal_truncate(self._h, int(off)) != 0:
                raise self._native_oserror("wal_truncate")
            return
        self._f.flush()
        self._f.truncate(off)

    def set_sync(self, sync: bool) -> None:
        """Runtime fsync-on-commit toggle, honored by both backends."""
        self.sync_on_commit = sync
        if self._h is not None:
            self._lib.wal_set_sync(self._h, int(sync))

    def _native_oserror(self, fn: str) -> OSError:
        """OSError carrying the native call's errno (the C side returns
        -1 with errno set).  A real full disk must look exactly like the
        injected one — errno is what flips the read-only degraded mode;
        0 (lost/overwritten errno) degrades to EIO so the commit still
        fails typed rather than with an errno-less IOError."""
        err = ctypes.get_errno() or errno.EIO
        return OSError(err, f"{fn} failed for {self.path}: "
                            f"{os.strerror(err)}")

    def commit(self) -> None:
        if self._h is not None:
            ctypes.set_errno(0)
            if self._lib.wal_commit(self._h) != 0:
                raise self._native_oserror("wal_commit")
        else:
            self._f.flush()
            if self.sync_on_commit:
                os.fsync(self._f.fileno())

    def sync(self) -> None:
        if self._h is not None:
            self._lib.wal_sync(self._h)
        else:
            self._f.flush()
            os.fsync(self._f.fileno())

    def probe(self) -> None:
        """Raise while appends would still fail; no-op once they can
        succeed again (the read-only degraded mode's auto-recovery
        probe).  Consults the same fault site as :meth:`append` (an
        injected ENOSPC keeps the probe failing until the rule stops
        firing), then proves the volume with a real, fsynced sidecar
        write — NOT an append to the log itself, which would poison
        replay with a non-effect record."""
        if faults.get_injector() is not None:
            self._faulted_append()
        p = self.path + ".probe"
        try:
            with open(p, "wb") as f:
                f.write(b"\0" * 4096)
                f.flush()
                os.fsync(f.fileno())
        finally:
            try:
                os.remove(p)
            except OSError:
                pass

    def close(self) -> None:
        if self._h is not None:
            self._lib.wal_close(self._h)
            self._h = None
        if self._f is not None:
            self._f.close()
            self._f = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass


def replay(path: str) -> Iterator[dict]:
    """Yield records from a WAL file; stops cleanly at a torn tail
    (crash mid-append), like disk_log repair-on-open."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return
            magic, ln, crc = _HDR.unpack(hdr)
            if magic != _MAGIC:
                return  # torn/corrupt tail
            payload = f.read(ln)
            if len(payload) < ln or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                return
            yield msgpack.unpackb(payload, raw=False, strict_map_key=False)
