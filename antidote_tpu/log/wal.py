"""Write-ahead log: ctypes bindings to the C++ WAL + Python read side.

The durable per-shard op log replacing the reference's ``logging_vnode``
over disk_log (/root/reference/src/logging_vnode.erl:896-919): every
committed transaction's effects are framed and appended before the device
tables observe them; recovery and the incomplete-read fallback replay from
here (analogue of get_all / get_up_to_time,
/root/reference/src/logging_vnode.erl:185-228).

The native library is built lazily with g++ (shipped toolchain); a pure-
Python fallback keeps the API working where no compiler exists.
"""

from __future__ import annotations

import ctypes
import errno
import heapq
import os
import struct
import subprocess
import threading
import time
import zlib
from pathlib import Path
from typing import Iterator, List, Optional, Sequence, Tuple
import msgpack

from antidote_tpu import faults

_MAGIC = 0xA17D07E1
_HDR = struct.Struct("<III")

_SRC = Path(__file__).parent / "cpp" / "wal.cc"
_SO = Path(__file__).parent / "cpp" / "_wal.so"

_lib = None
_lib_tried = False


def _load_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
                 str(_SRC), "-o", str(_SO)],
                check=True, capture_output=True,
            )
        # use_errno: a failed append/commit must surface WHICH OS error
        # (ENOSPC vs EIO vs ...) — the read-only degraded mode keys off it
        lib = ctypes.CDLL(str(_SO), use_errno=True)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.wal_append.restype = ctypes.c_int64
        lib.wal_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.wal_append_raw.restype = ctypes.c_int64
        lib.wal_append_raw.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
        lib.wal_commit.restype = ctypes.c_int
        lib.wal_commit.argtypes = [ctypes.c_void_p]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_set_sync.restype = None
        lib.wal_set_sync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.wal_tell.restype = ctypes.c_int64
        lib.wal_tell.argtypes = [ctypes.c_void_p]
        lib.wal_truncate.restype = ctypes.c_int
        lib.wal_truncate.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def pack_frames(payloads: Sequence[bytes]) -> bytes:
    """Frame several record payloads into one append buffer (the same
    magic|len|crc framing :func:`replay` reads).  Packing host-side lets
    a whole commit group reach the file in ONE write syscall
    (``append_packed``) instead of one native round trip per record."""
    parts = []
    for p in payloads:
        parts.append(_HDR.pack(_MAGIC, len(p), zlib.crc32(p) & 0xFFFFFFFF))
        parts.append(p)
    return b"".join(parts)


class ShardWAL:
    """Single-writer append log for one shard (or one shard segment)."""

    def __init__(self, path: str, sync_on_commit: bool = False,
                 sync_interval_ms: int = 100):
        self.path = path
        self.sync_on_commit = sync_on_commit
        #: bytes appended but not yet covered by an fsync (the
        #: per-segment WAL depth gauge's source; approximate under
        #: sync_log=false where the native background syncer drains it)
        self.pending_bytes = 0
        lib = _load_lib()
        self._lib = lib
        self._h = None
        self._f = None
        if lib is not None:
            self._h = lib.wal_open(
                path.encode(), int(sync_on_commit), sync_interval_ms
            )
        if self._h is None:
            # pure-Python fallback
            self._f = open(path, "ab")
        # end-of-file offset, tracked HOST-SIDE after the one open-time
        # probe: the append path used to pay an lseek round trip per
        # record just to learn its own rollback point (two, with the
        # group wrapper's) — at ~75 µs a ctypes call on a small host
        # that was the measured majority of the per-append floor.  The
        # fd is append-only and single-writer, so arithmetic is exact;
        # see the caveat in :meth:`append` for the failed-truncate case.
        self._end = self._tell_fs()

    @property
    def native(self) -> bool:
        return self._h is not None

    def _faulted_append(self) -> None:
        """Fault site "wal.append" (key = file basename): error/enospc/
        io_error raise before anything hits the file — the caller sees
        exactly what a full disk / dead device produces; delay sleeps in
        the append path (a stalling volume)."""
        d = faults.hit("wal.append", key=os.path.basename(self.path))
        if d is None:
            return
        if d.action == "enospc":
            raise OSError(
                errno.ENOSPC,
                f"injected fault: wal.append {self.path}: "
                "No space left on device",
            )
        if d.action == "io_error":
            raise OSError(
                errno.EIO,
                f"injected fault: wal.append {self.path}: "
                "Input/output error",
            )
        if d.action == "error":
            raise IOError(f"injected fault: wal.append {self.path}: {d.arg}")
        if d.action == "delay" and d.arg:
            time.sleep(float(d.arg))

    def append(self, record: dict) -> None:
        """Append one framed record.  On failure the torn frame is
        truncated away (replay stops at the first torn record, so torn
        bytes followed by LATER successful appends would silently hide
        those appends from recovery).  Caveat: if that heal itself fails
        (the disk is dying), the host-tracked offset can fall behind the
        torn tail — replay's CRC guard still stops there, same as the
        pre-tracking behavior."""
        self.append_packed(pack_frames(
            [msgpack.packb(record, use_bin_type=True)]))

    def append_packed(self, buf: bytes) -> None:
        """Append a :func:`pack_frames` buffer (1..N records) in one
        write; rolls the torn tail back on failure like :meth:`append`."""
        if faults.get_injector() is not None:
            self._faulted_append()
        start = self._end
        try:
            if self._h is not None:
                ctypes.set_errno(0)
                n = self._lib.wal_append_raw(self._h, buf, len(buf))
                if n < 0:
                    raise self._native_oserror("wal_append_raw")
            else:
                self._f.write(buf)
        except BaseException:
            # best-effort heal — shrinking needs no blocks
            try:
                self.rollback_to(start)
            except OSError:
                pass
            raise
        self._end = start + len(buf)
        self.pending_bytes += len(buf)

    def _tell_fs(self) -> int:
        """Real end-of-file offset from the filesystem (open-time seed
        for the host-tracked offset; includes any torn tail a crash
        left, so the first rollback point is still valid)."""
        if self._h is not None:
            n = self._lib.wal_tell(self._h)
            if n < 0:
                raise self._native_oserror("wal_tell")
            return int(n)
        self._f.flush()
        return os.fstat(self._f.fileno()).st_size

    def tell(self) -> int:
        """Current end-of-file offset (a rollback point for
        :meth:`rollback_to`) — host arithmetic, no syscall."""
        return self._end

    def rollback_to(self, off: int) -> None:
        """Discard everything appended past ``off`` (failed-group
        rollback; works on a full disk — truncation frees, never
        allocates)."""
        if self._h is not None:
            ctypes.set_errno(0)
            if self._lib.wal_truncate(self._h, int(off)) != 0:
                raise self._native_oserror("wal_truncate")
        else:
            self._f.flush()
            self._f.truncate(off)
        self.pending_bytes = max(0, self.pending_bytes - (self._end - off))
        self._end = off

    def set_sync(self, sync: bool) -> None:
        """Runtime fsync-on-commit toggle, honored by both backends."""
        self.sync_on_commit = sync
        if self._h is not None:
            self._lib.wal_set_sync(self._h, int(sync))

    def _native_oserror(self, fn: str) -> OSError:
        """OSError carrying the native call's errno (the C side returns
        -1 with errno set).  A real full disk must look exactly like the
        injected one — errno is what flips the read-only degraded mode;
        0 (lost/overwritten errno) degrades to EIO so the commit still
        fails typed rather than with an errno-less IOError."""
        err = ctypes.get_errno() or errno.EIO
        return OSError(err, f"{fn} failed for {self.path}: "
                            f"{os.strerror(err)}")

    def _faulted_fsync(self) -> None:
        """Fault site "wal.fsync" (key = file basename): delay stretches
        the fsync window (chaos scenario 13 SIGKILLs inside it);
        error/io_error fail the covering group-fsync ticket."""
        d = faults.hit("wal.fsync", key=os.path.basename(self.path))
        if d is None:
            return
        if d.action == "delay" and d.arg:
            time.sleep(float(d.arg))
        elif d.action in ("error", "io_error", "enospc"):
            err = errno.ENOSPC if d.action == "enospc" else errno.EIO
            raise OSError(err, f"injected fault: wal.fsync {self.path}")

    def commit(self) -> None:
        covered = self.pending_bytes
        if self._h is None and self._f is None:
            return  # retired segment (generation rotation) — nothing to flush
        if self._h is not None:
            ctypes.set_errno(0)
            if self._lib.wal_commit(self._h) != 0:
                raise self._native_oserror("wal_commit")
        else:
            self._f.flush()
            if self.sync_on_commit:
                os.fsync(self._f.fileno())
        # a barrier (fsynced or not) drains the depth gauge: depth
        # measures bytes between commit barriers, the write-plane's
        # in-flight durability debt.  Subtract the covered delta
        # rather than zeroing: appends are serialized under the commit
        # lock while their barrier waits, but a delta can never erase
        # bytes a racing append added after the snapshot
        self.pending_bytes -= covered

    def sync(self) -> None:
        covered = self.pending_bytes
        if self._h is None and self._f is None:
            # retired segment: a commit barrier that raced the generation
            # rotation may still submit it to the fsync coordinator — its
            # records are covered by the checkpoint image by then, so a
            # no-op is the correct durability answer (never a crash)
            return
        if faults.get_injector() is not None:
            self._faulted_fsync()
        if self._h is not None:
            ctypes.set_errno(0)
            if self._lib.wal_sync(self._h) != 0:
                raise self._native_oserror("wal_sync")
        else:
            self._f.flush()
            os.fsync(self._f.fileno())
        # delta, not zero (see commit()): the fsync covers exactly the
        # bytes that existed when it started
        self.pending_bytes -= covered

    def probe(self) -> None:
        """Raise while appends would still fail; no-op once they can
        succeed again (the read-only degraded mode's auto-recovery
        probe).  Consults the same fault site as :meth:`append` (an
        injected ENOSPC keeps the probe failing until the rule stops
        firing), then proves the volume with a real, fsynced sidecar
        write — NOT an append to the log itself, which would poison
        replay with a non-effect record."""
        if faults.get_injector() is not None:
            self._faulted_append()
        p = self.path + ".probe"
        try:
            with open(p, "wb") as f:
                f.write(b"\0" * 4096)
                f.flush()
                os.fsync(f.fileno())
        finally:
            try:
                os.remove(p)
            except OSError:
                pass

    def close(self) -> None:
        if self._h is not None:
            self._lib.wal_close(self._h)
            self._h = None
        if self._f is not None:
            self._f.close()
            self._f = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass


class FsyncTicket:
    """A commit barrier's handle on the group-fsync coordinator: the ack
    holding it may release once :meth:`wait` returns — the covering
    fsync completed (or the barrier needed none)."""

    __slots__ = ("_ev", "_err")

    def __init__(self, done: bool = False):
        self._ev = threading.Event()
        self._err: Optional[BaseException] = None
        if done:
            self._ev.set()

    def done(self, err: Optional[BaseException] = None) -> None:
        self._err = err
        self._ev.set()

    def wait(self, timeout: Optional[float] = 60.0) -> None:
        if not self._ev.wait(timeout):
            raise TimeoutError("WAL group fsync stalled")
        if self._err is not None:
            raise self._err


def ready_ticket() -> FsyncTicket:
    return FsyncTicket(done=True)


class GroupFsyncCoordinator:
    """Batches fsync requests across WAL segments (group commit).

    Commit barriers submit the segments they dirtied and get a ticket;
    the coordinator thread drains every pending request at once, fsyncs
    each distinct segment ONCE, and completes all covered tickets — so
    K barriers racing in (merged batches, remote-ingress applies, the
    next group arriving while the previous one syncs) cost one fsync
    per segment, not K.  A segment whose fsync fails fails exactly the
    tickets that cover it, with the OSError (the read-only degraded
    mode keys off its errno upstream)."""

    def __init__(self, on_batch=None):
        #: called with the number of barriers covered per fsync pass
        #: (the antidote_wal_fsync_batch histogram's feed)
        self.on_batch = on_batch
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # bounded-by: commit admission — each pending entry is a parked
        # commit barrier, and those are capped by max_commit_backlog
        self._pending: List[Tuple[FsyncTicket, list]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = False

    def submit(self, segments: list) -> FsyncTicket:
        """``segments``: ShardWAL objects to make durable up to their
        current end.  Returns the covering ticket."""
        if not segments:
            return ready_ticket()
        t = FsyncTicket()
        with self._cv:
            if self._stop:
                raise RuntimeError("fsync coordinator closed")
            self._pending.append((t, list(segments)))
            if self._thread is None:  # lazy: most logs never sync
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="antidote-wal-fsync"
                )
                self._thread.start()
            self._cv.notify()
        return t

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                batch, self._pending = self._pending, []
                if not batch and self._stop:
                    return
            self._run_batch(batch)

    def _run_batch(self, batch) -> None:
        failed: dict = {}
        synced: set = set()
        for _t, segs in batch:
            for s in segs:
                if id(s) in synced or id(s) in failed:
                    continue
                try:
                    s.sync()
                except OSError as e:
                    failed[id(s)] = e
                else:
                    synced.add(id(s))
        for t, segs in batch:
            err = next((failed[id(s)] for s in segs if id(s) in failed),
                       None)
            t.done(err)
        if self.on_batch is not None:
            try:
                self.on_batch(len(batch))
            except Exception:
                pass

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            th = self._thread
        if th is not None:
            th.join(timeout=10)
        # fail anything that raced in behind the stop
        with self._cv:
            pending, self._pending = self._pending, []
        for t, _segs in pending:
            t.done(RuntimeError("fsync coordinator closed"))


def replay_segments(paths: Sequence[str]) -> Iterator[dict]:
    """Merge several WAL segment files of ONE shard back into commit
    order.  Records carry a per-shard append sequence ``"q"``; legacy
    records (pre-segmentation) have none, exist only in segment 0, and
    precede every sequenced record, so positional order within segment
    0 followed by a q-merge across all segments reconstructs the exact
    append order."""

    def keyed(path):
        for pos, rec in enumerate(replay(path)):
            q = rec.get("q")
            yield ((0, pos) if q is None else (1, int(q))), rec

    for _k, rec in heapq.merge(*[keyed(p) for p in paths],
                               key=lambda item: item[0]):
        yield rec


def wholly_below(path: str, floor: int) -> bool:
    """True iff every decodable record in ``path`` is covered by a
    checkpoint floor: its append sequence ``"q"`` is ≤ ``floor``, or it
    is a legacy (pre-segmentation) record with no ``"q"`` at all — those
    can only predate any checkpoint, since checkpointing builds stamp a
    sequence on every record.  The reclaim guard: a WAL file may be
    deleted only when this holds (never a raw unlink)."""
    for rec in replay(path):
        q = rec.get("q")
        if q is not None and int(q) > floor:
            return False
    return True


def replay(path: str) -> Iterator[dict]:
    """Yield records from a WAL file; stops cleanly at a torn tail
    (crash mid-append), like disk_log repair-on-open."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return
            magic, ln, crc = _HDR.unpack(hdr)
            if magic != _MAGIC:
                return  # torn/corrupt tail
            payload = f.read(ln)
            if len(payload) < ln or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                return
            yield msgpack.unpackb(payload, raw=False, strict_map_key=False)
