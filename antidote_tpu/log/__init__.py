"""Durable op log: per-shard WALs + op-id chains + recovery replay.

The logging layer of the rebuild (reference: ``logging_vnode``, SURVEY
§2.4): effects are logged (with their blob payloads) before the device
tables observe them, per-(shard, origin-DC) op-ids chain monotonically for
gap detection (the #op_number scheme,
/root/reference/src/logging_vnode.erl:388-439), and recovery replays every
shard's log to rebuild tables, clocks and op-id counters
(/root/reference/src/logging_vnode.erl:595-643; recover_from_log,
/root/reference/src/materializer_vnode.erl:192-216).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.log.wal import ShardWAL, replay

__all__ = ["LogManager", "ShardWAL", "replay"]

_META_FILE = "antidote_meta.json"


class LogDirMismatch(RuntimeError):
    """The log directory was written under a different deployment shape."""


def load_dir_meta(directory: str) -> Optional[dict]:
    """The {n_shards, max_dcs} a log directory was created with, or None
    for a fresh/legacy directory."""
    path = os.path.join(directory, _META_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise LogDirMismatch(
            f"log dir metadata {path!r} is unreadable ({e}); if a crash "
            "truncated it, restore it as "
            '{"n_shards": N, "max_dcs": D, "version": 1} matching the '
            "directory's original deployment shape"
        ) from e


def _set_dir_meta_key(directory: str, key: str, value) -> None:
    """Atomically (write-temp + fsync + rename) set one key in a log
    dir's metadata file."""
    path = os.path.join(directory, _META_FILE)
    meta = load_dir_meta(directory) or {}
    meta[key] = value
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def mark_dir_retired(directory: str, by_epoch: int) -> None:
    """Stamp a log dir as superseded by a membership-layout change.

    Offline resize moves every shard's data into the NEW layout's dirs;
    an old-dir member booted afterwards would serve (and extend!) a
    stale copy of shards that now have different owners — a split-brain
    the riak_core ring epoch prevents in the reference.  Retired dirs
    refuse to boot until an operator consciously clears the stamp."""
    _set_dir_meta_key(directory, "retired_by_layout_epoch", int(by_epoch))


def stamp_layout_epoch(directory: str, epoch: int) -> None:
    """Record the membership-layout epoch a dir belongs to."""
    _set_dir_meta_key(directory, "layout_epoch", int(epoch))


def _validate_dir(cfg: AntidoteConfig, directory: str) -> None:
    """First boot stamps the deployment shape into the log directory;
    every later boot validates it.  Booting a WAL directory with a
    different shard count would silently strand or mis-route committed
    data, and a different max_dcs would mis-lane every recovered clock —
    the riak_core ring metadata persisted next to the data guards the
    reference against the same operator error (r1 advisor medium (a))."""
    meta = load_dir_meta(directory)
    if meta is not None:
        retired = meta.get("retired_by_layout_epoch")
        if retired is not None:
            raise LogDirMismatch(
                f"log dir {directory!r} was retired by membership-layout "
                f"epoch {retired} (its shards moved to the new layout's "
                "dirs at resize); booting it would serve and extend a "
                "stale pre-resize copy.  If this is intentional "
                "(restoring a backup), delete the "
                "'retired_by_layout_epoch' key from antidote_meta.json."
            )
        if (meta["n_shards"] != cfg.n_shards
                or meta["max_dcs"] != cfg.max_dcs):
            raise LogDirMismatch(
                f"log dir {directory!r} was created with n_shards="
                f"{meta['n_shards']}, max_dcs={meta['max_dcs']}; booting "
                f"with n_shards={cfg.n_shards}, max_dcs={cfg.max_dcs} "
                "would lose or corrupt committed data.  Use the recorded "
                "shape (or reshard via store.handoff.reshard into a new "
                "directory)."
            )
        return
    # legacy dir (pre-metadata build): shard files are created eagerly, so
    # their count IS the shape it was written with — any mismatch (shrink
    # OR grow) mis-routes recovered keys; a max_dcs mismatch is visible in
    # the clock width of any logged record
    shard_files = {
        int(m.group(1))
        for f in os.listdir(directory)
        if (m := re.fullmatch(r"shard_(\d+)\.wal", f))
    }
    if shard_files and shard_files != set(range(cfg.n_shards)):
        raise LogDirMismatch(
            f"legacy log dir {directory!r} holds shard files "
            f"{sorted(shard_files)} — written with n_shards="
            f"{len(shard_files)}, not {cfg.n_shards}"
        )
    for p in sorted(shard_files):
        for rec in replay(os.path.join(directory, f"shard_{p}.wal")):
            if len(rec["vc"]) != cfg.max_dcs:
                raise LogDirMismatch(
                    f"legacy log dir {directory!r} records carry "
                    f"{len(rec['vc'])}-lane clocks — written with "
                    f"max_dcs={len(rec['vc'])}, not {cfg.max_dcs}"
                )
            break  # one record per shard suffices
    # adopt: stamp the shape atomically (a crash mid-write must not leave
    # a truncated file that poisons every later boot)
    tmp = os.path.join(directory, _META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"n_shards": cfg.n_shards, "max_dcs": cfg.max_dcs,
                   "version": 1}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, _META_FILE))


class LogManager:
    def __init__(self, cfg: AntidoteConfig, directory: str,
                 sync_on_commit: Optional[bool] = None):
        self.cfg = cfg
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        _validate_dir(cfg, directory)
        sync = cfg.sync_log if sync_on_commit is None else sync_on_commit
        self.wals = [
            ShardWAL(os.path.join(directory, f"shard_{p}.wal"),
                     sync_on_commit=sync)
            for p in range(cfg.n_shards)
        ]
        #: per-(shard, origin) monotone op-id chain
        self.op_ids = np.zeros((cfg.n_shards, cfg.max_dcs), np.int64)
        #: blob handles already persisted per shard (avoid re-writing bytes)
        self._blob_seen = [set() for _ in range(cfg.n_shards)]

    def _append_one(self, shard: int, key, type_name: str, bucket: str,
                    eff_a, eff_b, commit_vc, origin: int,
                    blob_refs) -> Tuple[int, List[int]]:
        """Append one record; a failed append rolls the op-id chain and
        blob-dedup memory back (the WAL itself heals its torn frame), so
        a refused write never leaves a permanent op-id GAP for egress to
        publish.  Returns (opid, blob hashes first seen here)."""
        self.op_ids[shard, origin] += 1
        opid = int(self.op_ids[shard, origin])
        blobs = [
            (int(h), bytes(data))
            for h, data in blob_refs
            if h not in self._blob_seen[shard]
        ]
        new_hashes = [h for h, _ in blobs]
        for h in new_hashes:
            self._blob_seen[shard].add(h)
        try:
            self.wals[shard].append({
                "k": key,
                "b": bucket,
                "t": type_name,
                "a": np.asarray(eff_a, np.int64).tobytes(),
                "eb": np.asarray(eff_b, np.int32).tobytes(),
                "vc": [int(x) for x in np.asarray(commit_vc)],
                "o": int(origin),
                "id": opid,
                "bl": blobs,
            })
        except BaseException:
            self.op_ids[shard, origin] -= 1
            for h in new_hashes:
                self._blob_seen[shard].discard(h)
            raise
        return opid, new_hashes

    def log_effect(self, shard: int, key, type_name: str, bucket: str,
                   eff_a: np.ndarray, eff_b: np.ndarray, commit_vc, origin: int,
                   blob_refs=()) -> int:
        """Append one effect record; returns its op-id in the
        (shard, origin) chain."""
        opid, _ = self._append_one(shard, key, type_name, bucket,
                                   eff_a, eff_b, commit_vc, origin, blob_refs)
        return opid

    def log_effects(self, entries) -> None:
        """Append one commit group's records, atomically with respect to
        FAILURE: an OSError on a later record (ENOSPC mid-group) rolls
        every touched WAL, op-id chain and blob-dedup entry back to the
        pre-group state.  Without this, a NACKed group left a durable
        prefix that recovery replay resurrected — writes the clients
        were told failed came back locally (and were never published
        inter-DC, so DCs diverged).

        ``entries``: iterable of ``log_effect`` argument tuples
        ``(shard, key, type_name, bucket, eff_a, eff_b, commit_vc,
        origin, blob_refs)``."""
        offs: Dict[int, int] = {}
        op_snap = self.op_ids.copy()
        added: List[Tuple[int, int]] = []  # (shard, blob hash) logged
        try:
            for (shard, key, tname, bucket, ea, eb, vc, origin,
                 brefs) in entries:
                if shard not in offs:
                    offs[shard] = self.wals[shard].tell()
                _, new_hashes = self._append_one(
                    shard, key, tname, bucket, ea, eb, vc, origin, brefs)
                added.extend((shard, h) for h in new_hashes)
        except BaseException:
            for s, off in offs.items():
                try:
                    self.wals[s].rollback_to(off)
                except OSError:
                    pass  # the disk is failing; replay's CRC guard
                    # still stops at whatever half-frame remains
            self.op_ids[:] = op_snap
            for s, h in added:
                self._blob_seen[s].discard(h)
            raise

    def set_sync(self, sync: bool) -> None:
        """Runtime fsync-on-commit toggle (logging_vnode:set_sync_log,
        /root/reference/src/logging_vnode.erl:256-258)."""
        for w in self.wals:
            w.set_sync(sync)

    def commit_barrier(self, shards) -> None:
        for p in set(int(s) for s in shards):
            self.wals[p].commit()

    def probe_append(self) -> None:
        """Raise while ANY shard's WAL appends would still fail
        (degraded-mode recovery probe — see ShardWAL.probe).  Every
        shard is probed: a failure scoped to one file (bad block,
        per-file fault rule) must keep the node read-only, not flap it
        out on a healthy sibling's success."""
        for w in self.wals:
            w.probe()

    def truncate_shard(self, shard: int) -> None:
        """Discard one shard's log (post-handoff cleanup: the records now
        live in the receiver's chain).  Resets the shard's op-id chains and
        blob-dedup memory along with the file."""
        path = os.path.join(self.dir, f"shard_{shard}.wal")
        self.wals[shard].close()
        if os.path.exists(path):
            os.remove(path)
        self.wals[shard] = ShardWAL(
            path, sync_on_commit=self.wals[shard].sync_on_commit
        )
        self.op_ids[shard] = 0
        self._blob_seen[shard].clear()

    def replay_shard(self, shard: int) -> Iterator[dict]:
        return replay(os.path.join(self.dir, f"shard_{shard}.wal"))

    def replay_key(self, shard: int, key, bucket: str) -> List[dict]:
        """Scan one shard's log for a key's ops (the reference's whole-log
        scan + filter, /root/reference/src/logging_vnode.erl:663-702)."""
        from antidote_tpu.store.kv import freeze_key

        return [
            r for r in self.replay_shard(shard)
            if freeze_key(r["k"]) == key and r["b"] == bucket
        ]

    def close(self) -> None:
        for w in self.wals:
            w.close()
