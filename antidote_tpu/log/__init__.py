"""Durable op log: per-shard WALs + op-id chains + recovery replay.

The logging layer of the rebuild (reference: ``logging_vnode``, SURVEY
§2.4): effects are logged (with their blob payloads) before the device
tables observe them, per-(shard, origin-DC) op-ids chain monotonically for
gap detection (the #op_number scheme,
/root/reference/src/logging_vnode.erl:388-439), and recovery replays every
shard's log to rebuild tables, clocks and op-id counters
(/root/reference/src/logging_vnode.erl:595-643; recover_from_log,
/root/reference/src/materializer_vnode.erl:192-216).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.log.wal import ShardWAL, replay

__all__ = ["LogManager", "ShardWAL", "replay"]


class LogManager:
    def __init__(self, cfg: AntidoteConfig, directory: str,
                 sync_on_commit: Optional[bool] = None):
        self.cfg = cfg
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        sync = cfg.sync_log if sync_on_commit is None else sync_on_commit
        self.wals = [
            ShardWAL(os.path.join(directory, f"shard_{p}.wal"),
                     sync_on_commit=sync)
            for p in range(cfg.n_shards)
        ]
        #: per-(shard, origin) monotone op-id chain
        self.op_ids = np.zeros((cfg.n_shards, cfg.max_dcs), np.int64)
        #: blob handles already persisted per shard (avoid re-writing bytes)
        self._blob_seen = [set() for _ in range(cfg.n_shards)]

    def log_effect(self, shard: int, key, type_name: str, bucket: str,
                   eff_a: np.ndarray, eff_b: np.ndarray, commit_vc, origin: int,
                   blob_refs=()) -> int:
        """Append one effect record; returns its op-id in the
        (shard, origin) chain."""
        self.op_ids[shard, origin] += 1
        opid = int(self.op_ids[shard, origin])
        blobs = [
            (int(h), bytes(data))
            for h, data in blob_refs
            if h not in self._blob_seen[shard]
        ]
        for h, _ in blobs:
            self._blob_seen[shard].add(h)
        self.wals[shard].append({
            "k": key,
            "b": bucket,
            "t": type_name,
            "a": np.asarray(eff_a, np.int64).tobytes(),
            "eb": np.asarray(eff_b, np.int32).tobytes(),
            "vc": [int(x) for x in np.asarray(commit_vc)],
            "o": int(origin),
            "id": opid,
            "bl": blobs,
        })
        return opid

    def set_sync(self, sync: bool) -> None:
        """Runtime fsync-on-commit toggle (logging_vnode:set_sync_log,
        /root/reference/src/logging_vnode.erl:256-258)."""
        for w in self.wals:
            w.set_sync(sync)

    def commit_barrier(self, shards) -> None:
        for p in set(int(s) for s in shards):
            self.wals[p].commit()

    def truncate_shard(self, shard: int) -> None:
        """Discard one shard's log (post-handoff cleanup: the records now
        live in the receiver's chain).  Resets the shard's op-id chains and
        blob-dedup memory along with the file."""
        path = os.path.join(self.dir, f"shard_{shard}.wal")
        self.wals[shard].close()
        if os.path.exists(path):
            os.remove(path)
        self.wals[shard] = ShardWAL(
            path, sync_on_commit=self.wals[shard].sync_on_commit
        )
        self.op_ids[shard] = 0
        self._blob_seen[shard].clear()

    def replay_shard(self, shard: int) -> Iterator[dict]:
        return replay(os.path.join(self.dir, f"shard_{shard}.wal"))

    def replay_key(self, shard: int, key, bucket: str) -> List[dict]:
        """Scan one shard's log for a key's ops (the reference's whole-log
        scan + filter, /root/reference/src/logging_vnode.erl:663-702)."""
        from antidote_tpu.store.kv import freeze_key

        return [
            r for r in self.replay_shard(shard)
            if freeze_key(r["k"]) == key and r["b"] == bucket
        ]

    def close(self) -> None:
        for w in self.wals:
            w.close()
