"""Durable op log: per-shard WALs + op-id chains + recovery replay.

The logging layer of the rebuild (reference: ``logging_vnode``, SURVEY
§2.4): effects are logged (with their blob payloads) before the device
tables observe them, per-(shard, origin-DC) op-ids chain monotonically for
gap detection (the #op_number scheme,
/root/reference/src/logging_vnode.erl:388-439), and recovery replays every
shard's log to rebuild tables, clocks and op-id counters
(/root/reference/src/logging_vnode.erl:595-643; recover_from_log,
/root/reference/src/materializer_vnode.erl:192-216).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.log.wal import (
    FsyncTicket,
    GroupFsyncCoordinator,
    ShardWAL,
    pack_frames,
    ready_ticket,
    replay,
    replay_segments,
    wholly_below,
)

__all__ = ["LogManager", "SegmentedShardWAL", "ShardWAL", "FsyncTicket",
           "replay", "replay_segments", "shard_segment_paths",
           "gen_segment_paths", "wholly_below"]

_META_FILE = "antidote_meta.json"


class LogDirMismatch(RuntimeError):
    """The log directory was written under a different deployment shape."""


def load_dir_meta(directory: str) -> Optional[dict]:
    """The {n_shards, max_dcs} a log directory was created with, or None
    for a fresh/legacy directory."""
    path = os.path.join(directory, _META_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise LogDirMismatch(
            f"log dir metadata {path!r} is unreadable ({e}); if a crash "
            "truncated it, restore it as "
            '{"n_shards": N, "max_dcs": D, "version": 1} matching the '
            "directory's original deployment shape"
        ) from e


def _set_dir_meta_key(directory: str, key: str, value) -> None:
    """Atomically (write-temp + fsync + rename) set one key in a log
    dir's metadata file."""
    path = os.path.join(directory, _META_FILE)
    meta = load_dir_meta(directory) or {}
    meta[key] = value
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())  # fsync-ok: dir-meta atomic replace, not a
        # log append — the group-fsync policy governs record durability
    os.replace(tmp, path)


def mark_dir_retired(directory: str, by_epoch: int) -> None:
    """Stamp a log dir as superseded by a membership-layout change.

    Offline resize moves every shard's data into the NEW layout's dirs;
    an old-dir member booted afterwards would serve (and extend!) a
    stale copy of shards that now have different owners — a split-brain
    the riak_core ring epoch prevents in the reference.  Retired dirs
    refuse to boot until an operator consciously clears the stamp."""
    _set_dir_meta_key(directory, "retired_by_layout_epoch", int(by_epoch))


def stamp_layout_epoch(directory: str, epoch: int) -> None:
    """Record the membership-layout epoch a dir belongs to."""
    _set_dir_meta_key(directory, "layout_epoch", int(epoch))


def _validate_dir(cfg: AntidoteConfig, directory: str) -> None:
    """First boot stamps the deployment shape into the log directory;
    every later boot validates it.  Booting a WAL directory with a
    different shard count would silently strand or mis-route committed
    data, and a different max_dcs would mis-lane every recovered clock —
    the riak_core ring metadata persisted next to the data guards the
    reference against the same operator error (r1 advisor medium (a))."""
    meta = load_dir_meta(directory)
    if meta is not None:
        retired = meta.get("retired_by_layout_epoch")
        if retired is not None:
            raise LogDirMismatch(
                f"log dir {directory!r} was retired by membership-layout "
                f"epoch {retired} (its shards moved to the new layout's "
                "dirs at resize); booting it would serve and extend a "
                "stale pre-resize copy.  If this is intentional "
                "(restoring a backup), delete the "
                "'retired_by_layout_epoch' key from antidote_meta.json."
            )
        if (meta["n_shards"] != cfg.n_shards
                or meta["max_dcs"] != cfg.max_dcs):
            raise LogDirMismatch(
                f"log dir {directory!r} was created with n_shards="
                f"{meta['n_shards']}, max_dcs={meta['max_dcs']}; booting "
                f"with n_shards={cfg.n_shards}, max_dcs={cfg.max_dcs} "
                "would lose or corrupt committed data.  Use the recorded "
                "shape (or reshard via store.handoff.reshard into a new "
                "directory)."
            )
        return
    # legacy dir (pre-metadata build): shard files are created eagerly, so
    # their count IS the shape it was written with — any mismatch (shrink
    # OR grow) mis-routes recovered keys; a max_dcs mismatch is visible in
    # the clock width of any logged record
    shard_files = {
        int(m.group(1))
        for f in os.listdir(directory)
        if (m := re.fullmatch(r"shard_(\d+)\.wal", f))
    }
    if shard_files and shard_files != set(range(cfg.n_shards)):
        raise LogDirMismatch(
            f"legacy log dir {directory!r} holds shard files "
            f"{sorted(shard_files)} — written with n_shards="
            f"{len(shard_files)}, not {cfg.n_shards}"
        )
    for p in sorted(shard_files):
        for rec in replay(os.path.join(directory, f"shard_{p}.wal")):
            if len(rec["vc"]) != cfg.max_dcs:
                raise LogDirMismatch(
                    f"legacy log dir {directory!r} records carry "
                    f"{len(rec['vc'])}-lane clocks — written with "
                    f"max_dcs={len(rec['vc'])}, not {cfg.max_dcs}"
                )
            break  # one record per shard suffices
    # adopt: stamp the shape atomically (a crash mid-write must not leave
    # a truncated file that poisons every later boot)
    tmp = os.path.join(directory, _META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"n_shards": cfg.n_shards, "max_dcs": cfg.max_dcs,
                   "version": 1}, f)
        f.flush()
        os.fsync(f.fileno())  # fsync-ok: dir-meta atomic adopt (see above)
    os.replace(tmp, os.path.join(directory, _META_FILE))


def shard_segment_paths(directory: str, shard: int,
                        n_segments: int = 1) -> List[str]:
    """Every segment file a shard's records may live in: the configured
    segment set UNION whatever extra ``shard_P.sN.wal`` files exist on
    disk (including checkpoint-generation files ``shard_P.sN.gG.wal``) —
    a directory written with more segments (or a different generation)
    and opened with fewer must still replay everything."""
    paths = [os.path.join(directory, f"shard_{shard}.wal")] + [
        os.path.join(directory, f"shard_{shard}.s{i}.wal")
        for i in range(1, max(1, n_segments))
    ]
    extra = sorted(
        set(_glob.glob(os.path.join(directory, f"shard_{shard}.s*.wal")))
        - set(paths)
    )
    return paths + extra


def gen_segment_paths(directory: str, shard: int, n_segments: int,
                      gen: int) -> List[str]:
    """The ACTIVE segment file set of one shard at checkpoint generation
    ``gen``.  Generation 0 is the classic layout (``shard_P.wal`` +
    ``shard_P.sN.wal``); each checkpoint stamp rotates every shard onto a
    fresh generation's files (``shard_P.sN.gG.wal``), freezing the old
    ones so the post-publish reclaim can delete them wholesale once their
    records are covered by the image."""
    if gen == 0:
        return shard_segment_paths(directory, shard,
                                   n_segments)[:max(1, n_segments)]
    return [
        os.path.join(directory, f"shard_{shard}.s{i}.g{gen}.wal")
        for i in range(max(1, n_segments))
    ]


class SegmentedShardWAL:
    """One shard's WAL split over N parallel append segments (ISSUE 6).

    Segment 0 keeps the classic ``shard_P.wal`` path (a 1-segment log
    is byte-compatible with the pre-segmentation layout); segments 1..N
    live at ``shard_P.sN.wal``.  A commit group's records append to the
    CURRENT segment; the commit barrier rotates, so the group-fsync
    coordinator syncs one segment while the next group appends to its
    neighbor.  Records carry a per-shard append sequence (``"q"``,
    minted by LogManager) so recovery can merge segments back into
    exact commit order (:func:`~antidote_tpu.log.wal.replay_segments`)."""

    def __init__(self, directory: str, shard: int, n_segments: int = 1,
                 sync_on_commit: bool = False):
        self.shard = shard
        self.dir = directory
        self.n_segments = max(1, int(n_segments))
        self.segs = [
            ShardWAL(p, sync_on_commit=sync_on_commit)
            for p in shard_segment_paths(directory, shard,
                                         self.n_segments)[:self.n_segments]
        ]
        self._cur = 0

    def swap_generation(self, gen: int) -> List[ShardWAL]:
        """Rotate onto generation ``gen``'s fresh segment files (the
        checkpoint stamp's WAL barrier: all records appended so far stay
        in the now-frozen old files, every later record lands in the new
        ones).  Caller must hold the commit lock — no append may race
        the swap.  Returns the retired segments; the caller drains the
        fsync coordinator before closing them."""
        old = self.segs
        self.segs = [
            ShardWAL(p, sync_on_commit=self.sync_on_commit)
            for p in gen_segment_paths(self.dir, self.shard,
                                       self.n_segments, gen)
        ]
        self._cur = 0
        return old

    @property
    def current(self) -> ShardWAL:
        return self.segs[self._cur]

    @property
    def sync_on_commit(self) -> bool:
        return self.segs[0].sync_on_commit

    def rotate(self) -> None:
        if self.n_segments > 1:
            self._cur = (self._cur + 1) % self.n_segments

    # -- single-segment conveniences (tests, handoff) -------------------
    def append(self, record: dict) -> None:
        self.current.append(record)

    def tell(self) -> int:
        return self.current.tell()

    def rollback_to(self, off: int) -> None:
        self.current.rollback_to(off)

    def set_sync(self, sync: bool) -> None:
        for s in self.segs:
            s.set_sync(sync)

    def probe(self) -> None:
        """Probe EVERY segment file's volume (a per-file fault must keep
        the node read-only, not flap out via a healthy sibling)."""
        for s in self.segs:
            s.probe()

    def commit(self) -> None:
        for s in self.segs:
            s.commit()

    def close(self) -> None:
        for s in self.segs:
            s.close()


class LogManager:
    def __init__(self, cfg: AntidoteConfig, directory: str,
                 sync_on_commit: Optional[bool] = None,
                 segments: Optional[int] = None):
        self.cfg = cfg
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        _validate_dir(cfg, directory)
        sync = cfg.sync_log if sync_on_commit is None else sync_on_commit
        self.n_segments = max(1, int(
            getattr(cfg, "wal_segments", 1) if segments is None else segments
        ))
        self.wals = [
            SegmentedShardWAL(directory, p, self.n_segments,
                              sync_on_commit=sync)
            for p in range(cfg.n_shards)
        ]
        #: per-(shard, origin) monotone op-id chain
        self.op_ids = np.zeros((cfg.n_shards, cfg.max_dcs), np.int64)
        #: per-shard append sequence — total order across a shard's
        #: segments (stamped as ``"q"``; recovery merges by it)
        self.seqs = np.zeros(cfg.n_shards, np.int64)
        # --- checkpoint floors (ISSUE 8) -------------------------------
        #: per-shard append-sequence floor: records with q ≤ floor are
        #: covered by the loaded/published checkpoint image and are
        #: SKIPPED by every replay (they may or may not still exist on
        #: disk — reclaim deletes whole files once all their records are
        #: below the floor, so presence is never load-bearing)
        self.floor_seqs = np.zeros(cfg.n_shards, np.int64)
        #: per-(shard, origin) count of replication txn GROUPS below the
        #: floor — the base the inter-DC chain positions resume from
        #: (pub_opid for the own lane, last_seen for remote lanes); a
        #: catch-up below this base is below the compaction horizon
        self.chain_floor = np.zeros((cfg.n_shards, cfg.max_dcs), np.int64)
        #: active checkpoint generation: each checkpoint stamp rotates
        #: every shard onto generation-suffixed segment files so the old
        #: ones freeze and become deletable wholesale after publish
        self.gen = 0
        #: rotated-out segments awaiting the post-publish drain + close
        self._retired: List[ShardWAL] = []
        #: per-shard truncation epoch (durable in antidote_meta.json):
        #: bumped by truncate_shard so a checkpoint image written BEFORE
        #: a shard was relinquished can never resurrect it at recovery
        meta = load_dir_meta(directory) or {}
        self.shard_resets: Dict[int, int] = {
            int(k): int(v)
            for k, v in (meta.get("shard_resets") or {}).items()
        }
        #: blob handles already persisted per shard (avoid re-writing bytes)
        self._blob_seen = [set() for _ in range(cfg.n_shards)]
        #: group-fsync coordinator: commit barriers under sync_log=true
        #: submit their dirty segments and wait on the covering ticket
        self._fsync = GroupFsyncCoordinator(on_batch=self._fsync_batch)
        #: metrics hook — called with barriers-covered-per-fsync-pass
        #: (AntidoteNode points it at antidote_wal_fsync_batch.observe)
        self.on_fsync_batch = None

    def _fsync_batch(self, n: int) -> None:
        cb = self.on_fsync_batch
        if cb is not None:
            cb(n)

    def _mint_payload(self, shard: int, key, type_name: str, bucket: str,
                      eff_a, eff_b, commit_vc, origin: int,
                      blob_refs) -> Tuple[int, List[int], bytes]:
        """Mint the next op-id + append sequence and build the packed
        record payload.  MUTATES op_ids/seqs/_blob_seen — callers must
        snapshot those for rollback.  Returns (opid, new blob hashes,
        payload bytes)."""
        self.op_ids[shard, origin] += 1
        opid = int(self.op_ids[shard, origin])
        self.seqs[shard] += 1
        blobs = [
            (int(h), bytes(data))
            for h, data in blob_refs
            if h not in self._blob_seen[shard]
        ]
        new_hashes = [h for h, _ in blobs]
        for h in new_hashes:
            self._blob_seen[shard].add(h)
        payload = msgpack.packb({
            "k": key,
            "b": bucket,
            "t": type_name,
            "a": np.asarray(eff_a, np.int64).tobytes(),
            "eb": np.asarray(eff_b, np.int32).tobytes(),
            "vc": [int(x) for x in np.asarray(commit_vc)],
            "o": int(origin),
            "id": opid,
            "q": int(self.seqs[shard]),
            "bl": blobs,
        }, use_bin_type=True)
        return opid, new_hashes, payload

    def log_effect(self, shard: int, key, type_name: str, bucket: str,
                   eff_a: np.ndarray, eff_b: np.ndarray, commit_vc, origin: int,
                   blob_refs=()) -> int:
        """Append one effect record; returns its op-id in the
        (shard, origin) chain.  A failed append rolls the op-id chain,
        append sequence and blob-dedup memory back (the WAL itself heals
        its torn frame), so a refused write never leaves a permanent
        op-id GAP for egress to publish."""
        opid, new_hashes, payload = self._mint_payload(
            shard, key, type_name, bucket, eff_a, eff_b, commit_vc,
            origin, blob_refs)
        try:
            self.wals[shard].current.append_packed(pack_frames([payload]))
        except BaseException:
            self.op_ids[shard, origin] -= 1
            self.seqs[shard] -= 1
            for h in new_hashes:
                self._blob_seen[shard].discard(h)
            raise
        return opid

    def log_effects(self, entries) -> None:
        """Append one commit group's records, atomically with respect to
        FAILURE: an OSError on a later record (ENOSPC mid-group) rolls
        every touched WAL, op-id chain and blob-dedup entry back to the
        pre-group state.  Without this, a NACKed group left a durable
        prefix that recovery replay resurrected — writes the clients
        were told failed came back locally (and were never published
        inter-DC, so DCs diverged).

        The group's records reach each touched shard's current segment
        as ONE pre-framed buffer + ONE write (the measured per-append
        floor was ctypes/syscall round trips, not bytes).

        ``entries``: iterable of ``log_effect`` argument tuples
        ``(shard, key, type_name, bucket, eff_a, eff_b, commit_vc,
        origin, blob_refs)``."""
        op_snap = self.op_ids.copy()
        seq_snap = self.seqs.copy()
        added: List[Tuple[int, int]] = []  # (shard, blob hash) logged
        per_shard: Dict[int, List[bytes]] = {}
        try:
            for (shard, key, tname, bucket, ea, eb, vc, origin,
                 brefs) in entries:
                _, new_hashes, payload = self._mint_payload(
                    shard, key, tname, bucket, ea, eb, vc, origin, brefs)
                added.extend((shard, h) for h in new_hashes)
                per_shard.setdefault(shard, []).append(payload)
            offs: Dict[int, Tuple[ShardWAL, int]] = {}
            try:
                for shard, payloads in per_shard.items():
                    seg = self.wals[shard].current
                    offs[shard] = (seg, seg.tell())
                    seg.append_packed(pack_frames(payloads))
            except BaseException:
                for seg, off in offs.values():
                    try:
                        seg.rollback_to(off)
                    except OSError:
                        pass  # the disk is failing; replay's CRC guard
                        # still stops at whatever half-frame remains
                raise
        except BaseException:
            self.op_ids[:] = op_snap
            self.seqs[:] = seq_snap
            for s, h in added:
                self._blob_seen[s].discard(h)
            raise

    def log_effect_groups(self, groups: Sequence) -> List[Optional[Exception]]:
        """Log a MERGED commit batch — several independent sub-groups
        (one per source transaction/connection), each failure-atomic on
        its own (ISSUE 6 tentpole).  Fast path: the whole merged batch
        appends as one packed buffer per touched segment; if anything
        fails, everything rolls back and the sub-groups retry
        INDIVIDUALLY, so exactly the failing sub-group(s) are NACKed
        while siblings land durably.  Returns one ``None`` (logged) or
        ``Exception`` (NACKed, fully rolled back) per sub-group."""
        from antidote_tpu import faults as _faults

        groups = [list(g) for g in groups]
        # fast path: the whole merged batch as one packed buffer per
        # touched segment.  Skipped while a fault injector is armed —
        # a one-shot injected append fault must fire against exactly
        # one sub-group (deterministic chaos), not be consumed by the
        # merged attempt and then masked by the per-group redo below.
        if len(groups) > 1 and _faults.get_injector() is None:
            try:
                self.log_effects([e for g in groups for e in g])
                return [None] * len(groups)
            except Exception:
                pass  # fully rolled back; isolate the refusal per group
        errors: List[Optional[Exception]] = []
        for g in groups:
            try:
                self.log_effects(g)
            except Exception as e:
                errors.append(e)
            else:
                errors.append(None)
        return errors

    def set_sync(self, sync: bool) -> None:
        """Runtime fsync-on-commit toggle (logging_vnode:set_sync_log,
        /root/reference/src/logging_vnode.erl:256-258)."""
        for w in self.wals:
            w.set_sync(sync)

    def barrier_async(self, shards) -> FsyncTicket:
        """Deferred commit barrier: flush each touched shard's current
        segment, rotate it, and — under sync_log=true — submit the
        dirty segments to the group-fsync coordinator.  The returned
        ticket completes when the covering fsync does (immediately under
        sync_log=false); acks must not release before ``ticket.wait()``
        returns."""
        to_sync: List[ShardWAL] = []
        for p in set(int(s) for s in shards):
            w = self.wals[p]
            cur = w.current
            if cur.sync_on_commit and cur.pending_bytes:
                to_sync.append(cur)
            else:
                cur.commit()
            w.rotate()
        if not to_sync:
            return ready_ticket()
        return self._fsync.submit(to_sync)

    def commit_barrier(self, shards) -> None:
        """Blocking barrier (legacy callers: remote ingress, handoff,
        readiness probes).  Routed through the coordinator so a barrier
        racing a deferred one coalesces into the same fsync pass."""
        self.barrier_async(shards).wait()

    def segment_depths(self) -> List[int]:
        """Unsynced bytes per segment INDEX, aggregated across shards
        (the antidote_wal_segment_depth gauge)."""
        out = [0] * self.n_segments
        for w in self.wals:
            for i, s in enumerate(w.segs):
                out[i] += s.pending_bytes
        return out

    def probe_append(self) -> None:
        """Raise while ANY shard's WAL appends would still fail
        (degraded-mode recovery probe — see ShardWAL.probe).  Every
        shard (and every segment) is probed: a failure scoped to one
        file (bad block, per-file fault rule) must keep the node
        read-only, not flap it out on a healthy sibling's success."""
        for w in self.wals:
            w.probe()

    # ------------------------------------------------------------------
    # checkpoint floors & truncation (ISSUE 8)
    # ------------------------------------------------------------------
    def chain_base(self, shard: int, origin: int) -> int:
        """Replication txn groups below the compaction floor for one
        (shard, origin) chain — where opid/last_seen numbering resumes."""
        return int(self.chain_floor[shard, origin])

    def set_floor(self, floors, chain_floor) -> None:
        """Install a checkpoint's per-shard floors: every replay from now
        on skips records at or below them (they are covered by the
        image).  Caller holds the commit lock when the store is live."""
        self.floor_seqs = np.asarray(floors, np.int64).copy()
        self.chain_floor = np.asarray(chain_floor, np.int64).copy()
        # fresh appends must mint sequences above everything the image
        # covers even before any tail record is replayed
        np.maximum(self.seqs, self.floor_seqs, out=self.seqs)

    def rotate_generation(self) -> List[ShardWAL]:
        """Swap every shard onto a fresh segment-file generation (the
        checkpoint stamp's WAL barrier).  Caller must hold the commit
        lock.  The retired segments are queued for the post-publish
        drain+close in :meth:`reclaim_below`; returns them for tests."""
        self.gen += 1
        out: List[ShardWAL] = []
        for w in self.wals:
            out.extend(w.swap_generation(self.gen))
        self._retired.extend(out)
        return out

    def adopt_shard_resets(self, resets: Dict[int, int]) -> None:
        """Durably REPLACE the per-shard truncation epochs with another
        replica's (follower image bootstrap, ISSUE 9): the installed
        image carries the OWNER's reset epochs, and keeping the
        follower's own (bumped by its pre-bootstrap truncations) would
        make a later :func:`~antidote_tpu.log.checkpoint.install_image`
        of a LOCAL checkpoint drop every shard as stale.  Only valid
        right after the local image set was discarded — the epochs exist
        to fence exactly those images."""
        self.shard_resets = {int(k): int(v) for k, v in resets.items()}
        _set_dir_meta_key(self.dir, "shard_resets",
                          {str(k): v for k, v in self.shard_resets.items()})

    def set_chain_floor(self, shard: int, counts) -> None:
        """Install one shard's replication-group base counts (handoff
        from a compacted source: the package carries the source's chain
        floor so the importer's WAL-derived opid numbering continues the
        true chain instead of restarting at the tail count)."""
        self.chain_floor[shard] = np.maximum(
            self.chain_floor[shard], np.asarray(counts, np.int64))

    def drain_retired(self) -> None:
        """Drain the group-fsync coordinator and close rotated-out
        segment handles.  Runs after a publish (reclaim) AND after a
        FAILED checkpoint attempt — repeated failures must not
        accumulate open fds (sync on a closed segment is a no-op, so a
        straggler barrier that raced the rotation stays safe; the files
        themselves stay on disk until a published floor covers them)."""
        retired, self._retired = self._retired, []
        if not retired:
            return
        try:
            self._fsync.submit(list(retired)).wait()
        except Exception:
            pass  # frozen files owe no further durability here
        for s in retired:
            s.close()

    def reclaim_below(self, floors) -> int:
        """Delete WAL files wholly covered by a PUBLISHED checkpoint
        (every record's append sequence ≤ the shard's floor, verified by
        scan — the guarded truncation API; nothing in this package may
        raw-unlink a WAL file).  Active segments are never candidates.
        Returns bytes reclaimed.  Crash-safe at any point: deletion only
        removes records every replay already skips via the floor filter,
        so a SIGKILL mid-reclaim leaves a byte-identical recovery."""
        from antidote_tpu import faults as _faults

        floors = np.asarray(floors, np.int64)
        self.drain_retired()
        reclaimed = 0
        for shard in range(self.cfg.n_shards):
            floor = int(floors[shard])
            if floor <= 0:
                continue
            active = set(gen_segment_paths(self.dir, shard,
                                           self.n_segments, self.gen))
            for path in shard_segment_paths(self.dir, shard,
                                            self.n_segments):
                if path in active or not os.path.exists(path):
                    continue
                d = _faults.hit("wal.truncate_below",
                                key=os.path.basename(path))
                if d is not None:
                    if d.action == "delay" and d.arg:
                        time.sleep(float(d.arg))
                    elif d.action in ("error", "io_error", "enospc"):
                        raise IOError(
                            f"injected fault: wal.truncate_below {path}")
                if not wholly_below(path, floor):
                    continue  # still carries post-floor records
                size = os.path.getsize(path)
                os.remove(path)  # reclaim-ok: guarded — scan proved every
                # record ≤ the published checkpoint floor
                reclaimed += size
        return reclaimed

    def truncate_shard(self, shard: int) -> None:
        """Discard one shard's log — ALL its segments, including frozen
        checkpoint generations (post-handoff cleanup: the records now
        live in the receiver's chain).  Resets the shard's op-id chains,
        append sequence, compaction floors and blob-dedup memory along
        with the files, and durably bumps the shard's truncation epoch
        so a checkpoint image written before this call can never
        resurrect the relinquished shard at recovery."""
        sync = self.wals[shard].sync_on_commit
        self.wals[shard].close()
        # retired (previous-generation) segments of THIS shard lose their
        # files below; close them now and forget them
        prefix = os.path.join(self.dir, f"shard_{shard}.")
        for s in [s for s in self._retired if s.path.startswith(prefix)]:
            s.close()
            self._retired.remove(s)
        for path in shard_segment_paths(self.dir, shard, self.n_segments):
            if os.path.exists(path):
                os.remove(path)  # reclaim-ok: whole-shard handoff drop —
                # the records live on at the new owner
        self.wals[shard] = SegmentedShardWAL(
            self.dir, shard, self.n_segments, sync_on_commit=sync
        )
        if self.gen:
            for s in self.wals[shard].swap_generation(self.gen):
                s.close()
        self.op_ids[shard] = 0
        self.seqs[shard] = 0
        self.floor_seqs[shard] = 0
        self.chain_floor[shard] = 0
        self._blob_seen[shard].clear()
        self.shard_resets[shard] = self.shard_resets.get(shard, 0) + 1
        _set_dir_meta_key(self.dir, "shard_resets",
                          {str(k): v for k, v in self.shard_resets.items()})

    def replay_shard(self, shard: int,
                     floor: Optional[int] = None) -> Iterator[dict]:
        """Replay one shard's records in exact append order, merged
        across its segments by the ``"q"`` sequence.  Records at or
        below the shard's checkpoint floor are SKIPPED — they are
        covered by the checkpoint image (whether their file was already
        reclaimed or not), so recovery is load-image + this tail.
        Legacy records (no ``"q"``) predate any checkpoint and are
        skipped whenever a floor is set.  ``floor`` overrides the live
        one — callers that pair it with :meth:`chain_base` (catch-up
        serving on fabric threads) snapshot both under the commit lock
        so a concurrent publish can't split them.  Side effect: the
        shard's append-sequence counter resumes past every replayed
        record, so a recovered node's fresh appends never reuse a
        sequence (recovery always replays every shard)."""
        if floor is None:
            floor = int(self.floor_seqs[shard])
        for rec in replay_segments(
                shard_segment_paths(self.dir, shard, self.n_segments)):
            q = rec.get("q")
            if q is not None and q > self.seqs[shard]:
                self.seqs[shard] = int(q)
            if floor and (q is None or int(q) <= floor):
                continue
            yield rec

    def replay_key(self, shard: int, key, bucket: str) -> List[dict]:
        """Scan one shard's log for a key's ops (the reference's whole-log
        scan + filter, /root/reference/src/logging_vnode.erl:663-702)."""
        from antidote_tpu.store.kv import freeze_key

        return [
            r for r in self.replay_shard(shard)
            if freeze_key(r["k"]) == key and r["b"] == bucket
        ]

    def close(self) -> None:
        self._fsync.close()
        for s in self._retired:
            s.close()
        self._retired = []
        for w in self.wals:
            w.close()
