"""Checkpointed fast restart: VC-stamped epoch snapshots of the whole
store, WAL tail truncation, crash-safe compaction (ISSUE 8).

The reference treats "the op log IS the checkpoint" (``recover_from_log``)
and bounds it only by pruning ops below the min cached snapshot
(``prune_ops``, SURVEY §2.3).  This module lifts that idea to the store
level: a background checkpointer streams an atomically-published image of
the store — per-table frozen heads (the same immutable buffers the
serving-epoch plane gathers from), slot-tier metadata, the directory,
blob payloads, op-id chains, certification stamps, commit counters, and
the inter-DC chain positions — stamped with the applied vector clock and
each shard's WAL append sequence ``q`` (the *floor*).  Recovery becomes
load-image + heap-merge replay of only the WAL tail above the floor, and
WAL files wholly below the floor are reclaimed through a guarded API
(:meth:`~antidote_tpu.log.LogManager.reclaim_below` — never a raw
unlink), which is what bounds WAL growth under a sustained write storm.

Crash safety contract: a SIGKILL at ANY point — mid-stream, mid-rename,
mid-truncation — recovers byte-identical to a never-checkpointed replay.
The mechanics:

  * the stamp is captured under the commit lock (a short barrier: device
    head copies are *dispatched* there, materialized outside), so the
    image is a consistent cut: every WAL record with ``q ≤ floor`` is in
    the image, every record above it is not;
  * the image is written to a temp dir, fsynced THROUGH the group-fsync
    coordinator (checkpointing never adds a second fsync stream to the
    commit path), and published by one atomic directory rename;
  * replay always skips records at or below the installed floor, so
    whether a below-floor file was already deleted, half-deleted, or
    still present changes nothing;
  * reclaim runs only after publish, deletes only whole files whose
    every record a scan proves ≤ floor, and a checkpoint failure
    (ENOSPC mid-image) aborts BEFORE the floor moves — nothing is
    truncated and the store never flips read-only because of it.

Fault sites (chaos suite): ``ckpt.write``, ``ckpt.fsync``,
``ckpt.rename`` here, ``wal.truncate_below`` in the reclaim API.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from antidote_tpu import faults
from antidote_tpu.log.wal import replay_segments

log = logging.getLogger(__name__)

#: subdirectory of the log dir holding published images
CKPT_DIR = "checkpoints"
#: published checkpoint directory name
_CKPT_RE = re.compile(r"ckpt_(\d+)$")
#: image stream chunk (each chunk consults the ckpt.write fault site, so
#: chaos delays can hold the writer mid-stream)
_CHUNK = 8 << 20

_IMAGE = "image.bin"
_MANIFEST = "manifest.json"


class CheckpointError(RuntimeError):
    """A checkpoint attempt failed (nothing was published or truncated;
    the store's durability state is untouched)."""


def checkpoint_root(log_dir: str) -> str:
    return os.path.join(log_dir, CKPT_DIR)


def has_checkpoints(log_dir: str) -> bool:
    """True when the directory holds at least one published checkpoint —
    such a dir carries committed data even if every WAL file was
    reclaimed, so boot paths must demand ``recover=True`` for it."""
    return bool(list_checkpoints(checkpoint_root(log_dir)))


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """Published (id, path) pairs, oldest first.  A directory without a
    readable manifest is not published (a crash mid-write leaves only
    ``tmp.*`` dirs, which never match)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _CKPT_RE.fullmatch(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if os.path.exists(os.path.join(path, _MANIFEST)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_manifest(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def manifest_kind(manifest: dict) -> str:
    """"full" (whole-store image, possibly with a cold sidecar) or
    "delta" (parent-linked incremental link).  Pre-chain manifests carry
    no kind and are full images."""
    return str(manifest.get("kind", "full"))


def _load_verified(path: str, manifest: dict) -> Optional[dict]:
    """Read + CRC-verify + decode one published image/link, or None."""
    from antidote_tpu.store.handoff import unpack

    try:
        with open(os.path.join(path, _IMAGE), "rb") as f:
            data = f.read()
    except OSError:
        return None
    if (len(data) != int(manifest.get("image_bytes", -1))
            or (zlib.crc32(data) & 0xFFFFFFFF)
            != int(manifest.get("image_crc32", -1))):
        return None
    try:
        return unpack(data)
    except Exception:
        return None


def load_latest(log_dir: str) -> Optional[Tuple[dict, dict]]:
    """Newest FULL checkpoint whose image verifies (size + CRC against
    its manifest), or None.  A corrupt newest image falls back to the
    next older one — the retention window is the recovery safety margin.
    Delta links are skipped here; :func:`load_chain` composes them."""
    for id_, path in reversed(list_checkpoints(checkpoint_root(log_dir))):
        manifest = load_manifest(path)
        if manifest is None or manifest_kind(manifest) != "full":
            continue
        image = _load_verified(path, manifest)
        if image is None:
            log.warning("checkpoint %s fails verification; falling back "
                        "to an older image", path)
            continue
        return image, manifest
    return None


def load_chain(log_dir: str) -> Optional[Tuple[dict, dict, List[Tuple[dict, dict]]]]:
    """The recovery composition (ISSUE 13 incremental chains): the
    newest verifiable FULL image plus every parent-linked, CRC-verified
    delta link published after it, in apply order.  The chain STOPS at
    the first missing / corrupt / mis-linked delta — recovery then falls
    back to the last good prefix + a longer WAL tail (reclaim never
    deletes records above the retained full images' floors, so the tail
    is always still on disk).  Returns (image, manifest, deltas) or
    None when nothing full is published."""
    base = load_latest(log_dir)
    if base is None:
        return None
    image, manifest = base
    deltas: List[Tuple[dict, dict]] = []
    prev_id = int(manifest["id"])
    for id_, path in list_checkpoints(checkpoint_root(log_dir)):
        if id_ <= prev_id:
            continue
        man = load_manifest(path)
        if man is None or manifest_kind(man) != "delta":
            continue
        if int(man.get("parent", -1)) != (int(deltas[-1][1]["id"])
                                          if deltas else prev_id):
            log.warning(
                "checkpoint chain broken at link %d (parent %s does not "
                "match the chain head); recovering from the prefix + a "
                "longer WAL tail", id_, man.get("parent"))
            break
        delta = _load_verified(path, man)
        if delta is None:
            log.warning(
                "checkpoint chain link %d fails verification (bit rot / "
                "torn write); recovering from the prefix + a longer WAL "
                "tail", id_)
            break
        deltas.append((delta, man))
    return image, manifest, deltas


def latest_image_meta(log_dir: str,
                      before_id: Optional[int] = None) -> Optional[dict]:
    """Shippable metadata of the newest published checkpoint image —
    what the owner answers a follower's ``ckpt_meta`` request with:
    ``{id, image_bytes, image_crc32, stamp_vc_max, created_at}``.
    Served straight from the manifest (never decodes the image).
    ``before_id`` restricts to strictly older images — a follower whose
    fetch of the newest image failed verification (bit rot) falls back
    through the retention window exactly like owner-side recovery."""
    cks = list_checkpoints(checkpoint_root(log_dir))
    for _id, path in reversed(cks):
        if before_id is not None and _id >= int(before_id):
            continue
        manifest = load_manifest(path)
        if manifest is None or manifest_kind(manifest) != "full":
            continue  # delta links are not shippable on their own
        out = {
            "id": int(manifest["id"]),
            "image_bytes": int(manifest["image_bytes"]),
            "image_crc32": int(manifest["image_crc32"]),
            "stamp_vc_max": manifest.get("stamp_vc_max"),
            "created_at": manifest.get("created_at"),
        }
        cold = manifest.get("cold")
        if cold is not None:
            # a beyond-RAM owner: the follower must fetch the sidecar
            # too — but only when the image actually has cold keys (the
            # sidecar also exists, image-sized, on a budget-armed owner
            # with everything resident; shipping it then would double
            # the bootstrap transfer for nothing)
            out["cold_keys"] = int(manifest.get("cold_keys", 0))
            out["cold_bytes"] = int(cold["bytes"])
            out["cold_crc32"] = int(cold["crc32"])
            out["cold_manifest"] = cold
        return out
    return None


def image_path(log_dir: str, ckpt_id: int) -> str:
    """Path of a published image file by id (ckpt_fetch serving)."""
    return os.path.join(checkpoint_root(log_dir), f"ckpt_{int(ckpt_id)}",
                        _IMAGE)


def cold_path(log_dir: str, ckpt_id: int) -> str:
    """Path of a published cold sidecar by id (ckpt_fetch file="cold")."""
    from antidote_tpu.store.coldtier import COLD_BIN

    return os.path.join(checkpoint_root(log_dir), f"ckpt_{int(ckpt_id)}",
                        COLD_BIN)


def discard_all(log_dir: str) -> int:
    """Delete EVERY published checkpoint image under a log dir — the
    diverged-follower repair path: a follower re-bootstrapping from the
    owner's image must not let its own (possibly corrupt-derived) local
    images resurrect at the next restart.  Owned by this module so the
    deletion stays inside the guarded log/ lifecycle.  Returns the
    number of images discarded."""
    root = checkpoint_root(log_dir)
    cks = list_checkpoints(root)
    for _id, path in cks:
        shutil.rmtree(path, ignore_errors=True)  # reclaim-ok: explicit
        # whole-image discard before a follower re-bootstrap re-seeds
        # the store from the owner's image
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.startswith("tmp."):
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)  # reclaim-ok: orphaned
                # temp dir of a crashed writer
    return len(cks)


# ---------------------------------------------------------------------------
# image install (recovery side)
# ---------------------------------------------------------------------------
def install_image(store, txm, image: dict, shards=None) -> dict:
    """Install a checkpoint image into a FRESH store/txn-manager pair
    (the recovery fast path's first phase; the caller replays the WAL
    tail afterwards — :meth:`LogManager.replay_shard` already skips
    everything the installed floor covers).

    Shards whose durable truncation epoch (``antidote_meta.json``
    ``shard_resets``, bumped by every ``truncate_shard``) advanced past
    the image's are DROPPED: a shard relinquished to another owner after
    the checkpoint was written must not resurrect here.  Returns a
    summary dict (keys, tables, dropped shards).

    ``shards`` (optional) RESTRICTS the install to that shard set and
    MERGES onto whatever the store already holds instead of replacing it
    — the per-member composition primitive of the follower fleet tier
    (ISSUE 11): a follower of a clustered owner installs each member's
    image restricted to the shards that member owns, so one composed
    store covers the whole DC.  Un-restricted installs keep the exact
    whole-store replace semantics recovery depends on.
    """
    from antidote_tpu.store.kv import freeze_key

    import jax.numpy as jnp

    logm = store.log
    assert logm is not None, "checkpoint install needs the durable log"
    cfg = store.cfg
    if (int(image["n_shards"]) != cfg.n_shards
            or int(image["max_dcs"]) != cfg.max_dcs):
        raise CheckpointError(
            f"checkpoint image shape (n_shards={image['n_shards']}, "
            f"max_dcs={image['max_dcs']}) does not match the deployment "
            f"({cfg.n_shards}, {cfg.max_dcs})"
        )
    image_resets = {int(k): int(v)
                    for k, v in (image.get("shard_resets") or {}).items()}
    stale = sorted(
        s for s in range(cfg.n_shards)
        if logm.shard_resets.get(s, 0) > image_resets.get(s, 0)
    )
    stale_set = set(stale)
    if stale:
        log.warning("checkpoint image predates truncation of shard(s) %s "
                    "(moved/relinquished after the stamp); dropping them "
                    "from the restore", stale)
    #: restricted-merge mode: the shard rows this install may touch
    #: (sorted list), or None for the whole-store replace
    rlist = None
    if shards is not None:
        rlist = sorted(set(int(s) for s in shards) - stale_set)
    floors = np.asarray(image["floor_seqs"], np.int64).copy()
    chains = np.asarray(image["chain_floor"], np.int64).copy()
    op_ids = np.asarray(image["op_ids"], np.int64).copy()
    stamp = np.asarray(image["stamp_vc"], np.int32).copy()
    for s in stale:
        floors[s] = 0
        chains[s] = 0
        op_ids[s] = 0
        stamp[s] = 0
    n_rows_installed = 0
    for tname, tb in image["tables"].items():
        t = store.table(tname)
        used = np.asarray(tb["used_rows"], np.int64).copy()
        for s in stale:
            used[s] = 0
        head_vc = np.asarray(tb["head_vc"], np.int32).copy()
        u_cap = head_vc.shape[1]
        head = {f: np.asarray(x).copy() for f, x in tb["head"].items()}
        slots_ub = np.asarray(tb["slots_ub"], np.int32).copy()
        for s in stale:
            head_vc[s] = 0
            slots_ub[s] = 0
            for f in head:
                head[f][s] = 0
        while u_cap > t.n_rows:
            t._grow()

        # assemble full-extent arrays HOST-side and ship each in one
        # transfer: the store is fresh (all-zero tables), so building
        # zeros + one slice assign + one copying transfer replaces an
        # eager .at[].set dispatch PER ARRAY (each of which copies the
        # whole destination — the measured majority of install time at
        # 1M).  copy=True matters: jnp.asarray may ZERO-COPY alias the
        # host buffer on CPU, and a later donating kernel (the append
        # head fold) would then recycle memory the table still reads —
        # observed as pointer garbage in element lanes under the
        # persistent compile cache.
        def place(host_arr):
            out = jnp.array(host_arr, copy=True)
            if t.sharding is not None:
                import jax

                out = jax.device_put(out, t.sharding)
            return out

        def full(dst, src, snap_slot=False):
            if rlist is None:
                arr = np.zeros(dst.shape, np.dtype(dst.dtype))
                if snap_slot:
                    arr[:, :u_cap, 0] = src
                else:
                    arr[:, :u_cap] = src
            else:
                # restricted merge: keep the destination's other shards
                # (a previous member's installed rows) byte-intact
                arr = np.array(dst, dtype=np.dtype(dst.dtype))
                if snap_slot:
                    arr[rlist, :u_cap, 0] = src[rlist]
                else:
                    arr[rlist, :u_cap] = src[rlist]
            return place(arr)

        for f in t.head:
            t.head[f] = full(t.head[f], head[f])
            # seed ONE snapshot version from the restored head: versioned
            # reads at clocks ≥ a row's head_vc fold the (empty) ring on
            # this base exactly; reads below it come out "incomplete" and
            # surface the compaction horizon instead of silently missing
            # the pre-checkpoint ops the WAL no longer holds
            t.snap[f] = full(t.snap[f], head[f], snap_slot=True)
        t.head_vc = full(t.head_vc, head_vc)
        t.snap_vc = full(t.snap_vc, head_vc, snap_slot=True)
        seq_col = (np.arange(u_cap)[None, :]
                   < used[:, None]).astype(np.int64)
        t.snap_seq = full(t.snap_seq, seq_col, snap_slot=True)
        t.next_seq = 2
        if rlist is None:
            t.used_rows[:] = used
            t.slots_ub[:, :u_cap] = slots_ub
            t.max_abs_delta = int(tb["max_abs_delta"])
        else:
            t.used_rows[rlist] = used[rlist]
            t.slots_ub[rlist, :u_cap] = slots_ub[rlist]
            t.max_abs_delta = max(t.max_abs_delta,
                                  int(tb["max_abs_delta"]))
        if stale or rlist is not None:
            # a dropped shard may have held the table-wide max commit VC;
            # an inflated cap would let a serving epoch claim coverage of
            # commits that never restored — recompute from survivors
            # (restricted merges fold the installed rows into whatever
            # cap earlier members established)
            hv = head_vc if rlist is None else head_vc[rlist]
            mcv = hv.reshape(-1, head_vc.shape[-1]).max(axis=0) \
                if hv.size else np.zeros(cfg.max_dcs, np.int32)
            if rlist is not None:
                mcv = np.maximum(mcv, np.asarray(t.max_commit_vc,
                                                 np.int32))
            t.max_commit_vc = mcv.astype(np.int32)
        else:
            t.max_commit_vc = np.asarray(tb["max_commit_vc"],
                                         np.int32).copy()
        n_rows_installed += int(used.sum() if rlist is None
                                else used[rlist].sum())
    directory = image["directory"]
    if rlist is not None:
        keep = set(rlist)
        directory = [e for e in directory if int(e[3]) in keep]
    elif stale_set:
        directory = [e for e in directory if int(e[3]) not in stale_set]
    n_keys = len(directory)
    if directory:
        # columnar zip build: C-speed tuple pairing for the (vastly
        # common) scalar-key case; only list keys (composite map keys,
        # tuple keys through msgpack) pay a freeze pass
        keys, buckets, tnames, shards, rows = zip(*directory)
        if any(type(k) is list for k in keys):
            keys = tuple(freeze_key(k) for k in keys)
        store.directory.update(
            zip(zip(keys, buckets), zip(tnames, shards, rows)))
    for h, data in image.get("blobs", []):
        store.blobs.intern_bytes(int(h), bytes(data))
    for s, hashes in enumerate(image.get("blob_seen", [])):
        if s < cfg.n_shards and s not in stale_set \
                and (rlist is None or s in set(rlist)):
            logm._blob_seen[s] = {int(h) for h in hashes}
    if rlist is None:
        np.maximum(store.applied_vc, stamp, out=store.applied_vc)
        np.maximum(logm.op_ids, op_ids, out=logm.op_ids)
        logm.set_floor(floors, chains)
    else:
        # merge only the restricted rows — other members' floors/clocks
        # must survive this install untouched
        store.applied_vc[rlist] = np.maximum(store.applied_vc[rlist],
                                             stamp[rlist])
        logm.op_ids[rlist] = np.maximum(logm.op_ids[rlist],
                                        op_ids[rlist])
        fl = logm.floor_seqs.copy()
        ch = logm.chain_floor.copy()
        fl[rlist] = floors[rlist]
        ch[rlist] = chains[rlist]
        logm.set_floor(fl, ch)
    # cold keys (ISSUE 13): the image's cold_directory names keys whose
    # state lives ONLY in the cold sidecar — they get NO device row here
    # (that is the whole point: recovery of a beyond-RAM store installs
    # the bounded resident set); the caller registers them with its
    # ColdTier so reads fault them in on demand
    cold_entries = []
    for ent in image.get("cold_directory", []) or []:
        s = int(ent[3])
        if s in stale_set:
            continue
        if rlist is not None and s not in set(rlist):
            continue
        cold_entries.append(ent)
    committed = image.get("committed_keys", [])
    if committed and not stale_set and rlist is None \
            and not txm.committed_keys:
        # fresh manager, nothing dropped: bulk build (the per-entry
        # max/membership checks below cost ~1 s per million stamps)
        ck, cb, cv = zip(*committed)
        if any(type(k) is list for k in ck):
            ck = tuple(freeze_key(k) for k in ck)
        txm.committed_keys.update(zip(zip(ck, cb), cv))
    else:
        for key, bucket, counter in committed:
            dk = (freeze_key(key), bucket)
            if dk in store.directory:
                txm.committed_keys[dk] = max(
                    txm.committed_keys.get(dk, 0), int(counter)
                )
    return {
        "id": int(image["id"]),
        "keys": n_keys,
        "rows": n_rows_installed,
        "tables": len(image["tables"]),
        "dropped_shards": stale,
        "restricted_to": rlist,
        "cold_directory": cold_entries,
    }


def install_delta(store, txm, delta: dict) -> dict:
    """Overlay one incremental chain link onto an already-installed
    parent state (recovery composition, ISSUE 13): scatter the link's
    dirty rows' heads into the tables (seeding one snapshot version per
    row, exactly like :func:`install_image`), apply the directory /
    certification / blob deltas, re-register keys the link records as
    EVICTED, and advance floors, op-id chains and clocks to the link's
    stamp.  Returns a summary dict."""
    from antidote_tpu.store.kv import freeze_key

    logm = store.log
    assert logm is not None, "delta install needs the durable log"
    cfg = store.cfg
    if (int(delta["n_shards"]) != cfg.n_shards
            or int(delta["max_dcs"]) != cfg.max_dcs):
        raise CheckpointError(
            f"chain link shape (n_shards={delta['n_shards']}) does not "
            f"match the deployment ({cfg.n_shards})")
    delta_resets = {int(k): int(v)
                    for k, v in (delta.get("shard_resets") or {}).items()}
    stale = {
        s for s in range(cfg.n_shards)
        if logm.shard_resets.get(s, 0) > delta_resets.get(s, 0)
    }
    # evictions FIRST: the rows this link records as evicted were freed
    # and may be REUSED by the link's own row overlays below — clearing
    # them after the overlay would wipe the new tenants' state
    evicted = [e for e in delta.get("cold_delta", [])
               if int(e[3]) not in stale]
    if evicted and store.cold is None:
        # the chain recorded evictions but this boot has no cold tier
        # (restarted without --resident-rows): attach one anyway —
        # dropping the keys' directory entries without registering their
        # sidecar refs would turn their reads into silent bottoms
        from antidote_tpu.store.coldtier import ColdTier

        store.cold = ColdTier(store, budget=0,
                              lock=getattr(txm, "commit_lock", None))
    for key, bucket, tname, shard, _srow in evicted:
        dk = (freeze_key(key), bucket)
        ent = store.directory.get(dk)
        if ent is not None:
            t = store.table(ent[0])
            t.evict_rows(np.asarray([ent[1]]),  # evict-ok: composing a
                         np.asarray([ent[2]]))  # recorded cold-tier
            # eviction from the chain link — the sidecar coords ride in
            # the same entry and are re-registered just below
            store.directory.pop(dk, None)
    if store.cold is not None and evicted:
        src = delta.get("cold_src")
        store.cold.seed([[e[0], e[1], e[2], e[3], e[4]] for e in evicted],
                        src if src is not None else delta.get("parent"))
    n_rows = 0
    for tname, tb in delta["tables"].items():
        t = store.table(tname)
        pairs = [(int(s), int(r)) for s, r in tb["rows"]
                 if int(s) not in stale]
        if not pairs:
            continue
        keep = np.asarray([int(s) not in stale
                           for s, _ in tb["rows"]], bool)
        ss = np.asarray([p[0] for p in pairs], np.int64)
        rr = np.asarray([p[1] for p in pairs], np.int64)
        while int(rr.max()) >= t.n_rows:
            t._grow()
        head_rows = {f: np.asarray(x)[keep] for f, x in tb["head"].items()}
        hvc_rows = np.asarray(tb["head_vc"], np.int32)[keep]
        t.install_rows(ss, rr, head_rows, hvc_rows)
        # overlaid rows are OCCUPIED now: pull them off the free lists
        # the eviction pass above may have pushed them onto (a later
        # alloc_row handing one out again would double-bind the row)
        occupied: Dict[int, set] = {}
        for s, r in pairs:
            occupied.setdefault(s, set()).add(r)
        for s, rows_set in occupied.items():
            free = t.free_rows.get(s)
            if free:
                t.free_rows[s] = [r for r in free if r not in rows_set]
        t.slots_ub[ss, rr] = np.asarray(tb["slots_ub"], np.int32)[keep]
        used = np.asarray(tb["used_rows"], np.int64)
        for s in stale:
            used[s] = 0
        np.maximum(t.used_rows, used, out=t.used_rows)
        t.max_abs_delta = max(t.max_abs_delta, int(tb["max_abs_delta"]))
        np.maximum(t.max_commit_vc,
                   np.asarray(tb["max_commit_vc"], np.int32),
                   out=t.max_commit_vc)
        n_rows += len(pairs)
    for key, bucket, tname, shard, row in delta.get("directory_delta", []):
        if int(shard) in stale:
            continue
        dk = (freeze_key(key), bucket)
        store.directory[dk] = (tname, int(shard), int(row))
        if store.cold is not None and store.cold.is_cold(dk):
            # the link proves the key resident at its stamp: undo the
            # cold registration an earlier full install seeded
            store.cold.cold_set.discard(dk)
            s = store.cold.by_shard.get(int(shard))
            if s is not None:
                s.discard(dk)
    for key, bucket, counter in delta.get("committed_delta", []):
        dk = (freeze_key(key), bucket)
        txm.committed_keys[dk] = max(txm.committed_keys.get(dk, 0),
                                     int(counter))
    for h, data in delta.get("blobs_delta", []):
        store.blobs.intern_bytes(int(h), bytes(data))
    for s, hashes in enumerate(delta.get("blob_seen", [])):
        if s < cfg.n_shards and s not in stale:
            logm._blob_seen[s] = {int(h) for h in hashes}
    floors = np.asarray(delta["floor_seqs"], np.int64).copy()
    chains = np.asarray(delta["chain_floor"], np.int64).copy()
    stamp = np.asarray(delta["stamp_vc"], np.int32).copy()
    op_ids = np.asarray(delta["op_ids"], np.int64).copy()
    for s in stale:
        floors[s] = logm.floor_seqs[s]
        chains[s] = logm.chain_floor[s]
        stamp[s] = 0
        op_ids[s] = 0
    np.maximum(store.applied_vc, stamp, out=store.applied_vc)
    np.maximum(logm.op_ids, op_ids, out=logm.op_ids)
    logm.set_floor(floors, chains)
    return {
        "id": int(delta["id"]),
        "parent": int(delta["parent"]),
        "rows": n_rows,
        "keys": len(delta.get("directory_delta", [])),
        "evicted": len(evicted),
        "dropped_shards": sorted(stale),
    }


# ---------------------------------------------------------------------------
# checkpoint writer
# ---------------------------------------------------------------------------
class _ImageFsync:
    """Adapter letting the checkpoint image ride the WAL's group-fsync
    coordinator (one fsync stream for the whole process; a checkpoint
    fsync coalesces with commit-barrier fsyncs instead of competing)."""

    def __init__(self, fileno: int, name: str):
        self._fileno = fileno
        self._name = name

    def sync(self) -> None:
        d = faults.hit("ckpt.fsync", key=self._name)
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action in ("error", "io_error", "enospc"):
                err = errno.ENOSPC if d.action == "enospc" else errno.EIO
                raise OSError(err, f"injected fault: ckpt.fsync {self._name}")
        os.fsync(self._fileno)  # fsync-ok: checkpoint image durability —
        # routed through the group-fsync coordinator (see submit site)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)  # fsync-ok: directory entry durability for the
        # atomic checkpoint publish (rename is only durable with it)
    finally:
        os.close(fd)


def _faulted_write(f, data: bytes, name: str) -> None:
    """Stream ``data`` in chunks, consulting the ``ckpt.write`` fault
    site per chunk (delay rules hold the writer mid-stream so chaos can
    SIGKILL inside the window; enospc/io_error abort the attempt)."""
    view = memoryview(data)
    for off in range(0, max(len(view), 1), _CHUNK):
        d = faults.hit("ckpt.write", key=name)
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action == "enospc":
                raise OSError(errno.ENOSPC,
                              f"injected fault: ckpt.write {name}")
            elif d.action in ("error", "io_error"):
                raise OSError(errno.EIO,
                              f"injected fault: ckpt.write {name}")
        f.write(view[off:off + _CHUNK])


class Checkpointer:
    """Background checkpoint writer for one node.

    ``checkpoint_now`` runs one full cycle synchronously: stamp (short
    commit-lock barrier), stream + atomic publish, floor install,
    retention, WAL reclaim.  ``start`` runs it on ``interval_s`` in a
    daemon thread (``request`` nudges an immediate run).  Failures never
    flip the store read-only and never truncate anything — they raise
    :class:`CheckpointError` (or are logged by the loop) and the next
    interval retries.
    """

    def __init__(self, store, txm, metrics=None, interval_s: float = 300.0,
                 retain: int = 2, rebase_every: int = 8,
                 scrub_every_s: float = 0.0):
        assert store.log is not None, "checkpointing needs a durable log"
        self.store = store
        self.txm = txm
        self.log = store.log
        self.metrics = metrics
        self.interval_s = float(interval_s)
        #: FULL images retained (delta links between them ride along;
        #: links below the newest full are swept — the rebase covers them)
        self.retain = max(1, int(retain))
        #: delta links between full rebases: a stamp writes only the
        #: rows/keys dirtied since its parent (cost ∝ dirty set), and
        #: every ``rebase_every``-th stamp is a full image that re-bounds
        #: both the chain length and the reclaimable WAL.  0/1 = always
        #: full (the pre-chain behavior).
        self.rebase_every = max(0, int(rebase_every))
        #: background bit-rot scrub cadence (0 = disabled): CRC-verify
        #: retained images + links off the commit lock; a failed scrub
        #: retires a delta link and forces a rebase
        self.scrub_every_s = float(scrub_every_s)
        #: bytes/second ceiling for scrub reads (never starve the WAL)
        self.scrub_bps = 64 << 20
        #: the next stamp must be a FULL rebase (set by a failed stamp —
        #: the consumed dirty windows are unrecoverable — by a corrupt
        #: fault-in/scrub, and by cold-tier pressure)
        self.force_rebase = False
        #: delta links since the last full image (chain length)
        self.chain_len = 0
        self.scrub_counts = {"ok": 0, "corrupt": 0}
        self._last_scrub = 0.0
        self.root = checkpoint_root(self.log.dir)
        #: name -> callable returning a msgpack-able blob captured under
        #: the commit lock (cluster membership, embedder state, ...)
        self.extras_providers: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        #: True while a generation rotation from a FAILED attempt is
        #: still unpublished: the retry reuses it instead of rotating
        #: again, so a persistent-ENOSPC outage can't accumulate open
        #: segment handles/files cycle after cycle
        self._rotated_unpublished = False
        #: running total of WAL bytes reclaimed (node-status block)
        self.reclaimed_total = 0
        #: summary of the last published checkpoint (seeded from disk so
        #: a recovered node's status shows its inherited image)
        self.last: Optional[dict] = None
        self._next_id = 1
        cks = list_checkpoints(self.root)
        if cks:
            self._next_id = cks[-1][0] + 1
            self.last = load_manifest(cks[-1][1])
            # resume the chain position: links since the newest full
            self.chain_len = 0
            for _id, path in cks:
                m = load_manifest(path)
                if m is None:
                    continue
                if manifest_kind(m) == "full":
                    self.chain_len = 0
                else:
                    self.chain_len += 1
        if self.store.cold is not None:
            # cold-tier integration: budget pressure nudges a stamp;
            # fault-in CRC failures force a rebase (re-reads every row,
            # tombstones the truly lost ones)
            self.store.cold.on_pressure = self.request
            self.store.cold.on_corrupt = self._on_cold_corrupt

    def _on_cold_corrupt(self) -> None:
        self.force_rebase = True
        self._wake.set()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Checkpointer":
        if self._thread is None and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="antidote-checkpoint"
            )
            self._thread.start()
        return self

    def request(self) -> None:
        """Nudge the loop to checkpoint as soon as possible (e.g. after
        importing a shard from a compacted source)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        th = self._thread
        if th is not None:
            th.join(timeout=30)

    def _loop(self) -> None:
        # two independent cadences share the loop: the scrub must keep
        # its own (usually faster-or-slower) rhythm even when the
        # checkpoint interval is long — waking never stamps early, a
        # stamp is taken only when ITS deadline (or a request) is due
        last_ckpt = time.monotonic()
        self._last_scrub = time.monotonic()
        while not self._stop:
            wait = self.interval_s
            if self.scrub_every_s > 0:
                wait = min(wait, self.scrub_every_s)
            woke = self._wake.wait(timeout=wait)
            self._wake.clear()
            if self._stop:
                return
            now = time.monotonic()
            if woke or now - last_ckpt >= self.interval_s:
                last_ckpt = now
                try:
                    self.checkpoint_now()
                except CheckpointError as e:
                    log.warning("periodic checkpoint failed (will retry "
                                "on the next interval): %s", e)
                except Exception:
                    log.exception("periodic checkpoint failed "
                                  "unexpectedly")
            if (self.scrub_every_s > 0 and not self._stop
                    and time.monotonic() - self._last_scrub
                    >= self.scrub_every_s):
                self._last_scrub = time.monotonic()
                try:
                    self.scrub()
                except Exception:
                    log.exception("checkpoint scrub pass failed")

    # -- observability --------------------------------------------------
    def status(self) -> dict:
        last = self.last
        out = {
            "interval_s": self.interval_s,
            "retain": self.retain,
            "rebase_every": self.rebase_every,
            "chain_len": self.chain_len,
            "scrub": dict(self.scrub_counts),
            "reclaimed_bytes_total": self.reclaimed_total,
            "tail_records": int(
                (self.log.seqs - self.log.floor_seqs).sum()),
        }
        if last is not None:
            out.update({
                "last_id": last.get("id"),
                "stamp_vc_max": last.get("stamp_vc_max"),
                "image_bytes": last.get("image_bytes"),
                "age_s": round(time.time() - last.get("created_at", 0), 1),
            })
        if self.metrics is not None and last is not None:
            self.metrics.checkpoint_age.set(out["age_s"])
        return out

    # -- the cycle ------------------------------------------------------
    def _decide_full(self, full: "Optional[bool]") -> bool:
        """Full rebase or delta link?  Forced rebases win; otherwise a
        delta needs a published parent, an unbroken dirty window, a
        chain shorter than ``rebase_every``, and no staged (import)
        cold sources waiting to be persisted locally."""
        if full is not None:
            return bool(full)
        if self.force_rebase or self.last is None:
            return True
        if self.rebase_every <= 1 or self.chain_len + 1 >= self.rebase_every:
            return True
        if self.store.ckpt_dirty_keys is None \
                or self.store._ckpt_dirty_blobs is None \
                or getattr(self.txm, "ckpt_dirty_committed", 0) is None:
            return True
        for t in self.store.tables.values():
            if t._ckpt_dirty is None:
                return True
        cold = self.store.cold
        if cold is not None and cold._extra_sources:
            return True  # staged import sidecar: persist it locally NOW
        return False

    def _consume_windows_locked(self) -> Tuple[Any, dict, set, dict]:
        """Consume every incremental-stamp window under the commit-lock
        barrier (both capture modes reset them — the next window starts
        at this stamp).  Returns (dirty keys | None, evicted dict, blob
        hash set, committed-delta dict)."""
        store, txm = self.store, self.txm
        dirty = store.ckpt_dirty_keys
        store.ckpt_dirty_keys = set()
        evicted = store._ckpt_evicted
        store._ckpt_evicted = {}
        blob_hashes = store._ckpt_dirty_blobs
        store._ckpt_dirty_blobs = set()
        committed_dirty = getattr(txm, "ckpt_dirty_committed", None)
        if hasattr(txm, "ckpt_dirty_committed"):
            txm.ckpt_dirty_committed = set()
        if blob_hashes is None or committed_dirty is None:
            dirty = None  # any overflowed window ⇒ rebase
            blob_hashes = set()
            committed_dirty = set()
        committed = {}
        for dk in committed_dirty:
            v = txm.committed_keys.get(dk)
            if v is not None:
                committed[dk] = int(v)
        for t in store.tables.values():
            t.take_ckpt_dirty()
        return dirty, evicted, blob_hashes, committed

    def checkpoint_now(self, full: "Optional[bool]" = None) -> dict:
        with self._lock:
            t0 = time.monotonic()
            with self.txm.checkpoint_barrier:
                want_full = self._decide_full(full)
                if want_full:
                    cap, frozen = self._capture_locked()
                else:
                    cap, frozen = self._capture_delta_locked()
                    if cap is None:
                        want_full = True
                        cap, frozen = self._capture_locked()
            barrier_s = time.monotonic() - t0
            try:
                self._scan_chains(cap)
                if want_full:
                    path, manifest = self._write_atomic(cap, frozen)
                else:
                    path, manifest = self._write_atomic_delta(cap, frozen)
            except CheckpointError:
                self.force_rebase = True  # the consumed dirty windows
                # are gone; only a rebase re-covers everything
                raise
            except BaseException as e:
                # a failed checkpoint must leave the store EXACTLY as it
                # was: no floor movement, no truncation, and — crucially
                # for the ENOSPC case — no read-only flip (that mode is
                # the WAL append path's contract, not ours; reads and
                # writes keep flowing on the intact log).  Rotated-out
                # segment handles are closed NOW (their files stay; the
                # retry reuses the already-rotated generation), so hours
                # of failing cycles never leak fds
                self.log.drain_retired()
                self.force_rebase = True
                if self.metrics is not None:
                    self.metrics.checkpoint_total.inc(status="error")
                raise CheckpointError(
                    f"checkpoint aborted, nothing published: {e}"
                ) from e
            with self.txm.checkpoint_barrier:
                self.log.set_floor(cap["floor_seqs"], cap["chain_floor"])
            self._rotated_unpublished = False
            if want_full:
                self.chain_len = 0
                self.force_rebase = False
                cold = self.store.cold
                if cold is not None:
                    # re-anchor every cold/evict ref onto the fresh image
                    cold.rebind(cap["id"], cap.get("resident_map") or {},
                                cap.get("cold_rebinds") or {},
                                cap.get("cold_lost") or set())
                    for token in list(cold._extra_sources):
                        cold.drop_source(token)  # staged import persisted
                reclaimed = self._retire_and_reclaim(cap)
            else:
                self.chain_len += 1
                reclaimed = 0
            self.reclaimed_total += reclaimed
            manifest["reclaimed_bytes"] = reclaimed
            self.last = manifest
            if self.metrics is not None:
                self.metrics.checkpoint_total.inc(status="ok")
                self.metrics.checkpoint_stamp.inc(
                    kind=manifest.get("kind", "full"))
                self.metrics.checkpoint_stamp_rows.inc(
                    manifest["n_rows"], kind=manifest.get("kind", "full"))
                self.metrics.wal_reclaimed.inc(reclaimed)
                self.metrics.checkpoint_age.set(0.0)
            total_s = time.monotonic() - t0
            log.info(
                "checkpoint %d (%s) published: %d keys, %d table rows, "
                "%.1f MiB image, %.1f MiB WAL reclaimed "
                "(stamp barrier %.0f ms, total %.2f s)",
                manifest["id"], manifest.get("kind", "full"),
                manifest["n_keys"], manifest["n_rows"],
                manifest["image_bytes"] / 2**20, reclaimed / 2**20,
                barrier_s * 1e3, total_s,
            )
            return dict(manifest, barrier_ms=round(barrier_s * 1e3, 1),
                        total_s=round(total_s, 3))

    def _capture_locked(self) -> Tuple[dict, dict]:
        """The consistent cut, under the commit lock: host bookkeeping
        is copied, device heads are COPY-DISPATCHED (jit copies of the
        immutable head buffers — materialized outside the lock; the
        dispatch order protects them from later donating kernels), and
        the WAL rotates onto a fresh segment generation so the floor
        cleanly separates image from tail."""
        store, txm, logm = self.store, self.txm, self.log
        cap: Dict[str, Any] = {
            "id": self._next_id,
            "n_shards": store.cfg.n_shards,
            "max_dcs": store.cfg.max_dcs,
            "stamp_vc": store.applied_vc.copy(),
            "commit_counter": int(txm.commit_counter),
            "op_ids": logm.op_ids.copy(),
            "prev_floor": logm.floor_seqs.copy(),
            "prev_chain_floor": logm.chain_floor.copy(),
            "committed_keys": dict(txm.committed_keys),
            "directory": dict(store.directory),
            "blobs": dict(store.blobs._by_handle),
            "blob_seen": [sorted(s) for s in logm._blob_seen],
            "shard_resets": dict(logm.shard_resets),
            "extras": {},
        }
        for name, provider in self.extras_providers.items():
            try:
                cap["extras"][name] = provider()
            except Exception:
                log.exception("checkpoint extras provider %r failed "
                              "(omitted from the image)", name)
        # incremental windows reset at EVERY stamp (a full covers them)
        self._consume_windows_locked()
        # cold-tier snapshot: the still-cold keys this image must carry
        # forward into its sidecar appendix (coords read off-lock — cold
        # rows are immutable while cold, and a racing fault-in leaves
        # the bytes untouched until a post-capture write, which the next
        # window covers)
        if store.cold is not None:
            cap["cold_manifest"] = store.cold.cold_manifest()
        frozen: Dict[str, dict] = {}
        for tname, t in store.tables.items():
            used = t.used_rows.copy()
            if int(used.max()) == 0:
                continue
            frozen[tname] = {
                "slot": t._copy_tree_fn((t.head, t.head_vc)),
                "used": used,
                "slots_ub": t.slots_ub.copy(),
                "max_abs_delta": int(t.max_abs_delta),
                "max_commit_vc": t.max_commit_vc.copy(),
            }
        # rotate onto a fresh segment generation — unless a FAILED
        # attempt already did and never published: its generation is
        # still "everything since the last publish", and rotating again
        # would open n_shards × n_segments new files per failing cycle
        if not self._rotated_unpublished:
            logm.rotate_generation()
            self._rotated_unpublished = True
        cap["floor_seqs"] = logm.seqs.copy()
        self._next_id += 1
        return cap, frozen

    def _capture_delta_locked(self):
        """Delta-link capture: only the keys/rows dirtied since the
        parent link.  Device gathers are COPY-DISPATCHED (materialized
        outside the lock); the per-key bookkeeping deltas are host dict
        copies bounded by the dirty set.  Returns (None, None) when the
        windows turn out unusable — the caller falls back to a full
        rebase (the windows were consumed either way; the rebase covers
        everything)."""
        store, txm, logm = self.store, self.txm, self.log
        dirty, evicted, blob_hashes, committed = \
            self._consume_windows_locked()
        if dirty is None:
            return None, None
        anchor = store.cold.anchor if store.cold is not None else None
        cold_delta = []
        for dk, (tname, shard, srow, src) in evicted.items():
            if src != anchor or isinstance(src, str):
                return None, None  # unanchored eviction: rebase
            cold_delta.append([dk[0], dk[1], tname, int(shard), int(srow)])
        cap: Dict[str, Any] = {
            "id": self._next_id,
            "parent": int(self.last["id"]),
            "n_shards": store.cfg.n_shards,
            "max_dcs": store.cfg.max_dcs,
            "stamp_vc": store.applied_vc.copy(),
            "commit_counter": int(txm.commit_counter),
            "op_ids": logm.op_ids.copy(),
            "prev_floor": logm.floor_seqs.copy(),
            "prev_chain_floor": logm.chain_floor.copy(),
            "shard_resets": dict(logm.shard_resets),
            "cold_delta": cold_delta,
            "cold_src": anchor,
            "committed_delta": [[k, b, v] for (k, b), v in
                                committed.items()],
            "blobs_delta": [
                [int(h), bytes(store.blobs._by_handle[h])]
                for h in blob_hashes if h in store.blobs._by_handle
            ],
            "blob_seen": [sorted(s) for s in logm._blob_seen],
            "extras": {},
        }
        for name, provider in self.extras_providers.items():
            try:
                cap["extras"][name] = provider()
            except Exception:
                log.exception("checkpoint extras provider %r failed "
                              "(omitted from the link)", name)
        by_table: Dict[str, list] = {}
        directory_delta = []
        for dk in dirty:
            ent = store.directory.get(dk)
            if ent is None:
                continue  # evicted after the write (rides cold_delta)
            by_table.setdefault(ent[0], []).append((dk, ent[1], ent[2]))
            directory_delta.append([dk[0], dk[1], ent[0], int(ent[1]),
                                    int(ent[2])])
        cap["directory_delta"] = directory_delta
        frozen: Dict[str, dict] = {}
        for tname, items in by_table.items():
            t = store.tables[tname]
            ss = np.asarray([x[1] for x in items], np.int64)
            rr = np.asarray([x[2] for x in items], np.int64)
            frozen[tname] = {
                "rows": [[int(s), int(r)] for s, r in zip(ss, rr)],
                "slot": t.gather_rows_dispatch(ss, rr),
                "slots_ub": t.slots_ub[ss, rr].copy(),
                "used_rows": t.used_rows.copy(),
                "max_abs_delta": int(t.max_abs_delta),
                "max_commit_vc": t.max_commit_vc.copy(),
            }
        if not self._rotated_unpublished:
            logm.rotate_generation()
            self._rotated_unpublished = True
        cap["floor_seqs"] = logm.seqs.copy()
        self._next_id += 1
        return cap, frozen

    def _scan_chains(self, cap: dict) -> None:
        """Replication txn-group counts at the new floor = counts at the
        previous floor + groups in the (prev, new] sequence window, by
        (origin, commit VC) identity — one bounded scan of the data
        written since the last checkpoint (the first checkpoint scans
        the whole log, once, in the background)."""
        from antidote_tpu.log import shard_segment_paths

        logm = self.log
        chains = cap["prev_chain_floor"].copy()
        for shard in range(cap["n_shards"]):
            lo = int(cap["prev_floor"][shard])
            hi = int(cap["floor_seqs"][shard])
            if hi <= lo:
                continue
            seen: set = set()
            for rec in replay_segments(shard_segment_paths(
                    logm.dir, shard, logm.n_segments)):
                q = rec.get("q")
                if q is None:
                    if lo > 0:
                        continue  # legacy prefix already below prev floor
                elif q <= lo or q > hi:
                    continue
                ident = (int(rec["o"]),
                         tuple(int(x) for x in rec["vc"]))
                if ident in seen:
                    continue
                seen.add(ident)
                chains[shard, int(rec["o"])] += 1
        cap["chain_floor"] = chains

    def _carry_cold(self, cap: dict, tables: Dict[str, dict]):
        """Build the sidecar's cold appendix: every still-cold key's row
        is read (bulk per column) from its source sidecar, per-row
        CRC-verified, and re-addressed after the new image's resident
        extent.  Unreadable rows become ``lost`` — surfaced loudly, and
        tombstoned so their reads fail typed instead of serving bottom.
        Returns (appendix arrays merged into ``tables``, cold_directory
        entries, rebind map, lost set)."""
        cold_man = cap.get("cold_manifest") or {}
        cold_dir: list = []
        rebinds: Dict[Any, tuple] = {}
        lost: set = set()
        if not cold_man:
            return cold_dir, rebinds, lost
        cold = self.store.cold
        for tname, by_shard in cold_man.items():
            # group by source (one bulk column load per (src, table))
            srcs = {src for items in by_shard.values()
                    for _dk, _sr, src in items}
            cols: Dict[Any, dict] = {}
            for src in srcs:
                sc = cold._sidecar(src)
                tman = sc.man["tables"][tname]
                cols[src] = {
                    "fields": {f: sc.read_column(tname, f)
                               for f in sorted(tman["fields"])},
                    "head_vc": sc.read_column(tname, "head_vc"),
                    "slots_ub": sc.read_column(tname, "slots_ub"),
                    "row_crc": sc.read_column(tname, "row_crc"),
                }
            tb = tables.get(tname)
            if tb is None:
                # every key of this table is cold: synthesize an empty
                # resident block with the right shapes from the source
                any_src = next(iter(cols.values()))
                p = self.store.cfg.n_shards
                tb = tables[tname] = {
                    "used_rows": np.zeros(p, np.int64),
                    "head": {f: np.zeros((p, 0) + x.shape[2:], x.dtype)
                             for f, x in any_src["fields"].items()},
                    "head_vc": np.zeros((p, 0, self.store.cfg.max_dcs),
                                        np.int32),
                    "slots_ub": np.zeros((p, 0), np.int32),
                    "max_abs_delta": 0,
                    "max_commit_vc": np.zeros(self.store.cfg.max_dcs,
                                              np.int32),
                }
            u_cap = tb["head_vc"].shape[1]
            c_max = max(len(items) for items in by_shard.values())
            p = tb["head_vc"].shape[0]
            ext = {
                "head": {f: np.zeros((p, u_cap + c_max) + x.shape[2:],
                                     x.dtype)
                         for f, x in tb["head"].items()},
                "head_vc": np.zeros((p, u_cap + c_max,
                                     tb["head_vc"].shape[2]), np.int32),
                "slots_ub": np.zeros((p, u_cap + c_max), np.int32),
            }
            for f, x in tb["head"].items():
                ext["head"][f][:, :u_cap] = x
            ext["head_vc"][:, :u_cap] = tb["head_vc"]
            ext["slots_ub"][:, :u_cap] = tb["slots_ub"]
            for shard, items in by_shard.items():
                for i, (dk, srow, src) in enumerate(items):
                    c = cols[src]
                    parts = [c["fields"][f][shard, srow].tobytes()
                             for f in sorted(c["fields"])]
                    parts.append(np.ascontiguousarray(
                        c["head_vc"][shard, srow], np.int32).tobytes())
                    parts.append(np.ascontiguousarray(
                        c["slots_ub"][shard, srow], np.int32).tobytes())
                    want = int(c["row_crc"][shard, srow])
                    if (zlib.crc32(b"".join(parts)) & 0xFFFFFFFF) != want:
                        lost.add(dk)
                        log.error(
                            "cold carry-forward: row CRC mismatch for "
                            "%r (%s[%d,%d] of source %r) — the key's "
                            "state is LOST to bit rot", dk, tname, shard,
                            srow, src)
                        continue
                    new_row = u_cap + i
                    for f in ext["head"]:
                        ext["head"][f][shard, new_row] = \
                            c["fields"][f][shard, srow]
                    ext["head_vc"][shard, new_row] = \
                        c["head_vc"][shard, srow]
                    ext["slots_ub"][shard, new_row] = \
                        c["slots_ub"][shard, srow]
                    cold_dir.append([dk[0], dk[1], tname, int(shard),
                                     int(new_row)])
                    rebinds[dk] = (tname, int(shard), int(new_row))
            tb["head"] = ext["head"]
            tb["head_vc"] = ext["head_vc"]
            tb["slots_ub"] = ext["slots_ub"]
        return cold_dir, rebinds, lost

    def _write_atomic(self, cap: dict, frozen: dict) -> Tuple[str, dict]:
        from antidote_tpu.store.handoff import opaque, pack

        tables: Dict[str, dict] = {}
        for tname, fz in frozen.items():
            used = fz["used"]
            u_cap = int(used.max())
            head_cp, head_vc_cp = fz["slot"]
            tables[tname] = {
                "used_rows": used,
                "head": {f: np.asarray(x)[:, :u_cap].copy()
                         for f, x in head_cp.items()},
                "head_vc": np.asarray(head_vc_cp)[:, :u_cap].copy(),
                "slots_ub": fz["slots_ub"][:, :u_cap].copy(),
                "max_abs_delta": fz["max_abs_delta"],
                "max_commit_vc": fz["max_commit_vc"],
            }
        # the sidecar extends each table past its resident extent with
        # the carried-forward cold rows; the IMAGE keeps only the
        # resident slices (recovery installs exactly those on device)
        resident_caps = {tname: tb["head_vc"].shape[1]
                         for tname, tb in tables.items()}
        cold_dir, rebinds, lost = self._carry_cold(cap, tables)
        sidecar_tables = {
            tname: {"head": tb["head"], "head_vc": tb["head_vc"],
                    "slots_ub": tb["slots_ub"]}
            for tname, tb in tables.items()
        } if (self.store.cold is not None or cold_dir) else None
        if cold_dir:
            # restore the image's resident-only slices
            tables = {
                tname: dict(
                    tb,
                    head={f: x[:, :resident_caps[tname]]
                          for f, x in tb["head"].items()},
                    head_vc=tb["head_vc"][:, :resident_caps[tname]],
                    slots_ub=tb["slots_ub"][:, :resident_caps[tname]],
                )
                for tname, tb in tables.items()
            }
        cap["resident_map"] = dict(cap["directory"])
        cap["cold_rebinds"] = rebinds
        cap["cold_lost"] = lost
        image = {
            "version": 2,
            "id": cap["id"],
            "n_shards": cap["n_shards"],
            "max_dcs": cap["max_dcs"],
            "stamp_vc": cap["stamp_vc"],
            "commit_counter": cap["commit_counter"],
            "floor_seqs": cap["floor_seqs"],
            "chain_floor": cap["chain_floor"],
            "op_ids": cap["op_ids"],
            "shard_resets": {str(k): v
                             for k, v in cap["shard_resets"].items()},
            # opaque(): the two per-key lists are the image's big flat
            # payloads — one C-speed msgpack pass each, not a recursive
            # Python walk per entry (5M dec() calls at 1M keys)
            "committed_keys": opaque([
                [k, b, int(v)] for (k, b), v in cap["committed_keys"].items()
            ]),
            "directory": opaque([
                [key, bucket, tname, int(shard), int(row)]
                for (key, bucket), (tname, shard, row)
                in cap["directory"].items()
            ]),
            "blobs": opaque([[int(h), bytes(d)]
                             for h, d in cap["blobs"].items()]),
            "blob_seen": opaque(cap["blob_seen"]),
            "cold_directory": opaque(cold_dir),
            "tables": tables,
            "extras": cap["extras"],
        }
        data = pack(image)
        manifest = {
            "id": cap["id"],
            "kind": "full",
            "created_at": time.time(),
            "image_bytes": len(data),
            "image_crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "n_keys": len(cap["directory"]) + len(cold_dir),
            "n_rows": int(sum(int(t["used_rows"].sum())
                              for t in tables.values())),
            "cold_keys": len(cold_dir),
            "tables": sorted(tables),
            "commit_counter": cap["commit_counter"],
            "stamp_vc_max": [int(x) for x in cap["stamp_vc"].max(axis=0)],
            "floor_seqs": [int(x) for x in cap["floor_seqs"]],
        }
        return self._publish_dir(cap["id"], data, manifest, sidecar_tables)

    def _write_atomic_delta(self, cap: dict, frozen: dict) -> Tuple[str, dict]:
        from antidote_tpu.store.handoff import opaque, pack

        tables: Dict[str, dict] = {}
        n_rows = 0
        for tname, fz in frozen.items():
            head_cp, head_vc_cp = fz["slot"]
            m = len(fz["rows"])  # drop the gather's bucket padding
            tables[tname] = {
                "rows": fz["rows"],
                "head": {f: np.asarray(x)[:m].copy()
                         for f, x in head_cp.items()},
                "head_vc": np.asarray(head_vc_cp)[:m].copy(),
                "slots_ub": fz["slots_ub"],
                "used_rows": fz["used_rows"],
                "max_abs_delta": fz["max_abs_delta"],
                "max_commit_vc": fz["max_commit_vc"],
            }
            n_rows += len(fz["rows"])
        link = {
            "version": 2,
            "kind": "delta",
            "id": cap["id"],
            "parent": cap["parent"],
            "n_shards": cap["n_shards"],
            "max_dcs": cap["max_dcs"],
            "stamp_vc": cap["stamp_vc"],
            "commit_counter": cap["commit_counter"],
            "floor_seqs": cap["floor_seqs"],
            "chain_floor": cap["chain_floor"],
            "op_ids": cap["op_ids"],
            "shard_resets": {str(k): v
                             for k, v in cap["shard_resets"].items()},
            "directory_delta": opaque(cap["directory_delta"]),
            "committed_delta": opaque(cap["committed_delta"]),
            "blobs_delta": opaque(cap["blobs_delta"]),
            "blob_seen": opaque(cap["blob_seen"]),
            "cold_delta": opaque(cap["cold_delta"]),
            "cold_src": cap["cold_src"],
            "tables": tables,
            "extras": cap["extras"],
        }
        data = pack(link)
        manifest = {
            "id": cap["id"],
            "kind": "delta",
            "parent": int(cap["parent"]),
            "created_at": time.time(),
            "image_bytes": len(data),
            "image_crc32": zlib.crc32(data) & 0xFFFFFFFF,
            "n_keys": len(cap["directory_delta"]),
            "n_rows": n_rows,
            "tables": sorted(tables),
            "commit_counter": cap["commit_counter"],
            "stamp_vc_max": [int(x) for x in cap["stamp_vc"].max(axis=0)],
            "floor_seqs": [int(x) for x in cap["floor_seqs"]],
        }
        return self._publish_dir(cap["id"], data, manifest, None)

    def _publish_dir(self, cap_id: int, data: bytes, manifest: dict,
                     sidecar_tables) -> Tuple[str, dict]:
        """Shared atomic publish: stream image + (optional) cold sidecar
        + manifest into a temp dir, fsync through the group coordinator,
        one rename.  A failure at ANY point leaves the published set
        untouched."""
        from antidote_tpu.store.coldtier import COLD_BIN, write_sidecar

        os.makedirs(self.root, exist_ok=True)
        tmp = os.path.join(self.root, f"tmp.{os.getpid()}.{cap_id}")
        final = os.path.join(self.root, f"ckpt_{cap_id}")
        try:
            shutil.rmtree(tmp, ignore_errors=True)  # reclaim-ok: stale
            # temp dir from a crashed writer — never a published image
            os.makedirs(tmp)
            img_path = os.path.join(tmp, _IMAGE)
            with open(img_path, "wb") as f:
                _faulted_write(f, data, f"ckpt_{cap_id}")
                f.flush()
                # image durability rides the group-fsync coordinator —
                # one fsync stream process-wide, coalesced with any
                # commit barriers in flight
                self.log._fsync.submit(
                    [_ImageFsync(f.fileno(), f"ckpt_{cap_id}")]
                ).wait()
            if sidecar_tables is not None:
                d = faults.hit("ckpt.write", key=f"ckpt_{cap_id}")
                if d is not None:
                    if d.action == "delay" and d.arg:
                        time.sleep(float(d.arg))
                    elif d.action in ("error", "io_error", "enospc"):
                        raise OSError(
                            errno.ENOSPC if d.action == "enospc"
                            else errno.EIO,
                            f"injected fault: ckpt.write cold ckpt_{cap_id}")
                with open(os.path.join(tmp, COLD_BIN), "wb") as f:
                    cman = write_sidecar(f, sidecar_tables)
                    f.flush()
                    self.log._fsync.submit(
                        [_ImageFsync(f.fileno(), f"ckpt_{cap_id}")]
                    ).wait()
                cman["n_shards"] = self.store.cfg.n_shards
                manifest["cold"] = cman
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())  # fsync-ok: manifest must be durable
                # before the rename publishes the image
            _fsync_dir(tmp)
            d = faults.hit("ckpt.rename", key=f"ckpt_{cap_id}")
            if d is not None:
                if d.action == "delay" and d.arg:
                    time.sleep(float(d.arg))
                elif d.action in ("error", "io_error", "enospc"):
                    raise OSError(errno.EIO,
                                  "injected fault: ckpt.rename")
            os.rename(tmp, final)
            _fsync_dir(self.root)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)  # reclaim-ok: failed
            # attempt's temp dir; the published set is untouched
            raise
        return final, manifest

    def _retire_and_reclaim(self, cap: dict) -> int:
        """Post-publish housekeeping (runs on FULL publishes): drop full
        images beyond the retention window and every delta link the new
        rebase covers (links below the newest full), then reclaim WAL
        files wholly below the OLDEST RETAINED FULL image's floor —
        never a delta's.  Delta floors advance replay skipping, but the
        WAL above the last full stays on disk so a corrupt mid-chain
        link always falls back to full-image + longer tail.  Best-effort
        — a failure here never unpublishes the image."""
        reclaim_floors = np.asarray(cap["floor_seqs"], np.int64)
        try:
            published = []
            for _id, p in list_checkpoints(self.root):
                m = load_manifest(p)
                if m is not None:
                    published.append((_id, p, m))
            fulls = [(i, p, m) for i, p, m in published
                     if manifest_kind(m) == "full"]
            retained = fulls[-self.retain:]
            retained_ids = {i for i, _p, _m in retained}
            newest_full = retained[-1][0] if retained else -1
            for _id, path, m in published:
                if manifest_kind(m) == "full":
                    if _id not in retained_ids:
                        shutil.rmtree(path, ignore_errors=True)
                        # reclaim-ok: full image beyond the retention
                        # window; newer retained fulls cover it
                elif _id < newest_full:
                    shutil.rmtree(path, ignore_errors=True)  # reclaim-ok:
                    # delta link below the newest rebase — the rebase
                    # carries everything the link did
            for name in os.listdir(self.root):
                if name.startswith("tmp."):
                    shutil.rmtree(os.path.join(self.root, name),
                                  ignore_errors=True)  # reclaim-ok:
                    # orphaned temp dir from a crashed/failed writer
            floors = [m["floor_seqs"] for _i, _p, m in retained
                      if m.get("floor_seqs") is not None]
            if floors:
                reclaim_floors = np.minimum.reduce(
                    [np.asarray(f, np.int64) for f in floors])
        except OSError:
            log.warning("checkpoint retention sweep failed", exc_info=True)
        try:
            return self.log.reclaim_below(reclaim_floors)
        except Exception:
            log.warning("WAL reclaim below the checkpoint floor failed "
                        "(will retry next checkpoint)", exc_info=True)
            return 0

    # -- background scrub (ISSUE 13 satellite) --------------------------
    def _scrub_file(self, path: str, want_bytes: int, want_crc: int) -> bool:
        """Rate-limited whole-file CRC verification (off the commit
        lock; reads throttled to ``scrub_bps``)."""
        crc = 0
        n = 0
        t0 = time.monotonic()
        try:
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(_CHUNK)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    n += len(chunk)
                    budget = n / max(self.scrub_bps, 1)
                    spent = time.monotonic() - t0
                    if budget > spent:
                        time.sleep(min(budget - spent, 0.25))
        except OSError:
            return False
        return n == int(want_bytes) and (crc & 0xFFFFFFFF) == int(want_crc)

    def scrub(self) -> Dict[str, int]:
        """One background bit-rot pass over every retained image/link:
        re-read and CRC-verify ``image.bin`` (and the cold sidecar when
        present).  A corrupt DELTA link is retired on the spot (the
        chain re-anchors on the prefix) and a rebase is forced; a
        corrupt FULL image forces a rebase but is kept published — its
        per-row CRCs still guard individual cold fault-ins, and the
        rebase decides per row what survives.  Counts land in
        ``antidote_checkpoint_scrub_total{result}``."""
        out = {"ok": 0, "corrupt": 0}
        for _id, path in list_checkpoints(self.root):
            m = load_manifest(path)
            if m is None:
                continue
            ok = self._scrub_file(os.path.join(path, _IMAGE),
                                  m.get("image_bytes", -1),
                                  m.get("image_crc32", -1))
            cold = m.get("cold")
            if ok and cold is not None:
                from antidote_tpu.store.coldtier import COLD_BIN

                ok = self._scrub_file(os.path.join(path, COLD_BIN),
                                      cold.get("bytes", -1),
                                      cold.get("crc32", -1))
            result = "ok" if ok else "corrupt"
            out[result] += 1
            self.scrub_counts[result] = self.scrub_counts.get(result, 0) + 1
            if self.metrics is not None:
                self.metrics.checkpoint_scrub.inc(result=result)
            if ok:
                continue
            if manifest_kind(m) == "delta":
                log.error("scrub: chain link ckpt_%d is corrupt on disk; "
                          "retiring it and forcing a rebase", _id)
                shutil.rmtree(path, ignore_errors=True)  # reclaim-ok:
                # scrub-condemned delta link; the forced rebase below
                # re-covers its window from live state
            else:
                log.error("scrub: full image ckpt_%d is corrupt on disk; "
                          "forcing a rebase (kept published — per-row "
                          "CRCs still guard cold fault-ins)", _id)
            self.force_rebase = True
            self.request()
        return out
