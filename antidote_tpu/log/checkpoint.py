"""Checkpointed fast restart: VC-stamped epoch snapshots of the whole
store, WAL tail truncation, crash-safe compaction (ISSUE 8).

The reference treats "the op log IS the checkpoint" (``recover_from_log``)
and bounds it only by pruning ops below the min cached snapshot
(``prune_ops``, SURVEY §2.3).  This module lifts that idea to the store
level: a background checkpointer streams an atomically-published image of
the store — per-table frozen heads (the same immutable buffers the
serving-epoch plane gathers from), slot-tier metadata, the directory,
blob payloads, op-id chains, certification stamps, commit counters, and
the inter-DC chain positions — stamped with the applied vector clock and
each shard's WAL append sequence ``q`` (the *floor*).  Recovery becomes
load-image + heap-merge replay of only the WAL tail above the floor, and
WAL files wholly below the floor are reclaimed through a guarded API
(:meth:`~antidote_tpu.log.LogManager.reclaim_below` — never a raw
unlink), which is what bounds WAL growth under a sustained write storm.

Crash safety contract: a SIGKILL at ANY point — mid-stream, mid-rename,
mid-truncation — recovers byte-identical to a never-checkpointed replay.
The mechanics:

  * the stamp is captured under the commit lock (a short barrier: device
    head copies are *dispatched* there, materialized outside), so the
    image is a consistent cut: every WAL record with ``q ≤ floor`` is in
    the image, every record above it is not;
  * the image is written to a temp dir, fsynced THROUGH the group-fsync
    coordinator (checkpointing never adds a second fsync stream to the
    commit path), and published by one atomic directory rename;
  * replay always skips records at or below the installed floor, so
    whether a below-floor file was already deleted, half-deleted, or
    still present changes nothing;
  * reclaim runs only after publish, deletes only whole files whose
    every record a scan proves ≤ floor, and a checkpoint failure
    (ENOSPC mid-image) aborts BEFORE the floor moves — nothing is
    truncated and the store never flips read-only because of it.

Fault sites (chaos suite): ``ckpt.write``, ``ckpt.fsync``,
``ckpt.rename`` here, ``wal.truncate_below`` in the reclaim API.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from antidote_tpu import faults
from antidote_tpu.log.wal import replay_segments

log = logging.getLogger(__name__)

#: subdirectory of the log dir holding published images
CKPT_DIR = "checkpoints"
#: published checkpoint directory name
_CKPT_RE = re.compile(r"ckpt_(\d+)$")
#: image stream chunk (each chunk consults the ckpt.write fault site, so
#: chaos delays can hold the writer mid-stream)
_CHUNK = 8 << 20

_IMAGE = "image.bin"
_MANIFEST = "manifest.json"


class CheckpointError(RuntimeError):
    """A checkpoint attempt failed (nothing was published or truncated;
    the store's durability state is untouched)."""


def checkpoint_root(log_dir: str) -> str:
    return os.path.join(log_dir, CKPT_DIR)


def has_checkpoints(log_dir: str) -> bool:
    """True when the directory holds at least one published checkpoint —
    such a dir carries committed data even if every WAL file was
    reclaimed, so boot paths must demand ``recover=True`` for it."""
    return bool(list_checkpoints(checkpoint_root(log_dir)))


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """Published (id, path) pairs, oldest first.  A directory without a
    readable manifest is not published (a crash mid-write leaves only
    ``tmp.*`` dirs, which never match)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _CKPT_RE.fullmatch(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if os.path.exists(os.path.join(path, _MANIFEST)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_manifest(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def load_latest(log_dir: str) -> Optional[Tuple[dict, dict]]:
    """Newest checkpoint whose image verifies (size + CRC against its
    manifest), or None.  A corrupt newest image falls back to the next
    older one — the retention window is the recovery safety margin."""
    from antidote_tpu.store.handoff import unpack

    for id_, path in reversed(list_checkpoints(checkpoint_root(log_dir))):
        manifest = load_manifest(path)
        if manifest is None:
            continue
        try:
            with open(os.path.join(path, _IMAGE), "rb") as f:
                data = f.read()
        except OSError:
            continue
        if (len(data) != int(manifest.get("image_bytes", -1))
                or (zlib.crc32(data) & 0xFFFFFFFF)
                != int(manifest.get("image_crc32", -1))):
            log.warning("checkpoint %s fails verification; falling back "
                        "to an older image", path)
            continue
        try:
            image = unpack(data)
        except Exception:
            log.warning("checkpoint %s image undecodable; falling back",
                        path)
            continue
        return image, manifest
    return None


def latest_image_meta(log_dir: str,
                      before_id: Optional[int] = None) -> Optional[dict]:
    """Shippable metadata of the newest published checkpoint image —
    what the owner answers a follower's ``ckpt_meta`` request with:
    ``{id, image_bytes, image_crc32, stamp_vc_max, created_at}``.
    Served straight from the manifest (never decodes the image).
    ``before_id`` restricts to strictly older images — a follower whose
    fetch of the newest image failed verification (bit rot) falls back
    through the retention window exactly like owner-side recovery."""
    cks = list_checkpoints(checkpoint_root(log_dir))
    for _id, path in reversed(cks):
        if before_id is not None and _id >= int(before_id):
            continue
        manifest = load_manifest(path)
        if manifest is None:
            continue
        return {
            "id": int(manifest["id"]),
            "image_bytes": int(manifest["image_bytes"]),
            "image_crc32": int(manifest["image_crc32"]),
            "stamp_vc_max": manifest.get("stamp_vc_max"),
            "created_at": manifest.get("created_at"),
        }
    return None


def image_path(log_dir: str, ckpt_id: int) -> str:
    """Path of a published image file by id (ckpt_fetch serving)."""
    return os.path.join(checkpoint_root(log_dir), f"ckpt_{int(ckpt_id)}",
                        _IMAGE)


def discard_all(log_dir: str) -> int:
    """Delete EVERY published checkpoint image under a log dir — the
    diverged-follower repair path: a follower re-bootstrapping from the
    owner's image must not let its own (possibly corrupt-derived) local
    images resurrect at the next restart.  Owned by this module so the
    deletion stays inside the guarded log/ lifecycle.  Returns the
    number of images discarded."""
    root = checkpoint_root(log_dir)
    cks = list_checkpoints(root)
    for _id, path in cks:
        shutil.rmtree(path, ignore_errors=True)  # reclaim-ok: explicit
        # whole-image discard before a follower re-bootstrap re-seeds
        # the store from the owner's image
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.startswith("tmp."):
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)  # reclaim-ok: orphaned
                # temp dir of a crashed writer
    return len(cks)


# ---------------------------------------------------------------------------
# image install (recovery side)
# ---------------------------------------------------------------------------
def install_image(store, txm, image: dict, shards=None) -> dict:
    """Install a checkpoint image into a FRESH store/txn-manager pair
    (the recovery fast path's first phase; the caller replays the WAL
    tail afterwards — :meth:`LogManager.replay_shard` already skips
    everything the installed floor covers).

    Shards whose durable truncation epoch (``antidote_meta.json``
    ``shard_resets``, bumped by every ``truncate_shard``) advanced past
    the image's are DROPPED: a shard relinquished to another owner after
    the checkpoint was written must not resurrect here.  Returns a
    summary dict (keys, tables, dropped shards).

    ``shards`` (optional) RESTRICTS the install to that shard set and
    MERGES onto whatever the store already holds instead of replacing it
    — the per-member composition primitive of the follower fleet tier
    (ISSUE 11): a follower of a clustered owner installs each member's
    image restricted to the shards that member owns, so one composed
    store covers the whole DC.  Un-restricted installs keep the exact
    whole-store replace semantics recovery depends on.
    """
    from antidote_tpu.store.kv import freeze_key

    import jax.numpy as jnp

    logm = store.log
    assert logm is not None, "checkpoint install needs the durable log"
    cfg = store.cfg
    if (int(image["n_shards"]) != cfg.n_shards
            or int(image["max_dcs"]) != cfg.max_dcs):
        raise CheckpointError(
            f"checkpoint image shape (n_shards={image['n_shards']}, "
            f"max_dcs={image['max_dcs']}) does not match the deployment "
            f"({cfg.n_shards}, {cfg.max_dcs})"
        )
    image_resets = {int(k): int(v)
                    for k, v in (image.get("shard_resets") or {}).items()}
    stale = sorted(
        s for s in range(cfg.n_shards)
        if logm.shard_resets.get(s, 0) > image_resets.get(s, 0)
    )
    stale_set = set(stale)
    if stale:
        log.warning("checkpoint image predates truncation of shard(s) %s "
                    "(moved/relinquished after the stamp); dropping them "
                    "from the restore", stale)
    #: restricted-merge mode: the shard rows this install may touch
    #: (sorted list), or None for the whole-store replace
    rlist = None
    if shards is not None:
        rlist = sorted(set(int(s) for s in shards) - stale_set)
    floors = np.asarray(image["floor_seqs"], np.int64).copy()
    chains = np.asarray(image["chain_floor"], np.int64).copy()
    op_ids = np.asarray(image["op_ids"], np.int64).copy()
    stamp = np.asarray(image["stamp_vc"], np.int32).copy()
    for s in stale:
        floors[s] = 0
        chains[s] = 0
        op_ids[s] = 0
        stamp[s] = 0
    n_rows_installed = 0
    for tname, tb in image["tables"].items():
        t = store.table(tname)
        used = np.asarray(tb["used_rows"], np.int64).copy()
        for s in stale:
            used[s] = 0
        head_vc = np.asarray(tb["head_vc"], np.int32).copy()
        u_cap = head_vc.shape[1]
        head = {f: np.asarray(x).copy() for f, x in tb["head"].items()}
        slots_ub = np.asarray(tb["slots_ub"], np.int32).copy()
        for s in stale:
            head_vc[s] = 0
            slots_ub[s] = 0
            for f in head:
                head[f][s] = 0
        while u_cap > t.n_rows:
            t._grow()

        # assemble full-extent arrays HOST-side and ship each in one
        # transfer: the store is fresh (all-zero tables), so building
        # zeros + one slice assign + one copying transfer replaces an
        # eager .at[].set dispatch PER ARRAY (each of which copies the
        # whole destination — the measured majority of install time at
        # 1M).  copy=True matters: jnp.asarray may ZERO-COPY alias the
        # host buffer on CPU, and a later donating kernel (the append
        # head fold) would then recycle memory the table still reads —
        # observed as pointer garbage in element lanes under the
        # persistent compile cache.
        def place(host_arr):
            out = jnp.array(host_arr, copy=True)
            if t.sharding is not None:
                import jax

                out = jax.device_put(out, t.sharding)
            return out

        def full(dst, src, snap_slot=False):
            if rlist is None:
                arr = np.zeros(dst.shape, np.dtype(dst.dtype))
                if snap_slot:
                    arr[:, :u_cap, 0] = src
                else:
                    arr[:, :u_cap] = src
            else:
                # restricted merge: keep the destination's other shards
                # (a previous member's installed rows) byte-intact
                arr = np.array(dst, dtype=np.dtype(dst.dtype))
                if snap_slot:
                    arr[rlist, :u_cap, 0] = src[rlist]
                else:
                    arr[rlist, :u_cap] = src[rlist]
            return place(arr)

        for f in t.head:
            t.head[f] = full(t.head[f], head[f])
            # seed ONE snapshot version from the restored head: versioned
            # reads at clocks ≥ a row's head_vc fold the (empty) ring on
            # this base exactly; reads below it come out "incomplete" and
            # surface the compaction horizon instead of silently missing
            # the pre-checkpoint ops the WAL no longer holds
            t.snap[f] = full(t.snap[f], head[f], snap_slot=True)
        t.head_vc = full(t.head_vc, head_vc)
        t.snap_vc = full(t.snap_vc, head_vc, snap_slot=True)
        seq_col = (np.arange(u_cap)[None, :]
                   < used[:, None]).astype(np.int64)
        t.snap_seq = full(t.snap_seq, seq_col, snap_slot=True)
        t.next_seq = 2
        if rlist is None:
            t.used_rows[:] = used
            t.slots_ub[:, :u_cap] = slots_ub
            t.max_abs_delta = int(tb["max_abs_delta"])
        else:
            t.used_rows[rlist] = used[rlist]
            t.slots_ub[rlist, :u_cap] = slots_ub[rlist]
            t.max_abs_delta = max(t.max_abs_delta,
                                  int(tb["max_abs_delta"]))
        if stale or rlist is not None:
            # a dropped shard may have held the table-wide max commit VC;
            # an inflated cap would let a serving epoch claim coverage of
            # commits that never restored — recompute from survivors
            # (restricted merges fold the installed rows into whatever
            # cap earlier members established)
            hv = head_vc if rlist is None else head_vc[rlist]
            mcv = hv.reshape(-1, head_vc.shape[-1]).max(axis=0) \
                if hv.size else np.zeros(cfg.max_dcs, np.int32)
            if rlist is not None:
                mcv = np.maximum(mcv, np.asarray(t.max_commit_vc,
                                                 np.int32))
            t.max_commit_vc = mcv.astype(np.int32)
        else:
            t.max_commit_vc = np.asarray(tb["max_commit_vc"],
                                         np.int32).copy()
        n_rows_installed += int(used.sum() if rlist is None
                                else used[rlist].sum())
    directory = image["directory"]
    if rlist is not None:
        keep = set(rlist)
        directory = [e for e in directory if int(e[3]) in keep]
    elif stale_set:
        directory = [e for e in directory if int(e[3]) not in stale_set]
    n_keys = len(directory)
    if directory:
        # columnar zip build: C-speed tuple pairing for the (vastly
        # common) scalar-key case; only list keys (composite map keys,
        # tuple keys through msgpack) pay a freeze pass
        keys, buckets, tnames, shards, rows = zip(*directory)
        if any(type(k) is list for k in keys):
            keys = tuple(freeze_key(k) for k in keys)
        store.directory.update(
            zip(zip(keys, buckets), zip(tnames, shards, rows)))
    for h, data in image.get("blobs", []):
        store.blobs.intern_bytes(int(h), bytes(data))
    for s, hashes in enumerate(image.get("blob_seen", [])):
        if s < cfg.n_shards and s not in stale_set \
                and (rlist is None or s in set(rlist)):
            logm._blob_seen[s] = {int(h) for h in hashes}
    if rlist is None:
        np.maximum(store.applied_vc, stamp, out=store.applied_vc)
        np.maximum(logm.op_ids, op_ids, out=logm.op_ids)
        logm.set_floor(floors, chains)
    else:
        # merge only the restricted rows — other members' floors/clocks
        # must survive this install untouched
        store.applied_vc[rlist] = np.maximum(store.applied_vc[rlist],
                                             stamp[rlist])
        logm.op_ids[rlist] = np.maximum(logm.op_ids[rlist],
                                        op_ids[rlist])
        fl = logm.floor_seqs.copy()
        ch = logm.chain_floor.copy()
        fl[rlist] = floors[rlist]
        ch[rlist] = chains[rlist]
        logm.set_floor(fl, ch)
    committed = image.get("committed_keys", [])
    if committed and not stale_set and rlist is None \
            and not txm.committed_keys:
        # fresh manager, nothing dropped: bulk build (the per-entry
        # max/membership checks below cost ~1 s per million stamps)
        ck, cb, cv = zip(*committed)
        if any(type(k) is list for k in ck):
            ck = tuple(freeze_key(k) for k in ck)
        txm.committed_keys.update(zip(zip(ck, cb), cv))
    else:
        for key, bucket, counter in committed:
            dk = (freeze_key(key), bucket)
            if dk in store.directory:
                txm.committed_keys[dk] = max(
                    txm.committed_keys.get(dk, 0), int(counter)
                )
    return {
        "id": int(image["id"]),
        "keys": n_keys,
        "rows": n_rows_installed,
        "tables": len(image["tables"]),
        "dropped_shards": stale,
        "restricted_to": rlist,
    }


# ---------------------------------------------------------------------------
# checkpoint writer
# ---------------------------------------------------------------------------
class _ImageFsync:
    """Adapter letting the checkpoint image ride the WAL's group-fsync
    coordinator (one fsync stream for the whole process; a checkpoint
    fsync coalesces with commit-barrier fsyncs instead of competing)."""

    def __init__(self, fileno: int, name: str):
        self._fileno = fileno
        self._name = name

    def sync(self) -> None:
        d = faults.hit("ckpt.fsync", key=self._name)
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action in ("error", "io_error", "enospc"):
                err = errno.ENOSPC if d.action == "enospc" else errno.EIO
                raise OSError(err, f"injected fault: ckpt.fsync {self._name}")
        os.fsync(self._fileno)  # fsync-ok: checkpoint image durability —
        # routed through the group-fsync coordinator (see submit site)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)  # fsync-ok: directory entry durability for the
        # atomic checkpoint publish (rename is only durable with it)
    finally:
        os.close(fd)


def _faulted_write(f, data: bytes, name: str) -> None:
    """Stream ``data`` in chunks, consulting the ``ckpt.write`` fault
    site per chunk (delay rules hold the writer mid-stream so chaos can
    SIGKILL inside the window; enospc/io_error abort the attempt)."""
    view = memoryview(data)
    for off in range(0, max(len(view), 1), _CHUNK):
        d = faults.hit("ckpt.write", key=name)
        if d is not None:
            if d.action == "delay" and d.arg:
                time.sleep(float(d.arg))
            elif d.action == "enospc":
                raise OSError(errno.ENOSPC,
                              f"injected fault: ckpt.write {name}")
            elif d.action in ("error", "io_error"):
                raise OSError(errno.EIO,
                              f"injected fault: ckpt.write {name}")
        f.write(view[off:off + _CHUNK])


class Checkpointer:
    """Background checkpoint writer for one node.

    ``checkpoint_now`` runs one full cycle synchronously: stamp (short
    commit-lock barrier), stream + atomic publish, floor install,
    retention, WAL reclaim.  ``start`` runs it on ``interval_s`` in a
    daemon thread (``request`` nudges an immediate run).  Failures never
    flip the store read-only and never truncate anything — they raise
    :class:`CheckpointError` (or are logged by the loop) and the next
    interval retries.
    """

    def __init__(self, store, txm, metrics=None, interval_s: float = 300.0,
                 retain: int = 2):
        assert store.log is not None, "checkpointing needs a durable log"
        self.store = store
        self.txm = txm
        self.log = store.log
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.retain = max(1, int(retain))
        self.root = checkpoint_root(self.log.dir)
        #: name -> callable returning a msgpack-able blob captured under
        #: the commit lock (cluster membership, embedder state, ...)
        self.extras_providers: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        #: True while a generation rotation from a FAILED attempt is
        #: still unpublished: the retry reuses it instead of rotating
        #: again, so a persistent-ENOSPC outage can't accumulate open
        #: segment handles/files cycle after cycle
        self._rotated_unpublished = False
        #: running total of WAL bytes reclaimed (node-status block)
        self.reclaimed_total = 0
        #: summary of the last published checkpoint (seeded from disk so
        #: a recovered node's status shows its inherited image)
        self.last: Optional[dict] = None
        self._next_id = 1
        cks = list_checkpoints(self.root)
        if cks:
            self._next_id = cks[-1][0] + 1
            self.last = load_manifest(cks[-1][1])

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Checkpointer":
        if self._thread is None and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="antidote-checkpoint"
            )
            self._thread.start()
        return self

    def request(self) -> None:
        """Nudge the loop to checkpoint as soon as possible (e.g. after
        importing a shard from a compacted source)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        th = self._thread
        if th is not None:
            th.join(timeout=30)

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop:
                return
            try:
                self.checkpoint_now()
            except CheckpointError as e:
                log.warning("periodic checkpoint failed (will retry on "
                            "the next interval): %s", e)
            except Exception:
                log.exception("periodic checkpoint failed unexpectedly")

    # -- observability --------------------------------------------------
    def status(self) -> dict:
        last = self.last
        out = {
            "interval_s": self.interval_s,
            "retain": self.retain,
            "reclaimed_bytes_total": self.reclaimed_total,
            "tail_records": int(
                (self.log.seqs - self.log.floor_seqs).sum()),
        }
        if last is not None:
            out.update({
                "last_id": last.get("id"),
                "stamp_vc_max": last.get("stamp_vc_max"),
                "image_bytes": last.get("image_bytes"),
                "age_s": round(time.time() - last.get("created_at", 0), 1),
            })
        if self.metrics is not None and last is not None:
            self.metrics.checkpoint_age.set(out["age_s"])
        return out

    # -- the cycle ------------------------------------------------------
    def checkpoint_now(self) -> dict:
        with self._lock:
            t0 = time.monotonic()
            with self.txm.checkpoint_barrier:
                cap, frozen = self._capture_locked()
            barrier_s = time.monotonic() - t0
            try:
                self._scan_chains(cap)
                path, manifest = self._write_atomic(cap, frozen)
            except CheckpointError:
                raise
            except BaseException as e:
                # a failed checkpoint must leave the store EXACTLY as it
                # was: no floor movement, no truncation, and — crucially
                # for the ENOSPC case — no read-only flip (that mode is
                # the WAL append path's contract, not ours; reads and
                # writes keep flowing on the intact log).  Rotated-out
                # segment handles are closed NOW (their files stay; the
                # retry reuses the already-rotated generation), so hours
                # of failing cycles never leak fds
                self.log.drain_retired()
                if self.metrics is not None:
                    self.metrics.checkpoint_total.inc(status="error")
                raise CheckpointError(
                    f"checkpoint aborted, nothing published: {e}"
                ) from e
            with self.txm.checkpoint_barrier:
                self.log.set_floor(cap["floor_seqs"], cap["chain_floor"])
            self._rotated_unpublished = False
            reclaimed = self._retire_and_reclaim(cap)
            self.reclaimed_total += reclaimed
            manifest["reclaimed_bytes"] = reclaimed
            self.last = manifest
            if self.metrics is not None:
                self.metrics.checkpoint_total.inc(status="ok")
                self.metrics.wal_reclaimed.inc(reclaimed)
                self.metrics.checkpoint_age.set(0.0)
            total_s = time.monotonic() - t0
            log.info(
                "checkpoint %d published: %d keys, %d table rows, "
                "%.1f MiB image, %.1f MiB WAL reclaimed "
                "(stamp barrier %.0f ms, total %.2f s)",
                manifest["id"], manifest["n_keys"], manifest["n_rows"],
                manifest["image_bytes"] / 2**20, reclaimed / 2**20,
                barrier_s * 1e3, total_s,
            )
            return dict(manifest, barrier_ms=round(barrier_s * 1e3, 1),
                        total_s=round(total_s, 3))

    def _capture_locked(self) -> Tuple[dict, dict]:
        """The consistent cut, under the commit lock: host bookkeeping
        is copied, device heads are COPY-DISPATCHED (jit copies of the
        immutable head buffers — materialized outside the lock; the
        dispatch order protects them from later donating kernels), and
        the WAL rotates onto a fresh segment generation so the floor
        cleanly separates image from tail."""
        store, txm, logm = self.store, self.txm, self.log
        cap: Dict[str, Any] = {
            "id": self._next_id,
            "n_shards": store.cfg.n_shards,
            "max_dcs": store.cfg.max_dcs,
            "stamp_vc": store.applied_vc.copy(),
            "commit_counter": int(txm.commit_counter),
            "op_ids": logm.op_ids.copy(),
            "prev_floor": logm.floor_seqs.copy(),
            "prev_chain_floor": logm.chain_floor.copy(),
            "committed_keys": dict(txm.committed_keys),
            "directory": dict(store.directory),
            "blobs": dict(store.blobs._by_handle),
            "blob_seen": [sorted(s) for s in logm._blob_seen],
            "shard_resets": dict(logm.shard_resets),
            "extras": {},
        }
        for name, provider in self.extras_providers.items():
            try:
                cap["extras"][name] = provider()
            except Exception:
                log.exception("checkpoint extras provider %r failed "
                              "(omitted from the image)", name)
        frozen: Dict[str, dict] = {}
        for tname, t in store.tables.items():
            used = t.used_rows.copy()
            if int(used.max()) == 0:
                continue
            frozen[tname] = {
                "slot": t._copy_tree_fn((t.head, t.head_vc)),
                "used": used,
                "slots_ub": t.slots_ub.copy(),
                "max_abs_delta": int(t.max_abs_delta),
                "max_commit_vc": t.max_commit_vc.copy(),
            }
        # rotate onto a fresh segment generation — unless a FAILED
        # attempt already did and never published: its generation is
        # still "everything since the last publish", and rotating again
        # would open n_shards × n_segments new files per failing cycle
        if not self._rotated_unpublished:
            logm.rotate_generation()
            self._rotated_unpublished = True
        cap["floor_seqs"] = logm.seqs.copy()
        self._next_id += 1
        return cap, frozen

    def _scan_chains(self, cap: dict) -> None:
        """Replication txn-group counts at the new floor = counts at the
        previous floor + groups in the (prev, new] sequence window, by
        (origin, commit VC) identity — one bounded scan of the data
        written since the last checkpoint (the first checkpoint scans
        the whole log, once, in the background)."""
        from antidote_tpu.log import shard_segment_paths

        logm = self.log
        chains = cap["prev_chain_floor"].copy()
        for shard in range(cap["n_shards"]):
            lo = int(cap["prev_floor"][shard])
            hi = int(cap["floor_seqs"][shard])
            if hi <= lo:
                continue
            seen: set = set()
            for rec in replay_segments(shard_segment_paths(
                    logm.dir, shard, logm.n_segments)):
                q = rec.get("q")
                if q is None:
                    if lo > 0:
                        continue  # legacy prefix already below prev floor
                elif q <= lo or q > hi:
                    continue
                ident = (int(rec["o"]),
                         tuple(int(x) for x in rec["vc"]))
                if ident in seen:
                    continue
                seen.add(ident)
                chains[shard, int(rec["o"])] += 1
        cap["chain_floor"] = chains

    def _write_atomic(self, cap: dict, frozen: dict) -> Tuple[str, dict]:
        from antidote_tpu.store.handoff import opaque, pack

        tables: Dict[str, dict] = {}
        for tname, fz in frozen.items():
            used = fz["used"]
            u_cap = int(used.max())
            head_cp, head_vc_cp = fz["slot"]
            tables[tname] = {
                "used_rows": used,
                "head": {f: np.asarray(x)[:, :u_cap].copy()
                         for f, x in head_cp.items()},
                "head_vc": np.asarray(head_vc_cp)[:, :u_cap].copy(),
                "slots_ub": fz["slots_ub"][:, :u_cap].copy(),
                "max_abs_delta": fz["max_abs_delta"],
                "max_commit_vc": fz["max_commit_vc"],
            }
        image = {
            "version": 1,
            "id": cap["id"],
            "n_shards": cap["n_shards"],
            "max_dcs": cap["max_dcs"],
            "stamp_vc": cap["stamp_vc"],
            "commit_counter": cap["commit_counter"],
            "floor_seqs": cap["floor_seqs"],
            "chain_floor": cap["chain_floor"],
            "op_ids": cap["op_ids"],
            "shard_resets": {str(k): v
                             for k, v in cap["shard_resets"].items()},
            # opaque(): the two per-key lists are the image's big flat
            # payloads — one C-speed msgpack pass each, not a recursive
            # Python walk per entry (5M dec() calls at 1M keys)
            "committed_keys": opaque([
                [k, b, int(v)] for (k, b), v in cap["committed_keys"].items()
            ]),
            "directory": opaque([
                [key, bucket, tname, int(shard), int(row)]
                for (key, bucket), (tname, shard, row)
                in cap["directory"].items()
            ]),
            "blobs": opaque([[int(h), bytes(d)]
                             for h, d in cap["blobs"].items()]),
            "blob_seen": opaque(cap["blob_seen"]),
            "tables": tables,
            "extras": cap["extras"],
        }
        data = pack(image)
        crc = zlib.crc32(data) & 0xFFFFFFFF
        os.makedirs(self.root, exist_ok=True)
        tmp = os.path.join(self.root, f"tmp.{os.getpid()}.{cap['id']}")
        final = os.path.join(self.root, f"ckpt_{cap['id']}")
        manifest = {
            "id": cap["id"],
            "created_at": time.time(),
            "image_bytes": len(data),
            "image_crc32": crc,
            "n_keys": len(cap["directory"]),
            "n_rows": int(sum(int(t["used_rows"].sum())
                              for t in tables.values())),
            "tables": sorted(tables),
            "commit_counter": cap["commit_counter"],
            "stamp_vc_max": [int(x) for x in cap["stamp_vc"].max(axis=0)],
            "floor_seqs": [int(x) for x in cap["floor_seqs"]],
        }
        try:
            shutil.rmtree(tmp, ignore_errors=True)  # reclaim-ok: stale
            # temp dir from a crashed writer — never a published image
            os.makedirs(tmp)
            img_path = os.path.join(tmp, _IMAGE)
            with open(img_path, "wb") as f:
                _faulted_write(f, data, f"ckpt_{cap['id']}")
                f.flush()
                # image durability rides the group-fsync coordinator —
                # one fsync stream process-wide, coalesced with any
                # commit barriers in flight
                self.log._fsync.submit(
                    [_ImageFsync(f.fileno(), f"ckpt_{cap['id']}")]
                ).wait()
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())  # fsync-ok: manifest must be durable
                # before the rename publishes the image
            _fsync_dir(tmp)
            d = faults.hit("ckpt.rename", key=f"ckpt_{cap['id']}")
            if d is not None:
                if d.action == "delay" and d.arg:
                    time.sleep(float(d.arg))
                elif d.action in ("error", "io_error", "enospc"):
                    raise OSError(errno.EIO,
                                  "injected fault: ckpt.rename")
            os.rename(tmp, final)
            _fsync_dir(self.root)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)  # reclaim-ok: failed
            # attempt's temp dir; the published set is untouched
            raise
        return final, manifest

    def _retire_and_reclaim(self, cap: dict) -> int:
        """Post-publish housekeeping: drop images beyond the retention
        window, then reclaim WAL files wholly below the OLDEST RETAINED
        image's floor — not the newest.  The retention window is the
        recovery safety margin (a corrupt newest image falls back to an
        older one), and that fallback needs the older image's tail still
        on disk.  Both steps are best-effort — a failure here never
        unpublishes the image."""
        reclaim_floors = np.asarray(cap["floor_seqs"], np.int64)
        try:
            published = list_checkpoints(self.root)
            for _id, path in published[:-self.retain]:
                shutil.rmtree(path, ignore_errors=True)  # reclaim-ok:
                # beyond the retention window; newer images cover it
            for name in os.listdir(self.root):
                if name.startswith("tmp."):
                    shutil.rmtree(os.path.join(self.root, name),
                                  ignore_errors=True)  # reclaim-ok:
                    # orphaned temp dir from a crashed/failed writer
            floors = [
                m["floor_seqs"] for _id, p in published[-self.retain:]
                if (m := load_manifest(p)) is not None
                and m.get("floor_seqs") is not None
            ]
            if floors:
                reclaim_floors = np.minimum.reduce(
                    [np.asarray(f, np.int64) for f in floors])
        except OSError:
            log.warning("checkpoint retention sweep failed", exc_info=True)
        try:
            return self.log.reclaim_below(reclaim_floors)
        except Exception:
            log.warning("WAL reclaim below the checkpoint floor failed "
                        "(will retry next checkpoint)", exc_info=True)
            return 0
