"""Key→shard router: native XXH64 with a bit-exact Python fallback.

Replaces the reference's crypto-NIF consistent hash
(/root/reference/src/log_utilities.erl:96-118; SURVEY §2.9 row 3).
Integer keys map directly (``key % n_shards``) exactly like the
reference's direct-int path (:75-79); other keys hash their canonical
msgpack serialization.  The native library batches thousands of keys per
FFI crossing; the Python fallback implements the same XXH64 so replicas
with and without a compiler agree on every shard assignment.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Any, Sequence

import msgpack
import numpy as np

_SRC = Path(__file__).parent / "cpp" / "router.cc"
_SO = Path(__file__).parent / "cpp" / "_router.so"

_lib = None
_lib_tried = False

_M = (1 << 64) - 1
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _load_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 str(_SRC), "-o", str(_SO)],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(str(_SO))
        lib.router_hash64.restype = ctypes.c_uint64
        lib.router_hash64.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64]
        lib.router_shard_batch.restype = None
        lib.router_shard_batch.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


# ---------------------------------------------------------------------------
# pure-Python XXH64 (same spec as router.cc; must agree bit-for-bit)
# ---------------------------------------------------------------------------
def _rotl(x, r):
    return ((x << r) | (x >> (64 - r))) & _M


def _round(acc, inp):
    acc = (acc + inp * _P2) & _M
    return (_rotl(acc, 31) * _P1) & _M


def _merge(acc, val):
    acc ^= _round(0, val)
    return (acc * _P1 + _P4) & _M


def xxh64_py(data: bytes, seed: int = 0) -> int:
    n = len(data)
    p = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M
        v2 = (seed + _P2) & _M
        v3 = seed & _M
        v4 = (seed - _P1) & _M
        while p + 32 <= n:
            v1 = _round(v1, int.from_bytes(data[p:p + 8], "little")); p += 8
            v2 = _round(v2, int.from_bytes(data[p:p + 8], "little")); p += 8
            v3 = _round(v3, int.from_bytes(data[p:p + 8], "little")); p += 8
            v4 = _round(v4, int.from_bytes(data[p:p + 8], "little")); p += 8
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M
        h = _merge(h, v1)
        h = _merge(h, v2)
        h = _merge(h, v3)
        h = _merge(h, v4)
    else:
        h = (seed + _P5) & _M
    h = (h + n) & _M
    while p + 8 <= n:
        h ^= _round(0, int.from_bytes(data[p:p + 8], "little"))
        h = (_rotl(h, 27) * _P1 + _P4) & _M
        p += 8
    if p + 4 <= n:
        h ^= (int.from_bytes(data[p:p + 4], "little") * _P1) & _M
        h = (_rotl(h, 23) * _P2 + _P3) & _M
        p += 4
    while p < n:
        h ^= (data[p] * _P5) & _M
        h = (_rotl(h, 11) * _P1) & _M
        p += 1
    h ^= h >> 33
    h = (h * _P2) & _M
    h ^= h >> 29
    h = (h * _P3) & _M
    h ^= h >> 32
    return h


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def native_available() -> bool:
    return _load_lib() is not None


def key_bytes(key: Any, bucket: str) -> bytes:
    """Canonical serialization of a bound key for hashing."""
    return msgpack.packb((key, bucket), use_bin_type=True)


def hash64(data: bytes, seed: int = 0) -> int:
    lib = _load_lib()
    if lib is not None:
        return int(lib.router_hash64(data, len(data), seed))
    return xxh64_py(data, seed)


def shard_of(key: Any, bucket: str, n_shards: int) -> int:
    if isinstance(key, int) and not isinstance(key, bool):
        return key % n_shards  # reference direct-int path
    return hash64(key_bytes(key, bucket)) % n_shards


def shard_batch(keys: Sequence[Any], buckets: Sequence[str],
                n_shards: int) -> np.ndarray:
    """Vector route: one FFI crossing for the whole batch."""
    n = len(keys)
    out = np.empty(n, np.int64)
    ints = np.empty(n, bool)
    blobs = []
    offsets = [0]
    for i, (k, b) in enumerate(zip(keys, buckets)):
        if isinstance(k, int) and not isinstance(k, bool):
            ints[i] = True
            out[i] = k % n_shards
            continue
        ints[i] = False
        kb = key_bytes(k, b)
        blobs.append(kb)
        offsets.append(offsets[-1] + len(kb))
    if blobs:
        lib = _load_lib()
        m = len(blobs)
        hashed = np.empty(m, np.int64)
        if lib is not None:
            blob = b"".join(blobs)
            lib.router_shard_batch(
                blob, np.asarray(offsets, np.uint64), m, 0, n_shards, hashed
            )
        else:
            for j, kb in enumerate(blobs):
                hashed[j] = xxh64_py(kb) % n_shards
        out[~ints] = hashed
    return out
