from antidote_tpu.store.typed_table import TypedTable

__all__ = ["TypedTable"]
