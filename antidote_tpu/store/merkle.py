"""Merkle-split divergence digests (ISSUE 13).

The flat :func:`~antidote_tpu.store.kv.shard_digest` compares ONE hash
per shard — cheap to compare, but a single corrupted row costs a
full-shard digest to detect and a whole-store re-install to heal.  This
module splits each shard's content hash into a fixed-fanout tree over
``LEAVES`` key buckets:

  * two replicas at EQUAL applied clocks compare roots; on a mismatch
    the checker walks mismatching children level by level —
    ``O(fanout · log n)`` hash comparisons localize the diverged key
    range to one (or a few) leaves;
  * the heal then fetches ONLY those leaves' key states from the owner
    (a range-restricted image fetch) instead of quarantining the whole
    store behind a full re-install.

Leaf digests are pure functions of the CURRENT materialized values
(same canonical encoding as the flat digest), so they are maintained
**incrementally**: a write dirties exactly its key's leaf, and a check
recomputes only the dirty leaves — the steady-state cost of a
divergence sweep tracks the write working set, not the shard size.
The flat digest remains the oracle the unit tests pin the tree against.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Set

import numpy as np

#: leaf buckets per shard and tree fanout (root -> FANOUT nodes ->
#: LEAVES leaves with the defaults: depth 2)
LEAVES = 256
FANOUT = 16


def leaf_of(key, bucket: str, n_leaves: int = LEAVES) -> int:
    """Stable cross-process leaf index of one key (canonical msgpack
    bytes -> sha256 -> bucket)."""
    import msgpack as _mp

    h = hashlib.sha256(_mp.packb([key, bucket], use_bin_type=True,
                                 default=repr)).digest()
    return int.from_bytes(h[:4], "big") % n_leaves


class MerkleIndex:
    """Per-store incremental hash tree over each shard's keys.

    Membership (which keys live in which leaf) and leaf digests are
    maintained lazily: the first :meth:`root` call for a shard builds
    the partition from the directory + cold index in one pass; after
    that, writes dirty single leaves via :meth:`mark` and key
    add/remove flows through :meth:`mark` too (membership is re-derived
    for dirty leaves only).  Cold keys are members like any other —
    recomputing a leaf that contains one faults it in through the
    locked read path (both replicas hash the same VALUES, so a key
    being cold on one side and resident on the other digests
    identically)."""

    def __init__(self, store, n_leaves: int = LEAVES, fanout: int = FANOUT):
        self.store = store
        self.n_leaves = int(n_leaves)
        self.fanout = int(fanout)
        # the walk math assumes a complete tree
        n = self.fanout
        while n < self.n_leaves:
            n *= self.fanout
        assert n == self.n_leaves, \
            f"n_leaves ({n_leaves}) must be a power of fanout ({fanout})"
        #: shard -> list[bytes|None] leaf hashes (None = never computed)
        self._leaves: Dict[int, List[Optional[bytes]]] = {}
        #: shard -> set of dirty leaf indices (None = ALL dirty/unbuilt)
        self._dirty: Dict[int, Optional[Set[int]]] = {}
        #: shard -> leaf -> set of dks (the membership partition)
        self._members: Dict[int, List[Set[tuple]]] = {}

    # -- maintenance hooks ----------------------------------------------
    def mark(self, shard: int, dk) -> None:
        """A key's value (or membership) changed: dirty its leaf."""
        shard = int(shard)
        d = self._dirty.get(shard)
        if d is None:
            return  # tree never built for this shard: first root()
            # builds everything anyway
        leaf = leaf_of(dk[0], dk[1], self.n_leaves)
        d.add(leaf)
        mem = self._members.get(shard)
        if mem is not None:
            # membership may have changed (birth/heal-delete): re-derive
            # the leaf's member set on the next recompute
            mem[leaf] = None  # type: ignore[call-overload]

    def mark_all(self, shard: int) -> None:
        """Out-of-band mutation (install, heal, handoff): rebuild the
        shard's tree from scratch on the next check."""
        shard = int(shard)
        self._leaves.pop(shard, None)
        self._dirty.pop(shard, None)
        self._members.pop(shard, None)

    def rescan(self, shard: int) -> None:
        """Force every leaf to rehash from the LIVE device state on the
        next :meth:`root` (membership kept).  Divergence checks call
        this before the root compare: silent corruption by definition
        bypasses the incremental marks, so detection must re-read the
        data — the tree's win is the O(fanout·log n) COMPARISON walk
        and the leaf-restricted heal, not skipping the hash of rows it
        chose to trust."""
        shard = int(shard)
        d = self._dirty.get(shard)
        if d is not None:
            d.update(range(self.n_leaves))

    # -- (re)computation ------------------------------------------------
    def _shard_keys(self, shard: int):
        store = self.store
        keys = set(store.directory.shard_keys(shard))
        if store.cold is not None:
            keys |= set(store.cold.shard_cold_keys(shard))
        return keys

    def _build_members(self, shard: int) -> List[Set[tuple]]:
        mem: List[Set[tuple]] = [set() for _ in range(self.n_leaves)]
        for dk in self._shard_keys(shard):
            mem[leaf_of(dk[0], dk[1], self.n_leaves)].add(dk)
        return mem

    def _leaf_digest(self, shard: int, dks) -> bytes:
        """Hash one leaf's keys + materialized values at the shard's
        CURRENT applied clock (commit lock held by the caller) — the
        same canonical form as the flat shard digest."""
        import msgpack as _mp

        from antidote_tpu.store.kv import _canon, split_tier

        store = self.store
        objs = []
        for key, bucket in dks:
            ent = store.directory.get((key, bucket))
            if ent is None and store.cold is not None \
                    and store.cold.is_cold((key, bucket)):
                ent = store.cold.fault_in((key, bucket), admit=False)
            if ent is None:
                continue  # removed concurrently
            objs.append((key, split_tier(ent[0])[0], bucket))
        objs.sort(key=lambda o: _mp.packb([o[0], o[2], o[1]],
                                          use_bin_type=True, default=repr))
        h = hashlib.sha256()
        if objs:
            vals = store.read_values(objs, store.applied_vc[shard])
            for (key, tname, bucket), v in zip(objs, vals):
                h.update(_mp.packb([_canon(key), bucket, tname, _canon(v)],
                                   use_bin_type=True, default=repr))
        return h.digest()

    def _refresh(self, shard: int) -> List[bytes]:
        """Bring one shard's leaf hashes current (recompute dirty leaves
        only).  Caller must hold the commit lock."""
        shard = int(shard)
        leaves = self._leaves.get(shard)
        mem = self._members.get(shard)
        dirty = self._dirty.get(shard)
        if leaves is None or mem is None or dirty is None:
            mem = self._build_members(shard)
            self._members[shard] = mem
            leaves = [None] * self.n_leaves
            self._leaves[shard] = leaves
            dirty = set(range(self.n_leaves))
            self._dirty[shard] = dirty
        for leaf in list(dirty):
            if mem[leaf] is None:
                # membership invalidated: re-derive this leaf only
                mem[leaf] = {
                    dk for dk in self._shard_keys(shard)
                    if leaf_of(dk[0], dk[1], self.n_leaves) == leaf
                }
            leaves[leaf] = self._leaf_digest(shard, mem[leaf])
        dirty.clear()
        return leaves  # type: ignore[return-value]

    # -- tree views -----------------------------------------------------
    def _levels(self) -> int:
        n, lv = 1, 0
        while n < self.n_leaves:
            n *= self.fanout
            lv += 1
        return lv

    def node_hash(self, leaves: List[bytes], level: int, index: int) -> bytes:
        """Hash of the tree node at (level, index): level 0 = root;
        level == depth = the leaves themselves (the tree is complete:
        n_leaves is a power of fanout)."""
        depth = self._levels()
        if level >= depth:
            return leaves[index]
        h = hashlib.sha256()
        for child in range(self.fanout):
            h.update(self.node_hash(leaves, level + 1,
                                    index * self.fanout + child))
        return h.digest()

    def root(self, shard: int) -> str:
        """Current root hash (hex) of one shard — includes the applied
        clock the same way the flat digest does, so equal clocks +
        equal state ⇒ equal roots.  Caller holds the commit lock."""
        leaves = self._refresh(shard)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(self.store.applied_vc[int(shard)],
                                      dtype=np.int64).tobytes())
        h.update(self.node_hash(leaves, 0, 0))
        return h.hexdigest()

    def children(self, shard: int, level: int, index: int) -> List[str]:
        """Hex hashes of one node's children — the walk primitive a
        follower compares against its own.  Caller holds the commit
        lock; level counts from 0 (root's children = level 1 nodes)."""
        leaves = self._refresh(shard)
        out = []
        for child in range(self.fanout):
            ci = index * self.fanout + child
            out.append(self.node_hash(leaves, level + 1, ci).hex())
        return out

    def leaf_keys(self, shard: int, leaf: int):
        """The keys currently in one leaf (for the range-restricted
        heal fetch).  Caller holds the commit lock."""
        self._refresh(shard)
        mem = self._members[int(shard)][int(leaf)]
        if mem is None:
            mem = {
                dk for dk in self._shard_keys(int(shard))
                if leaf_of(dk[0], dk[1], self.n_leaves) == int(leaf)
            }
            self._members[int(shard)][int(leaf)] = mem
        return set(mem)

    def depth(self) -> int:
        return self._levels()


def get_merkle(store) -> MerkleIndex:
    """The store's (lazily-built) divergence tree."""
    if store.merkle is None:
        store.merkle = MerkleIndex(store)
    return store.merkle


__all__ = ["MerkleIndex", "get_merkle", "leaf_of", "LEAVES", "FANOUT"]
