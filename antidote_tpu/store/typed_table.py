"""Per-(shard, type) device table: key slots, snapshot versions, op rings.

The tensor re-design of ``materializer_vnode``'s two ETS tables
(/root/reference/src/materializer_vnode.erl:76): ``ops_cache`` becomes a
fixed op ring per key slot, ``snapshot_cache`` a fixed ring of materialized
snapshot versions.  All arrays carry a leading key-slot axis so a batch of
reads/commits is a gather/scatter + one fold launch.

Layout per table (N key slots, V versions, K ring slots, D clock lanes):

  snap[f]     : [N, V, *field_shape]   materialized snapshot fields
  snap_vc     : i32[N, V, D]           snapshot clocks
  snap_seq    : i64[N, V]              insertion sequence (0 = empty)
  ops_a       : i64[N, K, A]           effect payload lanes
  ops_b       : i32[N, K, B]
  ops_vc      : i32[N, K, D]           commit-augmented op clocks
  ops_origin  : i32[N, K]              origin DC lane
  n_ops       : host-mirrored i32[N]   valid ring prefix length

GC policy (replaces op_insert_gc/snapshot_insert_gc,
/root/reference/src/materializer_vnode.erl:513-647): when a key's ring
would overflow, fold the whole ring at the shard's applied VC into a new
snapshot version (evicting the oldest version) and reset the ring.  Folding
only at the applied VC means stored snapshots never contain holes — the
applied VC dominates every ring op by construction.

Reads below the oldest retained coverage are *incomplete*; the caller falls
back to a host-side log replay, mirroring the reference's
``get_from_snapshot_log`` (/root/reference/src/materializer_vnode.erl:415-419).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clock import orddict
from antidote_tpu.clock import vector as vc
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt.base import CRDTType
from antidote_tpu.materializer import fold as fold_mod


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return ((n + buckets[-1] - 1) // buckets[-1]) * buckets[-1]


class TypedTable:
    def __init__(self, ty: CRDTType, cfg: AntidoteConfig, n_rows: int | None = None):
        self.ty = ty
        self.cfg = cfg
        self.n_rows = n_rows or cfg.keys_per_table
        self.used_rows = 0
        self.next_seq = 1
        d, v, k = cfg.max_dcs, cfg.snap_versions, cfg.ops_per_key
        a, b = ty.eff_a_width(cfg), ty.eff_b_width(cfg)
        n = self.n_rows
        spec = ty.state_spec(cfg)
        self.snap = {
            f: jnp.zeros((n, v) + shape, dtype) for f, (shape, dtype) in spec.items()
        }
        self.snap_vc = jnp.zeros((n, v, d), jnp.int32)
        self.snap_seq = jnp.zeros((n, v), jnp.int64)
        self.ops_a = jnp.zeros((n, k, a), jnp.int64)
        self.ops_b = jnp.zeros((n, k, b), jnp.int32)
        self.ops_vc = jnp.zeros((n, k, d), jnp.int32)
        self.ops_origin = jnp.zeros((n, k), jnp.int32)
        self.n_ops = np.zeros((n,), np.int32)  # host-authoritative mirror

    # ------------------------------------------------------------------
    # row allocation / growth
    # ------------------------------------------------------------------
    def alloc_row(self) -> int:
        if self.used_rows == self.n_rows:
            self._grow()
        r = self.used_rows
        self.used_rows += 1
        return r

    def _grow(self):
        new_n = self.n_rows * 2

        def grow(arr):
            pad = [(0, new_n - self.n_rows)] + [(0, 0)] * (arr.ndim - 1)
            return jnp.pad(arr, pad)

        self.snap = {f: grow(x) for f, x in self.snap.items()}
        self.snap_vc = grow(self.snap_vc)
        self.snap_seq = grow(self.snap_seq)
        self.ops_a = grow(self.ops_a)
        self.ops_b = grow(self.ops_b)
        self.ops_vc = grow(self.ops_vc)
        self.ops_origin = grow(self.ops_origin)
        self.n_ops = np.pad(self.n_ops, (0, new_n - self.n_rows))
        self.n_rows = new_n

    # ------------------------------------------------------------------
    # device kernels (jitted per shape bucket)
    # ------------------------------------------------------------------
    @functools.lru_cache(maxsize=None)
    def _append_fn(self):
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def append(ops_a, ops_b, ops_vc_, ops_origin, rows, slots, a, b, v, o):
            # out-of-range rows (padding) are dropped by the scatter
            return (
                ops_a.at[rows, slots].set(a, mode="drop"),
                ops_b.at[rows, slots].set(b, mode="drop"),
                ops_vc_.at[rows, slots].set(v, mode="drop"),
                ops_origin.at[rows, slots].set(o, mode="drop"),
            )

        return append

    @functools.lru_cache(maxsize=None)
    def _read_fn(self):
        ty, cfg = self.ty, self.cfg

        @jax.jit
        def read(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc_, ops_origin,
                 rows, n_ops_rows, read_vcs):
            svc = snap_vc[rows]            # [M, V, D]
            sseq = snap_seq[rows]          # [M, V]
            idx, found = orddict.get_smaller(svc, sseq, read_vcs)
            m = rows.shape[0]
            take = jnp.arange(m)
            base_vc = jnp.where(found[:, None], svc[take, idx], 0)
            base_state = {
                f: jnp.where(
                    found.reshape((m,) + (1,) * (x.ndim - 2)),
                    x[rows][take, idx],
                    jnp.zeros_like(x[rows][take, idx]),
                )
                for f, x in snap.items()
            }
            state, applied = fold_mod.fold_batch(
                ty, cfg, base_state,
                ops_a[rows], ops_b[rows], ops_vc_[rows], ops_origin[rows],
                n_ops_rows, base_vc, read_vcs,
            )
            # complete ⟺ we had a base snapshot, or the key was never GC'd
            # (no stored versions ⇒ the ring still holds the key's whole
            # history and a bottom fold is exact)
            never_gcd = jnp.max(sseq, axis=-1) == 0
            complete = found | never_gcd
            return state, applied, complete

        return read

    @functools.lru_cache(maxsize=None)
    def _gc_fn(self):
        ty, cfg = self.ty, self.cfg

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def gc(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc_, ops_origin,
               rows, n_ops_rows, new_seqs):
            svc = snap_vc[rows]
            sseq = snap_seq[rows]
            m = rows.shape[0]
            take = jnp.arange(m)
            # Fold VC = per-lane max over the ring's valid ops and retained
            # snapshot clocks.  Causal in-order delivery guarantees no op
            # arriving later can be dominated by this merge, so the stored
            # snapshot has no holes.
            k = ops_vc_.shape[1]
            valid = jnp.arange(k)[None, :] < n_ops_rows[:, None]      # [M, K]
            ring_vc = jnp.where(valid[:, :, None], ops_vc_[rows], 0)  # [M, K, D]
            ring_max = jnp.max(ring_vc, axis=1)                       # [M, D]
            snap_valid = sseq > 0                                     # [M, V]
            snap_max = jnp.max(
                jnp.where(snap_valid[:, :, None], svc, 0), axis=1
            )                                                         # [M, D]
            read_vcs = jnp.maximum(ring_max, snap_max)
            idx, found = orddict.get_smaller(svc, sseq, read_vcs)
            base_vc = jnp.where(found[:, None], svc[take, idx], 0)
            base_state = {
                f: jnp.where(
                    found.reshape((m,) + (1,) * (x.ndim - 2)),
                    x[rows][take, idx],
                    jnp.zeros_like(x[rows][take, idx]),
                )
                for f, x in snap.items()
            }
            state, _ = fold_mod.fold_batch(
                ty, cfg, base_state,
                ops_a[rows], ops_b[rows], ops_vc_[rows], ops_origin[rows],
                n_ops_rows, base_vc, read_vcs,
            )
            slot = orddict.insert_slot(sseq)  # oldest version per row
            snap2 = {
                f: x.at[rows, slot].set(state[f], mode="drop")
                for f, x in snap.items()
            }
            snap_vc2 = snap_vc.at[rows, slot].set(read_vcs, mode="drop")
            snap_seq2 = snap_seq.at[rows, slot].set(new_seqs, mode="drop")
            return snap2, snap_vc2, snap_seq2

        return gc

    # ------------------------------------------------------------------
    # host API
    # ------------------------------------------------------------------
    def append(self, rows, eff_a, eff_b, vcs, origins, applied_vc=None):
        """Append a commit-ordered batch of effects.

        ``rows`` i64[M]; ``eff_a`` [M, A]; ``eff_b`` [M, B]; ``vcs`` [M, D];
        ``origins`` [M].  Handles ring overflow by GC-folding full rings
        first (``applied_vc`` is accepted for API compatibility but the GC
        derives its own safe fold VC).
        """
        rows = np.asarray(rows, np.int64)
        m = len(rows)
        if m == 0:
            return
        k = self.cfg.ops_per_key
        # per-op slot = current count + occurrence index of the row in batch
        occ = np.zeros(m, np.int64)
        counts: Dict[int, int] = {}
        for i, r in enumerate(rows):
            c = counts.get(r, 0)
            occ[i] = c
            counts[r] = c + 1
        slots = self.n_ops[rows] + occ
        over = slots >= k
        if over.any():
            # fold the overflowing rows' rings first, then retry
            gc_rows = np.unique(rows[over])
            self.gc(gc_rows)
            slots = self.n_ops[rows] + occ
            if (slots >= k).any():
                raise OverflowError(
                    f"more than {k} ops for one key in a single batch; "
                    f"split the batch (type={self.ty.name})"
                )
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m
        rows_p = np.concatenate([rows, np.full(pad, self.n_rows, np.int64)])
        slots_p = np.concatenate([slots, np.zeros(pad, np.int64)])
        a_p = np.concatenate([eff_a, np.zeros((pad,) + eff_a.shape[1:], np.int64)])
        b_p = np.concatenate([eff_b, np.zeros((pad,) + eff_b.shape[1:], np.int32)])
        v_p = np.concatenate([vcs, np.zeros((pad,) + vcs.shape[1:], np.int32)])
        o_p = np.concatenate([origins, np.zeros(pad, np.int32)])
        self.ops_a, self.ops_b, self.ops_vc, self.ops_origin = self._append_fn()(
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            rows_p, slots_p, a_p, b_p, v_p, o_p,
        )
        np.add.at(self.n_ops, rows, 1)

    def gc(self, rows, applied_vc=None):
        """Fold full rings into a fresh snapshot version and reset them."""
        rows = np.unique(np.asarray(rows, np.int64))
        m = len(rows)
        if m == 0:
            return
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m
        rows_p = np.concatenate([rows, np.full(pad, self.n_rows, np.int64)])
        n_ops_p = np.concatenate([self.n_ops[rows], np.zeros(pad, np.int32)])
        seqs = np.arange(self.next_seq, self.next_seq + m, dtype=np.int64)
        self.next_seq += m
        seqs_p = np.concatenate([seqs, np.zeros(pad, np.int64)])
        self.snap, self.snap_vc, self.snap_seq = self._gc_fn()(
            self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            rows_p, n_ops_p, seqs_p,
        )
        self.n_ops[rows] = 0

    def read(self, rows, read_vcs) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        """Materialize a batch of keys at per-row read VCs.

        Returns host copies: (state fields [M, ...], n_applied [M],
        complete [M]).  Incomplete rows need a log-replay fallback.
        """
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        m = len(rows)
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m
        rows_p = np.concatenate([rows, np.full(pad, 0, np.int64)])
        vcs_p = np.concatenate([read_vcs, np.zeros((pad,) + read_vcs.shape[1:], np.int32)])
        n_ops_p = np.concatenate([self.n_ops[rows], np.zeros(pad, np.int32)])
        state, applied, complete = self._read_fn()(
            self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            rows_p, n_ops_p, vcs_p,
        )
        state = {f: np.asarray(x[:m]) for f, x in state.items()}
        return state, np.asarray(applied[:m]), np.asarray(complete[:m])
