"""Per-type sharded device table: key slots, snapshot versions, op rings.

The tensor re-design of ``materializer_vnode``'s two ETS tables
(/root/reference/src/materializer_vnode.erl:76): ``ops_cache`` becomes a
fixed op ring per key slot, ``snapshot_cache`` a fixed ring of materialized
snapshot versions.  The riak_core ring (16 partitions by default,
/root/reference/config/vars.config:5) becomes a leading shard axis ``P`` on
every array; device kernels are per-shard bodies vmapped over that axis, so
when the arrays are laid out over a ``Mesh(('shard',))`` XLA partitions the
batch across devices with no cross-device traffic on the data plane.

Layout per type (P shards, N key slots, V versions, K ring slots, D lanes):

  snap[f]     : [P, N, V, *field_shape]   materialized snapshot fields
  snap_vc     : i32[P, N, V, D]           snapshot clocks
  snap_seq    : i64[P, N, V]              insertion sequence (0 = empty)
  ops_a       : i64[P, N, K, A]           effect payload lanes
  ops_b       : i32[P, N, K, B]
  ops_vc      : i32[P, N, K, D]           commit-augmented op clocks
  ops_origin  : i32[P, N, K]              origin DC lane
  n_ops       : host-mirrored i32[P, N]   valid ring prefix length

Host API is flat — (shards[M], rows[M], ...) — and is routed into padded
``[P, M']`` per-shard blocks internally.  Padding uses out-of-range indices:
scatters drop them (mode="drop"), gathers clip and the caller masks.

GC policy (replaces op_insert_gc / snapshot_insert_gc,
/root/reference/src/materializer_vnode.erl:513-647): when a key's ring
would overflow, fold the whole ring into a new snapshot version (evicting
the oldest) at a self-derived safe VC — the per-lane max of ring-op and
retained-snapshot clocks.  Causal in-order delivery guarantees no later op
can be dominated by that merge, so stored snapshots never contain holes.

Reads below the oldest retained coverage are flagged *incomplete*; the
caller falls back to a host-side log replay, mirroring the reference's
``get_from_snapshot_log`` (/root/reference/src/materializer_vnode.erl:415-419).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clock import orddict
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt.base import CRDTType
from antidote_tpu.materializer import fold as fold_mod


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return ((n + buckets[-1] - 1) // buckets[-1]) * buckets[-1]


def _shard_head_update_body(ty, cfg):
    """Per-shard write-time fold: apply ring slots [start, end) of each
    touched key onto its *head* state (the eagerly-materialized snapshot at
    the key's full applied history).  This is the write-side analogue of
    the reference pushing committed ops into the materializer at commit
    time (clocksi_vnode:update_materializer,
    /root/reference/src/clocksi_vnode.erl:634-657) — paying the fold once
    per commit so hot reads are pure gathers."""

    def update(head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
               rows, starts, ends):
        def one(h, hvc, a, b, v, o, start, end):
            k = v.shape[0]

            def step(carry, xs):
                state, cvc = carry
                ea, eb, op_vc, origin, slot = xs
                include = (slot >= start) & (slot < end)
                new = ty.apply(cfg, state, ea, eb, op_vc, origin)
                merged = jax.tree.map(
                    lambda n_, o_: jnp.where(include, n_, o_), new, state
                )
                cvc = jnp.where(include, jnp.maximum(cvc, op_vc), cvc)
                return (merged, cvc), None

            (state, cvc), _ = jax.lax.scan(
                step, (h, hvc),
                (a, b, v, o, jnp.arange(k, dtype=jnp.int32)),
            )
            return state, cvc

        n = head_vc.shape[0]
        rc = jnp.minimum(rows, n - 1)  # clip padding for gathers
        h_rows = {f: x[rc] for f, x in head.items()}
        state, cvc = jax.vmap(one)(
            h_rows, head_vc[rc],
            ops_a[rc], ops_b[rc], ops_vc[rc], ops_origin[rc],
            starts, ends,
        )
        # scatter with the UNclipped rows: padding (out-of-range) drops
        head2 = {f: x.at[rows].set(state[f], mode="drop") for f, x in head.items()}
        head_vc2 = head_vc.at[rows].set(cvc, mode="drop")
        return head2, head_vc2

    return update


def _shard_read_latest_body(ty, cfg):
    """Per-shard fast read: gather head rows; a row is *fresh* iff its head
    VC is dominated by the read VC (then head == the exact snapshot).
    Stale rows must take the versioned fold path."""

    def read(head, head_vc, rows, read_vcs):
        hvc = head_vc[rows]
        state = {f: x[rows] for f, x in head.items()}
        fresh = jnp.all(hvc <= read_vcs, axis=-1)
        return state, fresh

    return read


def _shard_read_body(ty, cfg):
    """Per-shard read kernel: operates on one shard's block."""

    def read(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
             rows, n_ops_rows, read_vcs):
        svc = snap_vc[rows]            # [M, V, D]
        sseq = snap_seq[rows]          # [M, V]
        idx, found = orddict.get_smaller(svc, sseq, read_vcs)
        m = rows.shape[0]
        take = jnp.arange(m)
        base_vc = jnp.where(found[:, None], svc[take, idx], 0)
        base_state = {
            f: jnp.where(
                found.reshape((m,) + (1,) * (x.ndim - 2)),
                x[rows][take, idx],
                jnp.zeros_like(x[rows][take, idx]),
            )
            for f, x in snap.items()
        }
        state, applied = fold_mod.fold_batch(
            ty, cfg, base_state,
            ops_a[rows], ops_b[rows], ops_vc[rows], ops_origin[rows],
            n_ops_rows, base_vc, read_vcs,
        )
        # complete ⟺ the key was never GC'd (ring holds its whole history),
        # or the selected base is the NEWEST retained version — the ring
        # only holds ops after the newest version, so folding onto an older
        # version would silently miss the ops GC'd into newer ones.
        never_gcd = jnp.max(sseq, axis=-1) == 0
        newest = jnp.max(sseq, axis=-1)
        picked_newest = found & (sseq[take, idx] == newest)
        complete = picked_newest | never_gcd
        return state, applied, complete

    return read


class TypedTable:
    """Host handle for one CRDT type's sharded device arrays."""

    def __init__(
        self,
        ty: CRDTType,
        cfg: AntidoteConfig,
        n_rows: int | None = None,
        n_shards: int | None = None,
        sharding=None,
    ):
        self.ty = ty
        self.cfg = cfg
        self.n_rows = n_rows or cfg.keys_per_table
        self.n_shards = n_shards or cfg.n_shards
        self.sharding = sharding
        self.used_rows = np.zeros((self.n_shards,), np.int64)
        self.next_seq = 1
        d, v, k = cfg.max_dcs, cfg.snap_versions, cfg.ops_per_key
        a, b = ty.eff_a_width(cfg), ty.eff_b_width(cfg)
        p, n = self.n_shards, self.n_rows
        spec = ty.state_spec(cfg)

        def mk(shape, dtype):
            arr = jnp.zeros(shape, dtype)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            return arr

        self.snap = {
            f: mk((p, n, v) + shape, dtype) for f, (shape, dtype) in spec.items()
        }
        self.snap_vc = mk((p, n, v, d), jnp.int32)
        self.snap_seq = mk((p, n, v), jnp.int64)
        self.ops_a = mk((p, n, k, a), jnp.int64)
        self.ops_b = mk((p, n, k, b), jnp.int32)
        self.ops_vc = mk((p, n, k, d), jnp.int32)
        self.ops_origin = mk((p, n, k), jnp.int32)
        self.n_ops = np.zeros((p, n), np.int32)  # host-authoritative mirror
        # head = eagerly-materialized state at each key's full applied
        # history (folded at append time; reads at VC ≥ head_vc are gathers)
        self.head = {
            f: mk((p, n) + shape, dtype) for f, (shape, dtype) in spec.items()
        }
        self.head_vc = mk((p, n, d), jnp.int32)

    # ------------------------------------------------------------------
    # row allocation / growth
    # ------------------------------------------------------------------
    def alloc_row(self, shard: int) -> int:
        if self.used_rows[shard] == self.n_rows:
            self._grow()
        r = int(self.used_rows[shard])
        self.used_rows[shard] += 1
        return r

    def _grow(self):
        new_n = self.n_rows * 2

        def grow(arr):
            pad = [(0, 0), (0, new_n - self.n_rows)] + [(0, 0)] * (arr.ndim - 2)
            out = jnp.pad(arr, pad)
            if self.sharding is not None:
                out = jax.device_put(out, self.sharding)
            return out

        self.snap = {f: grow(x) for f, x in self.snap.items()}
        self.snap_vc = grow(self.snap_vc)
        self.snap_seq = grow(self.snap_seq)
        self.ops_a = grow(self.ops_a)
        self.ops_b = grow(self.ops_b)
        self.ops_vc = grow(self.ops_vc)
        self.ops_origin = grow(self.ops_origin)
        self.head = {f: grow(x) for f, x in self.head.items()}
        self.head_vc = grow(self.head_vc)
        self.n_ops = np.pad(self.n_ops, ((0, 0), (0, new_n - self.n_rows)))
        self.n_rows = new_n

    # ------------------------------------------------------------------
    # device kernels
    # ------------------------------------------------------------------
    @functools.cached_property
    def _append_fn(self):
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def append(ops_a, ops_b, ops_vc, ops_origin, shards, rows, slots, a, b, v, o):
            # out-of-range indices (padding) are dropped by the scatter
            return (
                ops_a.at[shards, rows, slots].set(a, mode="drop"),
                ops_b.at[shards, rows, slots].set(b, mode="drop"),
                ops_vc.at[shards, rows, slots].set(v, mode="drop"),
                ops_origin.at[shards, rows, slots].set(o, mode="drop"),
            )

        return append

    @functools.cached_property
    def _read_fn(self):
        body = _shard_read_body(self.ty, self.cfg)

        @jax.jit
        def read(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                 rows, n_ops_rows, read_vcs):
            return jax.vmap(body)(
                snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                rows, n_ops_rows, read_vcs,
            )

        return read

    @functools.cached_property
    def _gc_fn(self):
        # GC = copy the head (already the exact fold of the full ring +
        # prior history) into a fresh snapshot version; no fold needed.
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def gc(snap, snap_vc, snap_seq, head, head_vc, rows, new_seqs):
            def per_shard(snap, snap_vc, snap_seq, head, head_vc, rows, seqs):
                from antidote_tpu.clock import orddict

                sseq = snap_seq[rows]
                slot = orddict.insert_slot(sseq)
                snap2 = {
                    f: x.at[rows, slot].set(head[f][rows], mode="drop")
                    for f, x in snap.items()
                }
                snap_vc2 = snap_vc.at[rows, slot].set(head_vc[rows], mode="drop")
                snap_seq2 = snap_seq.at[rows, slot].set(seqs, mode="drop")
                return snap2, snap_vc2, snap_seq2

            return jax.vmap(per_shard)(
                snap, snap_vc, snap_seq, head, head_vc, rows, new_seqs
            )

        return gc

    @functools.cached_property
    def _head_update_fn(self):
        body = _shard_head_update_body(self.ty, self.cfg)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def upd(head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
                rows, starts, ends):
            return jax.vmap(body)(
                head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
                rows, starts, ends,
            )

        return upd

    @functools.cached_property
    def _read_latest_fn(self):
        body = _shard_read_latest_body(self.ty, self.cfg)

        @jax.jit
        def read(head, head_vc, rows, read_vcs):
            return jax.vmap(body)(head, head_vc, rows, read_vcs)

        return read

    # ------------------------------------------------------------------
    # host routing helpers
    # ------------------------------------------------------------------
    def _route(self, shards, rows):
        """Group a flat (shard, row) batch into padded [P, M'] blocks.

        Returns (row_mat i64[P, M'], pos — list of (shard, slot) per input).
        Padding rows use index ``n_rows`` (dropped/clipped on device).
        """
        p = self.n_shards
        mtot = len(shards)
        counts = np.bincount(shards, minlength=p)
        m = _bucket(max(int(counts.max()), 1), self.cfg.batch_buckets)
        order = np.argsort(shards, kind="stable")
        sorted_shards = shards[order]
        starts = np.searchsorted(sorted_shards, np.arange(p))
        slot_in_shard = np.arange(mtot) - starts[sorted_shards]
        row_mat = np.full((p, m), self.n_rows, np.int64)
        row_mat[sorted_shards, slot_in_shard] = rows[order]
        pos = np.empty((mtot, 2), np.int64)
        pos[order, 0] = sorted_shards
        pos[order, 1] = slot_in_shard
        return row_mat, pos

    # ------------------------------------------------------------------
    # host API (flat batches)
    # ------------------------------------------------------------------
    def append(self, shards, rows, eff_a, eff_b, vcs, origins):
        """Append a commit-ordered batch of effects.

        ``shards`` i64[M]; ``rows`` i64[M]; ``eff_a`` [M, A]; ``eff_b``
        [M, B]; ``vcs`` [M, D]; ``origins`` [M].  Ring overflow triggers a
        GC fold of the affected keys first.
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        m = len(rows)
        if m == 0:
            return
        k = self.cfg.ops_per_key
        # occurrence index of each (shard, row) within the batch, vectorized
        combined = shards * np.int64(self.n_rows) + rows
        order = np.argsort(combined, kind="stable")
        sorted_c = combined[order]
        group_start = np.concatenate([[0], np.nonzero(np.diff(sorted_c))[0] + 1])
        group_of = np.cumsum(
            np.concatenate([[0], (np.diff(sorted_c) != 0).astype(np.int64)])
        )
        occ = np.empty(m, np.int64)
        occ[order] = np.arange(m) - group_start[group_of]
        slots = self.n_ops[shards, rows] + occ
        over = slots >= k
        if over.any():
            su, ru = shards[over], rows[over]
            uniq = np.unique(np.stack([su, ru], axis=1), axis=0)
            self.gc(uniq[:, 0], uniq[:, 1])
            slots = self.n_ops[shards, rows] + occ
            if (slots >= k).any():
                raise OverflowError(
                    f"more than {k} ops for one key in a single batch; "
                    f"split the batch (type={self.ty.name})"
                )
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m

        def padi(x, fill):
            return np.concatenate([x, np.full((pad,) + x.shape[1:], fill, x.dtype)])

        self.ops_a, self.ops_b, self.ops_vc, self.ops_origin = self._append_fn(
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            padi(shards, self.n_shards), padi(rows, 0), padi(slots, 0),
            padi(np.asarray(eff_a, np.int64), 0),
            padi(np.asarray(eff_b, np.int32), 0),
            padi(np.asarray(vcs, np.int32), 0),
            padi(np.asarray(origins, np.int32), 0),
        )
        # fold the newly-appended ring slots onto the head state
        uniq_mask = occ == 0
        us, ur = shards[uniq_mask], rows[uniq_mask]
        ucount = np.bincount(
            np.searchsorted(np.sort(combined[uniq_mask]), combined)
        )  # per-unique-pair op count, aligned to sorted unique order
        sort_u = np.argsort(combined[uniq_mask], kind="stable")
        us_s, ur_s = us[sort_u], ur[sort_u]
        starts = self.n_ops[us_s, ur_s].astype(np.int64)
        ends = starts + ucount
        row_mat, pos = self._route(us_s, ur_s)
        start_mat = np.zeros(row_mat.shape, np.int64)
        end_mat = np.zeros(row_mat.shape, np.int64)
        start_mat[pos[:, 0], pos[:, 1]] = starts
        end_mat[pos[:, 0], pos[:, 1]] = ends
        self.head, self.head_vc = self._head_update_fn(
            self.head, self.head_vc,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            row_mat, start_mat, end_mat,
        )
        np.add.at(self.n_ops, (shards, rows), 1)

    def gc(self, shards, rows):
        """Fold the given keys' rings into a fresh snapshot version."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        if len(rows) == 0:
            return
        row_mat, pos = self._route(shards, rows)
        count = len(rows)
        seq_mat = np.zeros(row_mat.shape, np.int64)
        seqs = np.arange(self.next_seq, self.next_seq + count, dtype=np.int64)
        self.next_seq += count
        seq_mat[pos[:, 0], pos[:, 1]] = seqs
        self.snap, self.snap_vc, self.snap_seq = self._gc_fn(
            self.snap, self.snap_vc, self.snap_seq,
            self.head, self.head_vc, row_mat, seq_mat,
        )
        self.n_ops[shards, rows] = 0

    def read_latest(
        self, shards, rows, read_vcs
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """Fast path: gather head states.  Returns (state fields [M, ...],
        fresh [M]).  A row is fresh iff head_vc ≤ its read VC — then the
        head IS the exact snapshot.  Stale rows must use :meth:`read`."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        row_mat, pos = self._route(shards, rows)
        p, mm = row_mat.shape
        vc_mat = np.zeros((p, mm, read_vcs.shape[-1]), np.int32)
        vc_mat[pos[:, 0], pos[:, 1]] = read_vcs
        row_gather = np.minimum(row_mat, self.n_rows - 1)
        state, fresh = self._read_latest_fn(
            self.head, self.head_vc, row_gather, vc_mat
        )
        s, j = pos[:, 0], pos[:, 1]
        out = {f: np.asarray(x)[s, j] for f, x in state.items()}
        return out, np.asarray(fresh)[s, j]

    def read(self, shards, rows, read_vcs) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        """Materialize a flat batch of keys at per-key read VCs.

        Returns host copies (state fields [M, ...], n_applied [M],
        complete [M]).  Incomplete rows need a log-replay fallback.
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        m = len(rows)
        row_mat, pos = self._route(shards, rows)
        p, mm = row_mat.shape
        # clip padding rows for the gather path
        row_gather = np.minimum(row_mat, self.n_rows - 1)
        n_ops_mat = self.n_ops[np.arange(p)[:, None], row_gather]
        n_ops_mat = np.where(row_mat < self.n_rows, n_ops_mat, 0)
        vc_mat = np.zeros((p, mm, read_vcs.shape[-1]), np.int32)
        vc_mat[pos[:, 0], pos[:, 1]] = read_vcs
        state, applied, complete = self._read_fn(
            self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            row_gather, n_ops_mat, vc_mat,
        )
        s, j = pos[:, 0], pos[:, 1]
        out = {f: np.asarray(x)[s, j] for f, x in state.items()}
        return out, np.asarray(applied)[s, j], np.asarray(complete)[s, j]
