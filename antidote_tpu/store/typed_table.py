"""Per-type sharded device table: key slots, snapshot versions, op rings.

The tensor re-design of ``materializer_vnode``'s two ETS tables
(/root/reference/src/materializer_vnode.erl:76): ``ops_cache`` becomes a
fixed op ring per key slot, ``snapshot_cache`` a fixed ring of materialized
snapshot versions.  The riak_core ring (16 partitions by default,
/root/reference/config/vars.config:5) becomes a leading shard axis ``P`` on
every array; device kernels are per-shard bodies vmapped over that axis, so
when the arrays are laid out over a ``Mesh(('shard',))`` XLA partitions the
batch across devices with no cross-device traffic on the data plane.

Layout per type (P shards, N key slots, V versions, K ring slots, D lanes):

  snap[f]     : [P, N, V, *field_shape]   materialized snapshot fields
  snap_vc     : i32[P, N, V, D]           snapshot clocks
  snap_seq    : i64[P, N, V]              insertion sequence (0 = empty)
  ops_a       : i64[P, N, K, A]           effect payload lanes
  ops_b       : i32[P, N, K, B]
  ops_vc      : i32[P, N, K, D]           commit-augmented op clocks
  ops_origin  : i32[P, N, K]              origin DC lane
  n_ops       : host-mirrored i32[P, N]   valid ring prefix length

Host API is flat — (shards[M], rows[M], ...) — and is routed into padded
``[P, M']`` per-shard blocks internally.  Padding uses out-of-range indices:
scatters drop them (mode="drop"), gathers clip and the caller masks.

GC policy (replaces op_insert_gc / snapshot_insert_gc,
/root/reference/src/materializer_vnode.erl:513-647): when a key's ring
would overflow, fold the whole ring into a new snapshot version (evicting
the oldest) at a self-derived safe VC — the per-lane max of ring-op and
retained-snapshot clocks.  Causal in-order delivery guarantees no later op
can be dominated by that merge, so stored snapshots never contain holes.

Reads below the oldest retained coverage are flagged *incomplete*; the
caller falls back to a host-side log replay, mirroring the reference's
``get_from_snapshot_log`` (/root/reference/src/materializer_vnode.erl:415-419).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from antidote_tpu.clock import orddict
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt.base import CRDTType
from antidote_tpu.materializer import fold as fold_mod
from antidote_tpu.materializer import longlog


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return ((n + buckets[-1] - 1) // buckets[-1]) * buckets[-1]


def _shard_head_update_body(ty, cfg, window: int = 0):
    """Per-shard write-time fold: apply ring slots [start, end) of each
    touched key onto its *head* state (the eagerly-materialized snapshot at
    the key's full applied history).  This is the write-side analogue of
    the reference pushing committed ops into the materializer at commit
    time (clocksi_vnode:update_materializer,
    /root/reference/src/clocksi_vnode.erl:634-657) — paying the fold once
    per commit so hot reads are pure gathers.

    ``window`` > 0 scans only a ``window``-slot dynamic slice at each
    key's start instead of the whole ring — a 1-op commit folds 1 slot,
    not ops_per_key (the write-amplification fix for small commits)."""

    def update(head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
               rows, starts, ends):
        def one(h, hvc, a, b, v, o, start, end):
            k = v.shape[0]
            if 0 < window < k:
                # clamped slice keeps [start, start+window) in range; the
                # include mask re-anchors to the true [start, end) span
                s0 = jnp.clip(start, 0, k - window)
                a = jax.lax.dynamic_slice_in_dim(a, s0, window, 0)
                b = jax.lax.dynamic_slice_in_dim(b, s0, window, 0)
                v = jax.lax.dynamic_slice_in_dim(v, s0, window, 0)
                o = jax.lax.dynamic_slice_in_dim(o, s0, window, 0)
                slots = s0 + jnp.arange(window, dtype=jnp.int64)
            else:
                slots = jnp.arange(k, dtype=jnp.int64)

            def step(carry, xs):
                state, cvc = carry
                ea, eb, op_vc, origin, slot = xs
                include = (slot >= start) & (slot < end)
                new = ty.apply(cfg, state, ea, eb, op_vc, origin)
                merged = jax.tree.map(
                    lambda n_, o_: jnp.where(include, n_, o_), new, state
                )
                cvc = jnp.where(include, jnp.maximum(cvc, op_vc), cvc)
                return (merged, cvc), None

            (state, cvc), _ = jax.lax.scan(
                step, (h, hvc), (a, b, v, o, slots),
            )
            return state, cvc

        n = head_vc.shape[0]
        rc = jnp.minimum(rows, n - 1)  # clip padding for gathers
        h_rows = {f: x[rc] for f, x in head.items()}
        state, cvc = jax.vmap(one)(
            h_rows, head_vc[rc],
            ops_a[rc], ops_b[rc], ops_vc[rc], ops_origin[rc],
            starts, ends,
        )
        # scatter with the UNclipped rows: padding (out-of-range) drops
        head2 = {f: x.at[rows].set(state[f], mode="drop") for f, x in head.items()}
        head_vc2 = head_vc.at[rows].set(cvc, mode="drop")
        return head2, head_vc2

    return update


def _shard_read_latest_body(ty, cfg):
    """Per-shard fast read: gather head rows; a row is *fresh* iff its head
    VC is dominated by the read VC (then head == the exact snapshot).
    Stale rows must take the versioned fold path."""

    def read(head, head_vc, rows, read_vcs):
        hvc = head_vc[rows]
        state = {f: x[rows] for f, x in head.items()}
        fresh = jnp.all(hvc <= read_vcs, axis=-1)
        return state, fresh

    return read


def _shard_base_select_body(ty, cfg):
    """Per-shard snapshot-version selection: the newest retained version
    dominated by each read VC becomes the fold base (vector_orddict
    get_smaller, /root/reference/src/vector_orddict.erl:74-87)."""

    def select(snap, snap_vc, snap_seq, rows, read_vcs):
        svc = snap_vc[rows]            # [M, V, D]
        sseq = snap_seq[rows]          # [M, V]
        idx, found = orddict.get_smaller(svc, sseq, read_vcs)
        m = rows.shape[0]
        take = jnp.arange(m)
        base_vc = jnp.where(found[:, None], svc[take, idx], 0)
        base_state = {
            f: jnp.where(
                found.reshape((m,) + (1,) * (x.ndim - 2)),
                x[rows][take, idx],
                jnp.zeros_like(x[rows][take, idx]),
            )
            for f, x in snap.items()
        }
        # complete ⟺ the key was never GC'd (ring holds its whole history),
        # or the selected base is the NEWEST retained version — the ring
        # only holds ops after the newest version, so folding onto an older
        # version would silently miss the ops GC'd into newer ones.
        never_gcd = jnp.max(sseq, axis=-1) == 0
        newest = jnp.max(sseq, axis=-1)
        picked_newest = found & (sseq[take, idx] == newest)
        complete = picked_newest | never_gcd
        return base_state, base_vc, complete

    return select


def _shard_read_body(ty, cfg):
    """Per-shard read kernel: operates on one shard's block."""

    select = _shard_base_select_body(ty, cfg)

    def read(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
             rows, n_ops_rows, read_vcs):
        base_state, base_vc, complete = select(
            snap, snap_vc, snap_seq, rows, read_vcs
        )
        state, applied = fold_mod.fold_batch(
            ty, cfg, base_state,
            ops_a[rows], ops_b[rows], ops_vc[rows], ops_origin[rows],
            n_ops_rows, base_vc, read_vcs,
        )
        return state, applied, complete

    return read


class TypedTable:
    """Host handle for one CRDT type's sharded device arrays."""

    def __init__(
        self,
        ty: CRDTType,
        cfg: AntidoteConfig,
        n_rows: int | None = None,
        n_shards: int | None = None,
        sharding=None,
        metrics=None,
    ):
        self.ty = ty
        self.cfg = cfg
        self.metrics = metrics
        #: per-strategy serving-fold dispatch counts (host tallies; the
        #: node status' materializer block and the
        #: antidote_fold_dispatch_total metric read these)
        self.fold_dispatches: Dict[str, int] = {}
        self.n_rows = n_rows or cfg.keys_per_table
        self.n_shards = n_shards or cfg.n_shards
        self.sharding = sharding
        self.used_rows = np.zeros((self.n_shards,), np.int64)
        #: per-shard reusable rows freed by the cold tier's guarded evict
        #: (store/coldtier.py) — ``alloc_row`` pops here before advancing
        #: the high-water mark, which is what keeps device residency
        #: BOUNDED under a beyond-RAM keyspace instead of growing the
        #: table forever.  ``used_rows`` stays the row-extent high-water
        #: mark (freed rows sit below it holding zeros).
        self.free_rows: Dict[int, list] = {}
        self.next_seq = 1
        self._resolved_fns: Dict[bool, Any] = {}
        self._resolved_flat_fns: Dict[bool, Any] = {}
        self._head_update_fns: Dict[int, Any] = {}
        # host-tracked bound on |eff_a lane 0| — gates the i32 Pallas
        # counter-fold dispatch without any device readback (the r1 advisor
        # flagged the per-call jnp.abs().max() guard as a blocking sync)
        self.max_abs_delta = 0
        # host-tracked entry-wise max over all appended commit VCs: a read
        # VC dominating this makes EVERY row fresh, so the serving read can
        # skip the versioned fold without any device round trip (the
        # common read-at-current-VC case — the reference's reads also take
        # the cached-snapshot fast path when nothing concurrent is
        # prepared, /root/reference/src/materializer_vnode.erl:382-413)
        self.max_commit_vc = np.zeros((cfg.max_dcs,), np.int32)
        d, v, k = cfg.max_dcs, cfg.snap_versions, cfg.ops_per_key
        a, b = ty.eff_a_width(cfg), ty.eff_b_width(cfg)
        p, n = self.n_shards, self.n_rows
        spec = ty.state_spec(cfg)

        def mk(shape, dtype):
            arr = jnp.zeros(shape, dtype)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            return arr

        self.snap = {
            f: mk((p, n, v) + shape, dtype) for f, (shape, dtype) in spec.items()
        }
        self.snap_vc = mk((p, n, v, d), jnp.int32)
        self.snap_seq = mk((p, n, v), jnp.int64)
        self.ops_a = mk((p, n, k, a), jnp.int64)
        self.ops_b = mk((p, n, k, b), jnp.int32)
        self.ops_vc = mk((p, n, k, d), jnp.int32)
        self.ops_origin = mk((p, n, k), jnp.int32)
        self.n_ops = np.zeros((p, n), np.int32)  # host-authoritative mirror
        # host-side conservative bound on per-key used element slots —
        # drives the overflow escape hatch (KVStore._promote_key): only
        # ever over-counts, reset to the exact count at promotion
        self.slots_ub = np.zeros((p, n), np.int32)
        # head = eagerly-materialized state at each key's full applied
        # history (folded at append time; reads at VC ≥ head_vc are gathers)
        self.head = {
            f: mk((p, n) + shape, dtype) for f, (shape, dtype) in spec.items()
        }
        self.head_vc = mk((p, n, d), jnp.int32)
        # published serving epochs: frozen copies of (head, head_vc) plus
        # the max-commit-VC cap at publish time — the read-while-write
        # double buffer (r4 VERDICT item 2).  Reads pinned at a VC ≤ cap
        # serve from the frozen copy as pure gathers while the live head
        # absorbs writes; see :meth:`publish_epoch` for the correctness
        # contract.  LRU-retained (an epoch a pinned snapshot still reads
        # stays alive; at most ``_EPOCH_CAP`` kept).
        self.epochs: list = []
        self._epoch_uses = 0
        #: serves that missed both gather fast paths (epoch publication
        #: is pointless while every read is provably fresh — publishers
        #: key off this)
        self.slow_serves = 0
        # --- serving-epoch double buffer (ISSUE 5 lock-split reads) ----
        # Two alternating frozen (head, head_vc) snapshots that the wire
        # server's lock-free read stage gathers from.  Unlike ``epochs``
        # (whole-head jnp.copy per publish), these are maintained
        # INCREMENTALLY: the publish scatters only the rows appended
        # since the spare buffer's freeze into the DONATED spare — so
        # publish cost scales with the write working set, not table size
        # (the satellite "bound publish_epoch cost per tick").
        self._serving = [None, None]
        self._serving_cur = 0
        #: (shard, row) pairs appended since the current / spare slot's
        #: freeze; None = unbounded (overflow or invalidation) — the next
        #: freeze must full-copy
        self._serving_dirty: "set | None" = set()
        self._serving_spare_dirty: "set | None" = None
        #: called (no args) whenever an out-of-band mutation invalidates
        #: the frozen buffers — the KVStore points this at its
        #: serving-epoch drop so stale store-wide epochs die with them
        self.on_serving_invalidate = None
        self._serving_conservative = False
        self._freeze_scatter_fns: Dict[int, Any] = {}
        #: (shard, row) pairs written since the last CHECKPOINT capture —
        #: the incremental-chain stamp's dirty window (independent of the
        #: serving-freeze windows above, which publishes consume on their
        #: own cadence).  None = untracked (overflow past the cap or an
        #: out-of-band mutation): the next stamp must be a full rebase.
        self._ckpt_dirty: "set | None" = set()

    #: checkpoint dirty windows larger than this stop tracking: a delta
    #: link that would carry most of the table has no cost advantage
    #: over a rebase, and the set itself must stay bounded
    _CKPT_DIRTY_CAP = 262144

    def take_ckpt_dirty(self) -> "set | None":
        """Consume the checkpoint dirty window (called under the commit
        lock by the stamp capture): returns the written (shard, row) set
        since the previous capture, or None when a rebase is required;
        the window restarts empty either way."""
        out = self._ckpt_dirty
        self._ckpt_dirty = set()
        return out

    # ------------------------------------------------------------------
    # serving-epoch double buffer (lock-free wire reads)
    # ------------------------------------------------------------------
    #: dirty sets past this size stop tracking rows; the next freeze
    #: full-copies (a scatter of 10k+ rows stops beating the copy)
    _SERVING_DIRTY_CAP = 8192

    def note_serving_touch(self, shards, rows) -> None:
        """Record appended rows for the incremental serving freeze AND
        the incremental checkpoint stamp (separate windows, separate
        consumers)."""
        pairs = list(zip(shards.tolist(), rows.tolist()))
        for attr in ("_serving_dirty", "_serving_spare_dirty"):
            s = getattr(self, attr)
            if s is None:
                continue
            s.update(pairs)
            if len(s) > self._SERVING_DIRTY_CAP:
                setattr(self, attr, None)
        ck = self._ckpt_dirty
        if ck is not None:
            ck.update(pairs)
            if len(ck) > self._CKPT_DIRTY_CAP:
                self._ckpt_dirty = None

    def serving_slot(self):
        """The current frozen serving buffer (or None before any freeze)."""
        return self._serving[self._serving_cur]

    def serving_spare(self):
        """The slot the NEXT freeze would donate — publishers check it
        against the live epoch's buffers (donating a buffer the current
        epoch still gathers from would delete it under a reader)."""
        return self._serving[1 - self._serving_cur]

    def serving_dirty(self) -> bool:
        cur = self._serving[self._serving_cur]
        return cur is None or self._serving_dirty is None or bool(
            self._serving_dirty)

    def invalidate_serving(self) -> None:
        """Drop both frozen buffers after any out-of-band table mutation
        (row growth, handoff install)."""
        self._serving = [None, None]
        self._serving_dirty = set()
        self._serving_spare_dirty = None
        #: the out-of-band mutation isn't row-tracked: the next freeze
        #: must report its write-set as UNKNOWN (touched=None) so cache
        #: entries cannot revalidate across it
        self._serving_conservative = True
        # same for the checkpoint window: a handoff install / promotion
        # moved rows the window didn't see — the next stamp must rebase
        self._ckpt_dirty = None
        cb = self.on_serving_invalidate
        if cb is not None:
            cb()

    def _freeze_scatter_for(self, bucket: int):
        """Jitted incremental freeze: donate the spare buffer, scatter
        the dirty rows' live head state over it.  One compile per
        padded-batch bucket."""
        fn = self._freeze_scatter_fns.get(bucket)
        if fn is None:
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def fn(sp_head, sp_vc, head, head_vc, ss, rr):
                out = {
                    f: x.at[ss, rr].set(head[f][ss, rr], mode="drop")
                    for f, x in sp_head.items()
                }
                return out, sp_vc.at[ss, rr].set(head_vc[ss, rr],
                                                 mode="drop")

            self._freeze_scatter_fns[bucket] = fn
        return fn

    def _freeze_scatter_shard_for(self, bucket: int):
        """ROUTED incremental freeze for mesh-placed tables (ISSUE 10):
        the dirty rows arrive as a per-shard padded row matrix
        ``[P, M']`` (padding = n_rows → gather clips, scatter drops), so
        each device scatters only its OWN shards' rows into its local
        slice of the donated spare — a clean shard's device slice is
        untouched, and one hot shard's write burst republishes exactly
        its own slice.  One compile per padded-per-shard bucket."""
        fn = self._freeze_scatter_fns.get(("shard", bucket))
        if fn is None:
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def fn(sp_head, sp_vc, head, head_vc, row_mat):
                sidx = jnp.arange(row_mat.shape[0])[:, None]
                out = {
                    f: x.at[sidx, row_mat].set(head[f][sidx, row_mat],
                                               mode="drop")
                    for f, x in sp_head.items()
                }
                return out, sp_vc.at[sidx, row_mat].set(
                    head_vc[sidx, row_mat], mode="drop")

            self._freeze_scatter_fns[("shard", bucket)] = fn
        return fn

    def freeze_serving(self, can_donate: bool, force_copy: bool = False):
        """Freeze the live head into the spare serving slot and make it
        current.  Returns (slot, mode, touched, rows, shard_rows): mode
        "scatter" (incremental — ``rows`` rows re-frozen) or "copy"
        (full).  ``touched`` is the frozenset of rows WRITTEN since the
        previous publish (one window — the snapshot cache's validity
        set; the scatter set itself spans two windows, one per buffer
        slot), or None when unknown (untracked overflow / after an
        out-of-band invalidation).  ``shard_rows`` maps shard → rows
        re-frozen in that shard's slice (the mesh plane's per-shard
        publish observable; tracked only for mesh-placed tables), or
        None — a full copy (every slice rebuilt) or an untracked
        single-chip scatter.  Returns None when the freeze must be
        DEFERRED (the
        spare may still be read by a pinned epoch and cannot be
        donated).  ``force_copy`` rebuilds the slot from scratch instead
        of donating — required when the spare is still referenced by the
        LIVE epoch (a partial publish left it there; waiting can never
        free it).

        Caller must hold the commit lock (no concurrent appends)."""
        spare_i = 1 - self._serving_cur
        spare = self._serving[spare_i]
        dirty = self._serving_spare_dirty
        if force_copy or spare is None or dirty is None:
            frozen = self._copy_tree_fn((self.head, self.head_vc))
            mode, rows, shard_rows = "copy", self.n_shards * self.n_rows, None
        elif not can_donate:
            return None
        else:
            pairs = sorted(dirty)
            m = len(pairs)
            shard_rows = None
            if self.sharding is not None:
                # per-shard counts are only consumed by the mesh
                # publisher — single-chip publishes skip the loop
                shard_rows = {}
                for s, _ in pairs:
                    shard_rows[int(s)] = shard_rows.get(int(s), 0) + 1
                # mesh-placed table: route the dirty rows per shard so
                # each device scatters only its own slice — a clean
                # shard's device slice is untouched (ISSUE 10).  Same
                # n_rows-padded [P, M'] layout the epoch gather uses.
                row_mat, _pos = self._route(
                    np.asarray([p[0] for p in pairs], np.int64),
                    np.asarray([p[1] for p in pairs], np.int64),
                )
                fn = self._freeze_scatter_shard_for(row_mat.shape[1])
                frozen = fn(spare["head"], spare["head_vc"],
                            self.head, self.head_vc, row_mat)
            else:
                mb = _bucket(max(m, 1), self.cfg.batch_buckets)
                ss = np.full(mb, self.n_shards, np.int64)
                rr = np.zeros(mb, np.int64)
                ss[:m] = [p[0] for p in pairs]
                rr[:m] = [p[1] for p in pairs]
                # padding uses shard index P (out of range): the scatter
                # drops it, and the matching gather clips harmlessly
                fn = self._freeze_scatter_for(mb)
                frozen = fn(spare["head"], spare["head_vc"],
                            self.head, self.head_vc, ss, rr)
            mode, rows = "scatter", m
        slot = {"head": frozen[0], "head_vc": frozen[1],
                "cap": self.max_commit_vc.copy()}
        if self._serving_conservative or self._serving_dirty is None:
            touched = None
            self._serving_conservative = False
        else:
            touched = frozenset(self._serving_dirty)
        self._serving[spare_i] = slot
        self._serving_cur = spare_i
        self._serving_spare_dirty = self._serving_dirty
        self._serving_dirty = set()
        return slot, mode, touched, rows, shard_rows

    # ------------------------------------------------------------------
    # row allocation / growth
    # ------------------------------------------------------------------
    def alloc_row(self, shard: int) -> int:
        free = self.free_rows.get(shard)
        if free:
            # evicted row reuse: the guarded evict zeroed the row's whole
            # device state, so the new occupant starts from bottom exactly
            # like a fresh row (the evictor also marked the row touched +
            # epoch-promoted, so no frozen buffer serves stale bytes)
            return free.pop()
        if self.used_rows[shard] == self.n_rows:
            self._grow()
        r = int(self.used_rows[shard])
        self.used_rows[shard] += 1
        return r

    def resident_rows(self) -> int:
        """Device rows currently holding key state: the allocation
        high-water mark minus the freed (evicted, reusable) rows — the
        quantity the cold tier's ``--resident-rows`` budget bounds."""
        return int(self.used_rows.sum()) - sum(
            len(v) for v in self.free_rows.values())

    @functools.cached_property
    def _evict_clear_fn(self):
        """One-launch guarded row clear (cold-tier evict): zero every
        device array at the given (shard, row) pairs.  Donated in place;
        padding uses shard index P (scatter drops)."""
        @functools.partial(jax.jit, donate_argnums=(0,))
        def fn(tree, ss, rr):
            return jax.tree.map(
                lambda x: x.at[ss, rr].set(
                    jnp.zeros(x.shape[2:], x.dtype), mode="drop"),
                tree,
            )

        return fn

    def evict_rows(self, shards, rows) -> None:
        """The GUARDED device-buffer drop of the cold tier (tools/lint.py
        enforces that nothing outside store/coldtier.py calls this
        without an ``# evict-ok:`` note): clear the rows' whole device
        state — head, snapshot versions, op ring — and push them onto the
        per-shard free lists for reuse.  The CALLER owns the correctness
        obligations: the rows' state must be covered by a retained
        checkpoint sidecar, the owning keys unbound from the directory,
        and every live serving epoch told to fall back for them."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        m = len(rows)
        if m == 0:
            return
        mb = _bucket(m, self.cfg.batch_buckets)
        ss = np.full(mb, self.n_shards, np.int64)
        rr = np.zeros(mb, np.int64)
        ss[:m] = shards
        rr[:m] = rows
        tree = {
            "snap": self.snap, "head": self.head,
            "snap_vc": self.snap_vc, "snap_seq": self.snap_seq,
            "ops_a": self.ops_a, "ops_b": self.ops_b,
            "ops_vc": self.ops_vc, "ops_origin": self.ops_origin,
            "head_vc": self.head_vc,
        }
        tree = self._evict_clear_fn(tree, ss, rr)
        self.snap, self.head = tree["snap"], tree["head"]
        self.snap_vc, self.snap_seq = tree["snap_vc"], tree["snap_seq"]
        self.ops_a, self.ops_b = tree["ops_a"], tree["ops_b"]
        self.ops_vc, self.ops_origin = tree["ops_vc"], tree["ops_origin"]
        self.head_vc = tree["head_vc"]
        self.n_ops[shards, rows] = 0
        self.slots_ub[shards, rows] = 0
        for s, r in zip(shards.tolist(), rows.tolist()):
            self.free_rows.setdefault(s, []).append(int(r))
        # the cleared rows must not serve from any frozen buffer: the
        # next publish re-freezes them (callers additionally mark the
        # evicted keys promoted on live epochs for the interim)
        self.note_serving_touch(shards, rows)
        # older whole-head epoch copies (the VC-pinned ladder) still hold
        # the evicted bytes; they'd serve them for the row's NEXT tenant
        self.epochs.clear()

    @functools.cached_property
    def _cold_install_fn(self):
        """One-launch cold fault-in / range-heal row install: set the
        head fields + head_vc at (shard, row) pairs and seed ONE snapshot
        version from the installed head (same discipline as
        checkpoint.install_image: versioned reads at clocks ≥ head_vc
        fold the empty ring on this base exactly; reads below surface the
        compaction horizon instead of a silently wrong value)."""
        @functools.partial(jax.jit, donate_argnums=(0,))
        def fn(tree, ss, rr, head_rows, hvc_rows, seqs):
            out = dict(tree)
            out["head"] = {
                f: x.at[ss, rr].set(head_rows[f], mode="drop")
                for f, x in tree["head"].items()
            }
            out["snap"] = {
                f: x.at[ss, rr, 0].set(head_rows[f], mode="drop")
                for f, x in tree["snap"].items()
            }
            out["head_vc"] = tree["head_vc"].at[ss, rr].set(
                hvc_rows, mode="drop")
            out["snap_vc"] = tree["snap_vc"].at[ss, rr, 0].set(
                hvc_rows, mode="drop")
            out["snap_seq"] = tree["snap_seq"].at[ss, rr, 0].set(
                seqs, mode="drop")
            return out

        return fn

    def install_rows(self, shards, rows, head_rows, head_vc_rows) -> None:
        """Install per-row head states (cold-tier fault-in / Merkle range
        heal).  ``head_rows`` maps field -> [M, *field_shape] host
        arrays; the rows must be freshly-allocated or evict-cleared (the
        ring is empty, so the seeded snapshot version is the row's entire
        retained history)."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        m = len(rows)
        if m == 0:
            return
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m
        ss = np.concatenate([shards, np.full(pad, self.n_shards, np.int64)])
        rr = np.concatenate([rows, np.zeros(pad, np.int64)])
        hr = {}
        for f, x in self.head.items():
            src = np.asarray(head_rows[f])
            buf = np.zeros((mb,) + x.shape[2:], np.dtype(x.dtype))
            buf[:m] = src
            hr[f] = buf
        hvc = np.zeros((mb, self.head_vc.shape[-1]), np.int32)
        hvc[:m] = np.asarray(head_vc_rows, np.int32)
        seqs = np.zeros(mb, np.int64)
        seqs[:m] = np.arange(self.next_seq, self.next_seq + m)
        self.next_seq += m
        tree = {
            "snap": self.snap, "head": self.head,
            "snap_vc": self.snap_vc, "snap_seq": self.snap_seq,
            "head_vc": self.head_vc,
        }
        tree = self._cold_install_fn(tree, ss, rr, hr, hvc, seqs)
        self.snap, self.head = tree["snap"], tree["head"]
        self.snap_vc, self.snap_seq = tree["snap_vc"], tree["snap_seq"]
        self.head_vc = tree["head_vc"]
        self.n_ops[shards, rows] = 0
        np.maximum(self.max_commit_vc,
                   np.asarray(head_vc_rows, np.int32).max(axis=0)
                   if m else self.max_commit_vc,
                   out=self.max_commit_vc)
        self.note_serving_touch(shards, rows)
        self.epochs.clear()

    @functools.cached_property
    def _gather_rows_fn(self):
        """Dispatch-only gather of (head, head_vc) rows — the delta
        checkpoint's capture primitive: launched under the commit-lock
        barrier, materialized outside it."""
        @jax.jit
        def fn(head, head_vc, ss, rr):
            return ({f: x[ss, rr] for f, x in head.items()},
                    head_vc[ss, rr])

        return fn

    def gather_rows_dispatch(self, shards, rows):
        """Launch a (head, head_vc) gather for the given rows; returns
        DEVICE handles padded to a batch bucket (the caller slices to
        the true length after materializing off the lock — padding
        keeps each delta stamp from minting a fresh XLA trace for its
        particular dirty-row count)."""
        m = len(rows)
        mb = _bucket(max(m, 1), self.cfg.batch_buckets)
        ss = np.zeros(mb, np.int64)
        rr = np.zeros(mb, np.int64)
        ss[:m] = np.minimum(np.asarray(shards, np.int64),
                            self.n_shards - 1)
        rr[:m] = np.minimum(np.asarray(rows, np.int64), self.n_rows - 1)
        return self._gather_rows_fn(self.head, self.head_vc, ss, rr)

    def _grow(self):
        new_n = self.n_rows * 2

        def grow(arr):
            pad = [(0, 0), (0, new_n - self.n_rows)] + [(0, 0)] * (arr.ndim - 2)
            out = jnp.pad(arr, pad)
            if self.sharding is not None:
                out = jax.device_put(out, self.sharding)
            return out

        self.snap = {f: grow(x) for f, x in self.snap.items()}
        self.snap_vc = grow(self.snap_vc)
        self.snap_seq = grow(self.snap_seq)
        self.ops_a = grow(self.ops_a)
        self.ops_b = grow(self.ops_b)
        self.ops_vc = grow(self.ops_vc)
        self.ops_origin = grow(self.ops_origin)
        self.head = {f: grow(x) for f, x in self.head.items()}
        self.head_vc = grow(self.head_vc)
        self.n_ops = np.pad(self.n_ops, ((0, 0), (0, new_n - self.n_rows)))
        self.slots_ub = np.pad(self.slots_ub, ((0, 0), (0, new_n - self.n_rows)))
        self.n_rows = new_n
        # epoch copies still have the old row extent — row indices past it
        # would gather-clip onto the wrong key.  The CHECKPOINT dirty
        # window survives: growth moves no row and changes no content, so
        # the incremental stamp's tracking stays exact (new rows enter it
        # through their first touch)
        ck = self._ckpt_dirty
        self.invalidate_epochs()
        self._ckpt_dirty = ck

    # ------------------------------------------------------------------
    # serving epochs (read-while-write double buffer)
    # ------------------------------------------------------------------
    _EPOCH_CAP = 2

    @functools.cached_property
    def _copy_tree_fn(self):
        return jax.jit(lambda tree: jax.tree.map(jnp.copy, tree))

    def publish_epoch(self) -> None:
        """Freeze the current head as a serving epoch.

        Correctness contract (the reason an epoch gather is an *exact*
        snapshot read): ``cap`` is the entry-wise max commit VC this table
        has absorbed at publish time.  Appends are causally gated — an op
        from origin ``o`` carries a commit timestamp on lane ``o`` strictly
        above every lane-``o`` value previously appended (local sequencer
        monotonicity; remote chains apply in op-id order behind the causal
        gate, so a cross-origin snapshot entry can never outrun its
        origin's applied ops).  Hence any op appended AFTER publish is
        invisible at any read VC ``R ≤ cap``, and a row whose frozen
        ``head_vc ≤ R`` serves exactly — the double-buffered analogue of
        the reference's lock-free reads against a single writer
        (/root/reference/src/materializer_vnode.erl:93-102)."""
        frozen = self._copy_tree_fn((self.head, self.head_vc))
        self._epoch_uses += 1
        self.epochs.append({
            "head": frozen[0],
            "head_vc": frozen[1],
            "cap": self.max_commit_vc.copy(),
            "seq": self._epoch_uses,   # publish order (age)
            "used": self._epoch_uses,  # recency (eviction only)
        })
        if len(self.epochs) > self._EPOCH_CAP:
            victim = min(self.epochs, key=lambda e: e["used"])
            self.epochs = [e for e in self.epochs if e is not victim]

    def invalidate_epochs(self) -> None:
        """Drop every published epoch — required after any out-of-band
        table mutation (row growth, key promotion, handoff install)."""
        self.epochs.clear()
        self.invalidate_serving()

    def _epoch_for(self, read_vcs: np.ndarray):
        """Oldest epoch whose cap dominates every read VC in the batch
        (oldest = closest above the pin = most rows frozen-fresh)."""
        best = None
        for e in self.epochs:
            if (read_vcs <= e["cap"]).all():
                if best is None or e["seq"] < best["seq"]:
                    best = e
        if best is not None:
            self._epoch_uses += 1
            best["used"] = self._epoch_uses
        return best

    # ------------------------------------------------------------------
    # device kernels
    # ------------------------------------------------------------------
    @functools.cached_property
    def _append_fn(self):
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def append(ops_a, ops_b, ops_vc, ops_origin, shards, rows, slots, a, b, v, o):
            # out-of-range indices (padding) are dropped by the scatter
            return (
                ops_a.at[shards, rows, slots].set(a, mode="drop"),
                ops_b.at[shards, rows, slots].set(b, mode="drop"),
                ops_vc.at[shards, rows, slots].set(v, mode="drop"),
                ops_origin.at[shards, rows, slots].set(o, mode="drop"),
            )

        return append

    @functools.cached_property
    def _read_fn(self):
        body = _shard_read_body(self.ty, self.cfg)

        @jax.jit
        def read(snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                 rows, n_ops_rows, read_vcs):
            return jax.vmap(body)(
                snap, snap_vc, snap_seq, ops_a, ops_b, ops_vc, ops_origin,
                rows, n_ops_rows, read_vcs,
            )

        return read

    @functools.cached_property
    def _gc_fn(self):
        # GC = copy the head (already the exact fold of the full ring +
        # prior history) into a fresh snapshot version; no fold needed.
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def gc(snap, snap_vc, snap_seq, head, head_vc, rows, new_seqs):
            def per_shard(snap, snap_vc, snap_seq, head, head_vc, rows, seqs):
                from antidote_tpu.clock import orddict

                sseq = snap_seq[rows]
                slot = orddict.insert_slot(sseq)
                snap2 = {
                    f: x.at[rows, slot].set(head[f][rows], mode="drop")
                    for f, x in snap.items()
                }
                snap_vc2 = snap_vc.at[rows, slot].set(head_vc[rows], mode="drop")
                snap_seq2 = snap_seq.at[rows, slot].set(seqs, mode="drop")
                return snap2, snap_vc2, snap_seq2

            return jax.vmap(per_shard)(
                snap, snap_vc, snap_seq, head, head_vc, rows, new_seqs
            )

        return gc

    def _head_update_for(self, window: int):
        """Head-update kernel scanning a ``window``-slot slice (0 = the
        whole ring); one compiled fn per power-of-2 window."""
        fn = self._head_update_fns.get(window)
        if fn is None:
            body = _shard_head_update_body(self.ty, self.cfg, window)

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def fn(head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
                   rows, starts, ends):
                return jax.vmap(body)(
                    head, head_vc, ops_a, ops_b, ops_vc, ops_origin,
                    rows, starts, ends,
                )

            self._head_update_fns[window] = fn
        return fn

    @functools.cached_property
    def _read_latest_fn(self):
        body = _shard_read_latest_body(self.ty, self.cfg)

        @jax.jit
        def read(head, head_vc, rows, read_vcs):
            return jax.vmap(body)(head, head_vc, rows, read_vcs)

        return read

    @functools.cached_property
    def _latest_resolved_fn(self):
        """Fold-free serving read for read VCs that dominate every commit
        this table has seen (host-decided via ``max_commit_vc``): head
        gather + device value resolution only."""
        ty, cfg = self.ty, self.cfg
        latest = _shard_read_latest_body(ty, cfg)

        @jax.jit
        def fn(head, head_vc, rows, read_vcs):
            state, fresh = jax.vmap(latest)(head, head_vc, rows, read_vcs)
            resolved = (
                ty.resolve(cfg, state)
                if ty.resolve_spec(cfg) is not None
                else state
            )
            return resolved, fresh

        return fn

    def _read_resolved_fn(self, strategy: str, kmax: int = 0):
        """The fused serving read: head gather + snapshot-version select +
        versioned ring fold + freshness select + device value resolution,
        all in ONE launch — the whole read path of SURVEY §3.3
        (check-freshness ≈ check_clock, fold ≈ clocksi_materializer:
        materialize, resolution ≈ Type:value) without intermediate host
        round trips.  ``strategy`` (from :meth:`_fold_strategy`) picks the
        ring fold: ``pallas_counter``/``pallas_set_aw`` dispatch the fused
        Pallas kernels (VERDICT r1 item 3; this PR puts the BASELINE
        workload's own fold on a kernel), ``assoc`` the O(log K) monoid
        reduction (materializer/longlog.py), ``serial`` the masked scan.
        ``kmax`` > 0 folds only ring slots [0, kmax) — valid whenever the
        host-tracked ``n_ops`` max over the batch is ≤ kmax (rings fill
        from 0 and reset at GC), cutting fold work from ops_per_key to the
        actual used prefix (r4 VERDICT item 4)."""
        cached = self._resolved_fns.get((strategy, kmax))
        if cached is not None:
            return cached
        ty, cfg = self.ty, self.cfg
        latest = _shard_read_latest_body(ty, cfg)
        select = _shard_base_select_body(ty, cfg)

        @jax.jit
        def fn(head, head_vc, snap, snap_vc, snap_seq,
               ops_a, ops_b, ops_vc, ops_origin, rows, n_ops_rows, read_vcs):
            state_h, fresh = jax.vmap(latest)(head, head_vc, rows, read_vcs)
            base_state, base_vc, complete = jax.vmap(select)(
                snap, snap_vc, snap_seq, rows, read_vcs
            )
            if kmax:
                # slice the fold to the used ring prefix AFTER the row
                # gather (fuses; never materializes a sliced table copy)
                gat = jax.vmap(lambda x, r: x[r, :kmax])
            else:
                gat = jax.vmap(lambda x, r: x[r])
            opa, opv = gat(ops_a, rows), gat(ops_vc, rows)
            if strategy == "pallas_counter":
                from antidote_tpu.materializer import pallas_kernels as pk

                p, m = rows.shape
                k, d = opv.shape[2], opv.shape[3]
                dcnt, applied = pk._counter_fold_call(
                    opa[..., 0].reshape(p * m, k).astype(jnp.int32),
                    opv.reshape(p * m, k, d),
                    n_ops_rows.reshape(p * m),
                    base_vc.reshape(p * m, d),
                    read_vcs.reshape(p * m, d),
                    256, not pk._on_tpu(),
                )
                state_f = {
                    "cnt": base_state["cnt"]
                    + dcnt.astype(jnp.int64).reshape(p, m)
                }
                applied = applied.reshape(p, m)
            elif strategy == "pallas_set_aw":
                from antidote_tpu.materializer import pallas_kernels as pk

                p, m = rows.shape
                opb, opo = gat(ops_b, rows), gat(ops_origin, rows)
                flat = lambda x: x.reshape((p * m,) + x.shape[2:])
                state_pm, applied = pk.set_aw_fold_local(
                    {f: flat(x) for f, x in base_state.items()},
                    flat(opa), flat(opb), flat(opv), flat(opo),
                    n_ops_rows.reshape(p * m),
                    base_vc.reshape(p * m, -1), read_vcs.reshape(p * m, -1),
                    256, not pk._on_tpu(),
                )
                state_f = {
                    f: x.reshape((p, m) + x.shape[1:])
                    for f, x in state_pm.items()
                }
                applied = applied.reshape(p, m)
            elif strategy == "assoc":
                opb, opo = gat(ops_b, rows), gat(ops_origin, rows)
                state_f, applied = jax.vmap(jax.vmap(
                    lambda s, a, b, v, o, n, bv, rv: longlog.assoc_fold(
                        ty, cfg, s, a, b, v, o, n, bv, rv
                    )
                ))(base_state, opa, opb, opv, opo, n_ops_rows, base_vc,
                   read_vcs)
            else:
                opb, opo = gat(ops_b, rows), gat(ops_origin, rows)
                state_f, applied = jax.vmap(
                    lambda s, a, b, v, o, n, bv, rv: fold_mod.fold_batch(
                        ty, cfg, s, a, b, v, o, n, bv, rv
                    )
                )(base_state, opa, opb, opv, opo, n_ops_rows, base_vc, read_vcs)
            state = {
                f: jnp.where(
                    fresh.reshape(fresh.shape + (1,) * (x.ndim - 2)),
                    state_h[f], x,
                )
                for f, x in state_f.items()
            }
            complete = complete | fresh
            resolved = (
                ty.resolve(cfg, state)
                if ty.resolve_spec(cfg) is not None
                else state
            )
            return resolved, fresh, complete

        self._resolved_fns[(strategy, kmax)] = fn
        return fn

    @functools.cached_property
    def _latest_resolved_flat_fn(self):
        """Flat single-gather variant of :meth:`_latest_resolved_fn` —
        no [P, M'] routing: index the tables by (shard, row) pairs in one
        advanced-indexing gather.  Serving hot path on a single device;
        mesh-sharded tables keep the routed layout (a flat gather across
        the sharded axis would induce collectives)."""
        ty, cfg = self.ty, self.cfg

        @jax.jit
        def fn(head, head_vc, ss, rr, read_vcs):
            hvc = head_vc[ss, rr]
            state = {f: x[ss, rr] for f, x in head.items()}
            fresh = jnp.all(hvc <= read_vcs, axis=-1)
            resolved = (
                ty.resolve(cfg, state)
                if ty.resolve_spec(cfg) is not None
                else state
            )
            return resolved, fresh

        return fn

    def _read_resolved_flat_fn(self, strategy: str, kmax: int = 0):
        """Flat single-gather variant of :meth:`_read_resolved_fn`: the
        same fused serving read (freshness + version select + ring fold +
        resolution, one launch) with the batch as the leading axis — the
        per-shard bodies run on pre-gathered rows via an identity index.
        ``strategy``/``kmax`` as in :meth:`_read_resolved_fn`."""
        cached = self._resolved_flat_fns.get((strategy, kmax))
        if cached is not None:
            return cached
        ty, cfg = self.ty, self.cfg
        select = _shard_base_select_body(ty, cfg)

        @jax.jit
        def fn(head, head_vc, snap, snap_vc, snap_seq,
               ops_a, ops_b, ops_vc, ops_origin, ss, rr, n_ops_flat,
               read_vcs):
            m = ss.shape[0]
            idx = jnp.arange(m)
            hvc = head_vc[ss, rr]
            state_h = {f: x[ss, rr] for f, x in head.items()}
            fresh = jnp.all(hvc <= read_vcs, axis=-1)
            base_state, base_vc, complete = select(
                {f: x[ss, rr] for f, x in snap.items()},
                snap_vc[ss, rr], snap_seq[ss, rr], idx, read_vcs,
            )
            if kmax:
                opa = ops_a[ss, rr][:, :kmax]
                opv = ops_vc[ss, rr][:, :kmax]
            else:
                opa, opv = ops_a[ss, rr], ops_vc[ss, rr]
            if strategy == "pallas_counter":
                from antidote_tpu.materializer import pallas_kernels as pk

                k, d = opv.shape[1], opv.shape[2]
                dcnt, applied = pk._counter_fold_call(
                    opa[..., 0].astype(jnp.int32),
                    opv, n_ops_flat, base_vc, read_vcs,
                    256, not pk._on_tpu(),
                )
                state_f = {"cnt": base_state["cnt"] + dcnt.astype(jnp.int64)}
            elif strategy == "pallas_set_aw":
                from antidote_tpu.materializer import pallas_kernels as pk

                opb, opo = ops_b[ss, rr], ops_origin[ss, rr]
                if kmax:
                    opb, opo = opb[:, :kmax], opo[:, :kmax]
                state_f, applied = pk.set_aw_fold_local(
                    base_state, opa, opb, opv, opo,
                    n_ops_flat, base_vc, read_vcs,
                    256, not pk._on_tpu(),
                )
            elif strategy == "assoc":
                opb, opo = ops_b[ss, rr], ops_origin[ss, rr]
                if kmax:
                    opb, opo = opb[:, :kmax], opo[:, :kmax]
                state_f, applied = jax.vmap(
                    lambda s, a, b, v, o, n, bv, rv: longlog.assoc_fold(
                        ty, cfg, s, a, b, v, o, n, bv, rv
                    )
                )(base_state, opa, opb, opv, opo, n_ops_flat, base_vc,
                  read_vcs)
            else:
                opb, opo = ops_b[ss, rr], ops_origin[ss, rr]
                if kmax:
                    opb, opo = opb[:, :kmax], opo[:, :kmax]
                state_f, applied = fold_mod.fold_batch(
                    ty, cfg, base_state, opa, opb, opv,
                    opo, n_ops_flat, base_vc, read_vcs,
                )
            state = {
                f: jnp.where(
                    fresh.reshape(fresh.shape + (1,) * (x.ndim - 1)),
                    state_h[f], x,
                )
                for f, x in state_f.items()
            }
            complete = complete | fresh
            resolved = (
                ty.resolve(cfg, state)
                if ty.resolve_spec(cfg) is not None
                else state
            )
            return resolved, fresh, complete

        self._resolved_flat_fns[(strategy, kmax)] = fn
        return fn

    @functools.cached_property
    def _merge_scatter_fn(self):
        @jax.jit
        def fn(dst_tree, idx, src_tree):
            return jax.tree.map(
                lambda d, s: d.at[idx].set(s, mode="drop"), dst_tree, src_tree
            )

        return fn

    def _kmax_bucket(self, n: int) -> int:
        """Power-of-4 fold-window bucket covering ``n`` used ring slots
        (0 = fold the whole ring).  Coarse on purpose: every distinct
        kmax is a separate XLA compile of the whole serve path, and on a
        small host a compile is a multi-second serving outage — fewer,
        slightly-wider folds beat a tight ladder."""
        w = 4
        while w < n:
            w *= 4
        return 0 if w >= self.cfg.ops_per_key else w

    def read_resolved_flat(self, shards, rows, read_vcs):
        """Flat serving read — no host routing, no unroute: returns
        (resolved fields [M, ...], fresh [M], complete [M]) in input
        order (device arrays on the all-gather paths, the fold path
        merges on device but returns host fresh/complete).  The
        single-device fast path; callers on a mesh use
        :meth:`read_resolved_raw` (routed layout keeps gathers
        shard-local).

        Dispatch ladder (r4 VERDICT item 2 — reads must not collapse
        under a concurrent write stream):

        1. read VC dominates every commit seen → live head gather.
        2. read VC pinned exactly at a published epoch's cap → frozen
           head gather (the double-buffer hot path: writers advance the
           live head, pinned readers never see them).
        3. otherwise two-phase: gather (frozen epoch if one covers the
           VC, else live head), host-check freshness, and run the
           versioned ring fold ONLY on the stale remainder — fold work
           scales with the write working set, not the read batch.
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        if (read_vcs >= self.max_commit_vc).all():
            resolved, fresh = self._latest_resolved_flat_fn(
                self.head, self.head_vc, shards, rows, read_vcs
            )
            return resolved, fresh, fresh
        epoch = self._epoch_for(read_vcs)
        if epoch is not None and (read_vcs >= epoch["cap"]).all():
            # pinned exactly at the epoch cap: every row frozen-fresh
            # (head_vc ≤ cap = R row-wise) — pure gather, no host sync
            resolved, fresh = self._latest_resolved_flat_fn(
                epoch["head"], epoch["head_vc"], shards, rows, read_vcs
            )
            return resolved, fresh, fresh
        self.slow_serves += 1
        if epoch is not None:
            src_head, src_vc = epoch["head"], epoch["head_vc"]
        else:
            src_head, src_vc = self.head, self.head_vc
        resolved_h, fresh_d = self._latest_resolved_flat_fn(
            src_head, src_vc, shards, rows, read_vcs
        )
        fresh = np.asarray(fresh_d)
        if fresh.all():
            return resolved_h, fresh, fresh
        stale = np.nonzero(~fresh)[0]
        ns = len(stale)
        mb = _bucket(ns, self.cfg.batch_buckets)
        pad = mb - ns
        sss = np.concatenate([shards[stale], np.zeros(pad, np.int64)])
        rrs = np.concatenate([rows[stale], np.zeros(pad, np.int64)])
        vcss = np.concatenate(
            [read_vcs[stale], np.zeros((pad, read_vcs.shape[-1]), np.int32)]
        )
        n_ops_flat = self.n_ops[sss, rrs]
        n_ops_flat[ns:] = 0
        kmax = self._kmax_bucket(int(n_ops_flat.max()))
        strategy = self._fold_strategy()
        self._count_dispatch(strategy)
        fn = self._read_resolved_flat_fn(strategy, kmax)
        resolved_s, _, complete_s = fn(
            self.head, self.head_vc, self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            sss, rrs, n_ops_flat, vcss,
        )
        # scatter the folded rows back over the gathered batch on device
        # (padding scatters at index M → dropped)
        midx = np.concatenate([stale, np.full(pad, len(shards), np.int64)])
        merged = self._merge_scatter_fn(resolved_h, midx, resolved_s)
        complete = fresh.copy()
        complete[stale] = np.asarray(complete_s)[:ns]
        return merged, fresh, complete

    # ------------------------------------------------------------------
    # host routing helpers
    # ------------------------------------------------------------------
    def _route(self, shards, rows):
        """Group a flat (shard, row) batch into padded [P, M'] blocks.

        Returns (row_mat i64[P, M'], pos — list of (shard, slot) per input).
        Padding rows use index ``n_rows`` (dropped/clipped on device).
        """
        p = self.n_shards
        mtot = len(shards)
        counts = np.bincount(shards, minlength=p)
        m = _bucket(max(int(counts.max()), 1), self.cfg.batch_buckets)
        order = np.argsort(shards, kind="stable")
        sorted_shards = shards[order]
        starts = np.searchsorted(sorted_shards, np.arange(p))
        slot_in_shard = np.arange(mtot) - starts[sorted_shards]
        row_mat = np.full((p, m), self.n_rows, np.int64)
        row_mat[sorted_shards, slot_in_shard] = rows[order]
        pos = np.empty((mtot, 2), np.int64)
        pos[order, 0] = sorted_shards
        pos[order, 1] = slot_in_shard
        return row_mat, pos

    # ------------------------------------------------------------------
    # host API (flat batches)
    # ------------------------------------------------------------------
    def append(self, shards, rows, eff_a, eff_b, vcs, origins):
        """Append a commit-ordered batch of effects.

        ``shards`` i64[M]; ``rows`` i64[M]; ``eff_a`` [M, A]; ``eff_b``
        [M, B]; ``vcs`` [M, D]; ``origins`` [M].  Ring overflow triggers a
        GC fold of the affected keys first.
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        m = len(rows)
        if m == 0:
            return
        k = self.cfg.ops_per_key
        # occurrence index of each (shard, row) within the batch, vectorized
        combined = shards * np.int64(self.n_rows) + rows
        order = np.argsort(combined, kind="stable")
        sorted_c = combined[order]
        group_start = np.concatenate([[0], np.nonzero(np.diff(sorted_c))[0] + 1])
        group_of = np.cumsum(
            np.concatenate([[0], (np.diff(sorted_c) != 0).astype(np.int64)])
        )
        occ = np.empty(m, np.int64)
        occ[order] = np.arange(m) - group_start[group_of]
        slots = self.n_ops[shards, rows] + occ
        over = slots >= k
        if over.any():
            su, ru = shards[over], rows[over]
            uniq = np.unique(np.stack([su, ru], axis=1), axis=0)
            self.gc(uniq[:, 0], uniq[:, 1])
            slots = self.n_ops[shards, rows] + occ
            if (slots >= k).any():
                # a single batch carries more ops for one key than the
                # ring holds (e.g. one txn add_all of 3x ops_per_key):
                # split by per-key occurrence so each sub-batch fits, with
                # a GC fold between them — per-key commit order preserved
                chunk = occ // k
                for c in range(int(chunk.max()) + 1):
                    m = chunk == c
                    self.append(
                        shards[m], rows[m],
                        np.asarray(eff_a, np.int64)[m],
                        np.asarray(eff_b, np.int32)[m],
                        np.asarray(vcs, np.int32)[m],
                        np.asarray(origins, np.int32)[m],
                    )
                return
        eff_a = np.asarray(eff_a, np.int64)
        if m and eff_a.shape[1] > 0:
            self.max_abs_delta = max(
                self.max_abs_delta, int(np.abs(eff_a[:, 0]).max())
            )
        vcs_np = np.asarray(vcs, np.int32)
        if m:
            np.maximum(
                self.max_commit_vc, vcs_np.max(axis=0), out=self.max_commit_vc
            )
        mb = _bucket(m, self.cfg.batch_buckets)
        pad = mb - m

        def padi(x, fill):
            return np.concatenate([x, np.full((pad,) + x.shape[1:], fill, x.dtype)])

        self.ops_a, self.ops_b, self.ops_vc, self.ops_origin = self._append_fn(
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            padi(shards, self.n_shards), padi(rows, 0), padi(slots, 0),
            padi(np.asarray(eff_a, np.int64), 0),
            padi(np.asarray(eff_b, np.int32), 0),
            padi(np.asarray(vcs, np.int32), 0),
            padi(np.asarray(origins, np.int32), 0),
        )
        # fold the newly-appended ring slots onto the head state
        uniq_mask = occ == 0
        us, ur = shards[uniq_mask], rows[uniq_mask]
        ucount = np.bincount(
            np.searchsorted(np.sort(combined[uniq_mask]), combined)
        )  # per-unique-pair op count, aligned to sorted unique order
        sort_u = np.argsort(combined[uniq_mask], kind="stable")
        us_s, ur_s = us[sort_u], ur[sort_u]
        starts = self.n_ops[us_s, ur_s].astype(np.int64)
        ends = starts + ucount
        row_mat, pos = self._route(us_s, ur_s)
        start_mat = np.zeros(row_mat.shape, np.int64)
        end_mat = np.zeros(row_mat.shape, np.int64)
        start_mat[pos[:, 0], pos[:, 1]] = starts
        end_mat[pos[:, 0], pos[:, 1]] = ends
        # window choice is deliberately binary (1-op commits vs full-ring
        # scan): each window is a separate XLA compile of the head fold,
        # and compile outages cost more than the extra masked slots
        span = int(ucount.max()) if len(ucount) else 0
        w = 1 if span <= 1 else k
        self.head, self.head_vc = self._head_update_for(0 if w >= k else w)(
            self.head, self.head_vc,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            row_mat, start_mat, end_mat,
        )
        np.add.at(self.n_ops, (shards, rows), 1)
        self.note_serving_touch(us_s, ur_s)

    def gc(self, shards, rows):
        """Fold the given keys' rings into a fresh snapshot version."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        if len(rows) == 0:
            return
        row_mat, pos = self._route(shards, rows)
        count = len(rows)
        seq_mat = np.zeros(row_mat.shape, np.int64)
        seqs = np.arange(self.next_seq, self.next_seq + count, dtype=np.int64)
        self.next_seq += count
        seq_mat[pos[:, 0], pos[:, 1]] = seqs
        self.snap, self.snap_vc, self.snap_seq = self._gc_fn(
            self.snap, self.snap_vc, self.snap_seq,
            self.head, self.head_vc, row_mat, seq_mat,
        )
        self.n_ops[shards, rows] = 0

    def read_latest(
        self, shards, rows, read_vcs
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """Fast path: gather head states.  Returns (state fields [M, ...],
        fresh [M]).  A row is fresh iff head_vc ≤ its read VC — then the
        head IS the exact snapshot.  Stale rows must use :meth:`read`."""
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        row_mat, pos = self._route(shards, rows)
        p, mm = row_mat.shape
        vc_mat = np.zeros((p, mm, read_vcs.shape[-1]), np.int32)
        vc_mat[pos[:, 0], pos[:, 1]] = read_vcs
        row_gather = np.minimum(row_mat, self.n_rows - 1)
        state, fresh = self._read_latest_fn(
            self.head, self.head_vc, row_gather, vc_mat
        )
        s, j = pos[:, 0], pos[:, 1]
        out = {f: np.asarray(x)[s, j] for f, x in state.items()}
        return out, np.asarray(fresh)[s, j]

    @staticmethod
    def _pallas_platform_ok() -> bool:
        """Pallas strategies need a real TPU backend to pay off — on CPU
        the interpreter-mode kernels regress serve ~2x and mixed load
        ~16x (see pallas_kernels.in_path_ok, which also honors the
        ANTIDOTE_PALLAS_INTERPRET=1 parity-test escape)."""
        from antidote_tpu.materializer import pallas_kernels as pk

        return pk.in_path_ok()

    def _pallas_counter_ok(self) -> bool:
        return (
            getattr(self.cfg, "use_pallas", False)
            and self.ty.name == "counter_pn"
            and self.max_abs_delta
            <= (2**31 - 1) // max(self.cfg.ops_per_key, 1)
        )

    def _fold_strategy(self) -> str:
        """Pick the ring fold for the serving read's stale remainder.

        Pallas kernels first (TPU-gated — see ``_pallas_platform_ok``;
        counter masked-sum when the i32 bound holds; the set_aw add-wins
        fold — the BASELINE workload — needs no bound, it has no sums),
        then the O(log K) assoc reduction for monoid types whose delta
        is exact from an ARBITRARY base (counter without the kernel,
        flags; sets are bottom-only — see
        crdt/base.py::assoc_bottom_only), serial masked scan as fallback.
        """
        if self._pallas_counter_ok() and self._pallas_platform_ok():
            return "pallas_counter"
        if (
            getattr(self.cfg, "use_pallas", False)
            and self.ty.name == "set_aw"
            and self._pallas_platform_ok()
        ):
            return "pallas_set_aw"
        if self.ty.supports_assoc and not self.ty.assoc_bottom_only:
            return "assoc"
        return "serial"

    def _count_dispatch(self, strategy: str, n: int = 1):
        self.fold_dispatches[strategy] = (
            self.fold_dispatches.get(strategy, 0) + n
        )
        m = getattr(self.metrics, "fold_dispatch", None)
        if m is not None:
            m.inc(n, strategy=strategy)

    def read_resolved_raw(self, shards, rows, read_vcs):
        """One-launch serving read; returns DEVICE arrays still in routed
        [P, M'] layout plus the (shard, slot) positions — callers that
        pipeline batches fetch/unroute later (``copy_to_host_async``).

        Output: (resolved fields or full state [P, M', ...], fresh
        [P, M'], complete [P, M'], pos [M, 2]).
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        row_mat, pos = self._route(shards, rows)
        p, mm = row_mat.shape
        row_gather = np.minimum(row_mat, self.n_rows - 1)
        vc_mat = np.zeros((p, mm, read_vcs.shape[-1]), np.int32)
        vc_mat[pos[:, 0], pos[:, 1]] = read_vcs
        if (read_vcs >= self.max_commit_vc).all():
            # every row is provably fresh: skip the versioned fold
            resolved, fresh = self._latest_resolved_fn(
                self.head, self.head_vc, row_gather, vc_mat
            )
            return resolved, fresh, fresh, pos
        n_ops_mat = self.n_ops[np.arange(p)[:, None], row_gather]
        n_ops_mat = np.where(row_mat < self.n_rows, n_ops_mat, 0)
        kmax = self._kmax_bucket(int(n_ops_mat.max()) if n_ops_mat.size else 1)
        strategy = self._fold_strategy()
        self._count_dispatch(strategy)
        fn = self._read_resolved_fn(strategy, kmax)
        resolved, fresh, complete = fn(
            self.head, self.head_vc, self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            row_gather, n_ops_mat, vc_mat,
        )
        return resolved, fresh, complete, pos

    def read_resolved(self, shards, rows, read_vcs):
        """Serving read with device value resolution, one launch, flat
        output.  Returns (resolved fields [M, ...], fresh [M], complete
        [M]).  For types without ``resolve_spec`` the fields are the full
        materialized state.  Incomplete rows (read VC below retained device
        coverage) need the caller's log-replay fallback, as with
        :meth:`read`.

        Single-device tables serve through the flat path (one gather, no
        [P, M'] routing/unrouting); mesh-sharded tables keep the routed
        layout so gathers stay shard-local."""
        if self.sharding is None:
            resolved, fresh, complete = self.read_resolved_flat(
                shards, rows, read_vcs
            )
            return ({f: np.asarray(x) for f, x in resolved.items()},
                    np.asarray(fresh), np.asarray(complete))
        resolved, fresh, complete, pos = self.read_resolved_raw(
            shards, rows, read_vcs
        )
        s, j = pos[:, 0], pos[:, 1]
        out = {f: np.asarray(x)[s, j] for f, x in resolved.items()}
        return out, np.asarray(fresh)[s, j], np.asarray(complete)[s, j]

    def read(self, shards, rows, read_vcs) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        """Materialize a flat batch of keys at per-key read VCs.

        Returns host copies (state fields [M, ...], n_applied [M],
        complete [M]).  Incomplete rows need a log-replay fallback.
        """
        shards = np.asarray(shards, np.int64)
        rows = np.asarray(rows, np.int64)
        read_vcs = np.asarray(read_vcs, np.int32)
        m = len(rows)
        row_mat, pos = self._route(shards, rows)
        p, mm = row_mat.shape
        # clip padding rows for the gather path
        row_gather = np.minimum(row_mat, self.n_rows - 1)
        n_ops_mat = self.n_ops[np.arange(p)[:, None], row_gather]
        n_ops_mat = np.where(row_mat < self.n_rows, n_ops_mat, 0)
        vc_mat = np.zeros((p, mm, read_vcs.shape[-1]), np.int32)
        vc_mat[pos[:, 0], pos[:, 1]] = read_vcs
        state, applied, complete = self._read_fn(
            self.snap, self.snap_vc, self.snap_seq,
            self.ops_a, self.ops_b, self.ops_vc, self.ops_origin,
            row_gather, n_ops_mat, vc_mat,
        )
        s, j = pos[:, 0], pos[:, 1]
        out = {f: np.asarray(x)[s, j] for f, x in state.items()}
        return out, np.asarray(applied)[s, j], np.asarray(complete)[s, j]
