"""Shard handoff & resharding — the riak_core handoff analogue.

The reference migrates a partition by folding its vnode state into handoff
messages: materializer_vnode folds ``ops_cache``
(/root/reference/src/materializer_vnode.erl:221-246), logging_vnode folds
every log record (/root/reference/src/logging_vnode.erl:781-812), and
riak_core replays the fold at the receiver.  Here a shard is a slice of
the per-type device tables plus its WAL, so handoff is three batched
moves:

  * ``export_shard``   — gather one shard's rows off-device into a
    serializable package (tables + directory + clocks + WAL records).
  * ``import_shard``   — scatter a package into a destination replica
    (one ``.at[shard, base:base+n].set`` per array), re-chain the WAL.
  * ``drop_shard``     — zero the source slice after a successful move.

``reshard`` rebuilds a replica onto a different shard count: every key is
re-routed with one native ``shard_batch`` crossing and every table row
moves with one gather + one scatter per array — no per-key work.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Any, Dict, List, Optional

import msgpack
import numpy as np

from antidote_tpu.store.kv import KVStore, effect_from_rec, freeze_key
from antidote_tpu.store.router import shard_batch


def _table_slice(t, shard: int, used: int) -> Dict[str, Any]:
    out = {
        "snap": {f: np.asarray(x[shard, :used]) for f, x in t.snap.items()},
        "snap_vc": np.asarray(t.snap_vc[shard, :used]),
        "snap_seq": np.asarray(t.snap_seq[shard, :used]),
        "ops_a": np.asarray(t.ops_a[shard, :used]),
        "ops_b": np.asarray(t.ops_b[shard, :used]),
        "ops_vc": np.asarray(t.ops_vc[shard, :used]),
        "ops_origin": np.asarray(t.ops_origin[shard, :used]),
        "n_ops": t.n_ops[shard, :used].copy(),
        "head": {f: np.asarray(x[shard, :used]) for f, x in t.head.items()},
        "head_vc": np.asarray(t.head_vc[shard, :used]),
        # host-tracked serving-path gates (table-wide, conservative): the
        # importer must inherit them or the Pallas counter dispatch /
        # provably-fresh fast path would trust stale bounds
        "max_abs_delta": int(t.max_abs_delta),
        "max_commit_vc": t.max_commit_vc.copy(),
        "slots_ub": t.slots_ub[shard, :used].copy(),
    }
    return out


def export_shard(store: KVStore, shard: int,
                 include_log: bool = True) -> Dict[str, Any]:
    """Package one shard of a replica for transfer.

    Returns a dict of host arrays + metadata; ``pack``/``unpack`` turn it
    into wire bytes for a cross-node move.
    """
    if store.cold is not None:
        # whole-shard export works on device state: every cold key of
        # the shard must fault back in first (operator-paced path — the
        # rate cap does not apply)
        store.cold.fault_in_shard(int(shard))
    with_log = include_log and store.log is not None
    # a checkpoint-truncated source (ISSUE 8): the ride-along log is only
    # the tail above the compaction floor — the importer's WAL cannot
    # rebuild the rows' earlier history until a LOCAL checkpoint covers
    # them (the import path nudges/warns about exactly that)
    compacted = bool(
        with_log and int(store.log.floor_seqs[int(shard)]) > 0
    )
    pkg: Dict[str, Any] = {
        "shard": int(shard),
        "applied_vc": store.applied_vc[shard].copy(),
        "tables": {},
        "directory": [],
        "log": [],
        "compacted": compacted,
        # per-origin replication-group counts below the source's
        # compaction floor: the ride-along log is only the tail, so the
        # importer must seed its chain numbering from here or every
        # WAL-derived position (restore_from_log, extras-less
        # adopt_shard, catch-up serving) would restart at the tail
        # count and remote subscribers would drop the shard's next
        # commits as duplicates
        "chain_floor": (store.log.chain_floor[int(shard)].tolist()
                        if compacted else None),
        # payload bytes for value handles: when the FULL WAL rides along,
        # its records carry every handle this shard references (first use
        # per shard logs the bytes — log/__init__.py _blob_seen), so
        # shipping the blob dict again would be pure duplication.
        # Without a log — or with only a compacted tail, whose records
        # may reference pre-floor handles whose bytes live only in the
        # checkpoint image — we cannot tell which handles the shard's
        # state references (handle lanes are type-specific), so ship the
        # whole content-addressed dict; receivers setdefault, duplicates
        # are free.
        "blobs": [] if (with_log and not compacted) else [
            (int(h), bytes(d)) for h, d in store.blobs._by_handle.items()
        ],
    }
    for tname, t in store.tables.items():
        used = int(t.used_rows[shard])
        if used == 0:
            continue
        sl = _table_slice(t, shard, used)
        sl["used"] = used
        sl["next_seq"] = int(t.next_seq)
        pkg["tables"][tname] = sl
    # per-shard directory index: exactly the shard's keys, not an
    # O(total keys) filter (ISSUE 10 satellite)
    for key, bucket in sorted(store.directory.shard_keys(shard),
                              key=repr):
        tname, _s, row = store.directory[(key, bucket)]
        pkg["directory"].append((key, bucket, tname, int(row)))
    if with_log:
        pkg["log"] = list(store.log.replay_shard(shard))
    return pkg


def import_shard(store: KVStore, pkg: Dict[str, Any],
                 shard: Optional[int] = None) -> None:
    """Merge an exported shard into ``store`` at ``shard`` (defaults to the
    package's original shard index).  Imported rows are appended after the
    destination's existing rows; the directory re-binds keys to their new
    (shard, row) homes.  Key collisions (same (key, bucket) already bound
    here) are rejected — a shard has exactly one home per ring epoch.
    """
    dst = int(pkg["shard"] if shard is None else shard)
    # validate BEFORE any mutation: a rejected import must leave the
    # destination untouched (no orphan rows / partial directory)
    for key, bucket, _, _ in pkg["directory"]:
        dk = (freeze_key(key), bucket)
        if dk in store.directory:
            raise ValueError(
                f"import_shard: {dk!r} already bound on this replica"
            )
    if store.merkle is not None:
        store.merkle.mark_all(dst)
    # exclusive ownership: a shard has one home per ring epoch.  Importing
    # into a shard that already holds rows would merge two partial copies
    # of the same (origin, shard) replication chains — the duplicate
    # suppression in the dependency gate is only sound when the shard's
    # applied clocks describe THIS replica's chain progress.
    for tname, t in store.tables.items():
        if t.used_rows[dst] > 0:
            raise ValueError(
                f"import_shard: destination shard {dst} already holds "
                f"{int(t.used_rows[dst])} {tname!r} rows; hand off into an "
                "empty shard (exclusive ownership per ring epoch)"
            )
    if (store.log is not None and pkg["tables"] and not pkg["log"]
            and not pkg.get("compacted")):
        raise ValueError(
            "import_shard: this replica is durable (WAL attached) but the "
            "package carries no log records — the imported rows could "
            "never recover and their blob payloads would be lost on "
            "re-export; export with include_log=True from a logged source"
        )
    bases: Dict[str, int] = {}
    for tname, sl in pkg["tables"].items():
        t = store.table(tname)
        used = int(sl["used"])
        base = int(t.used_rows[dst])
        while base + used > t.n_rows:
            t._grow()
        bases[tname] = base
        end = base + used
        for f in t.snap:
            t.snap[f] = t.snap[f].at[dst, base:end].set(sl["snap"][f])
            t.head[f] = t.head[f].at[dst, base:end].set(sl["head"][f])
        # renumber snapshot sequence ids above everything local so the
        # per-key newest-version order is preserved
        seq = np.asarray(sl["snap_seq"], np.int64)
        seq = np.where(seq > 0, seq + t.next_seq, 0)
        t.next_seq += int(sl["next_seq"])
        t.snap_vc = t.snap_vc.at[dst, base:end].set(sl["snap_vc"])
        t.snap_seq = t.snap_seq.at[dst, base:end].set(seq)
        t.ops_a = t.ops_a.at[dst, base:end].set(sl["ops_a"])
        t.ops_b = t.ops_b.at[dst, base:end].set(sl["ops_b"])
        t.ops_vc = t.ops_vc.at[dst, base:end].set(sl["ops_vc"])
        t.ops_origin = t.ops_origin.at[dst, base:end].set(sl["ops_origin"])
        t.head_vc = t.head_vc.at[dst, base:end].set(sl["head_vc"])
        t.invalidate_epochs()  # out-of-band mutation: frozen copies stale
        t.n_ops[dst, base:end] = sl["n_ops"]
        # packages from builds predating the overflow hatch lack the slot
        # bound; the conservative default (capacity) forces a promotion on
        # the next add rather than risking a drop
        cap = t.ty.slot_capacity(t.cfg)
        t.slots_ub[dst, base:end] = np.asarray(
            sl.get("slots_ub", np.full(used, cap or 0, np.int32)), np.int32
        )
        t.used_rows[dst] = end
        # packages from builds predating these gates lack the keys; the
        # conservative defaults disable the Pallas counter dispatch and the
        # provably-fresh fast path rather than trusting stale bounds
        t.max_abs_delta = max(
            t.max_abs_delta, int(sl.get("max_abs_delta", 2**62))
        )
        np.maximum(
            t.max_commit_vc,
            np.asarray(
                sl.get("max_commit_vc", np.full_like(t.max_commit_vc, 2**31 - 1)),
                np.int32,
            ),
            out=t.max_commit_vc,
        )
    for key, bucket, tname, row in pkg["directory"]:
        store.directory[(freeze_key(key), bucket)] = (
            tname, dst, bases[tname] + int(row)
        )
    for h, data in pkg.get("blobs", []):
        store.blobs.intern_bytes(int(h), bytes(data))
    np.maximum(store.applied_vc[dst], pkg["applied_vc"],
               out=store.applied_vc[dst])
    if pkg.get("chain_floor") and store.log is not None:
        # compacted source: continue the replication chains where the
        # source's checkpoint image left them (see export_shard)
        store.log.set_chain_floor(dst, pkg["chain_floor"])
    for rec in pkg["log"]:
        # the ride-along WAL records carry this shard's blob bytes
        eff = effect_from_rec(rec)
        for h, data in eff.blob_refs:
            store.blobs.intern_bytes(int(h), bytes(data))
        if store.log is not None:
            store.log.log_effect(
                dst, eff.key, eff.type_name, eff.bucket, eff.eff_a,
                eff.eff_b, np.asarray(rec["vc"], np.int32), int(rec["o"]),
                blob_refs=eff.blob_refs,
            )
    if pkg["log"] and store.log is not None:
        store.log.commit_barrier([dst])


def drop_shard(store: KVStore, shard: int) -> None:
    """Clear a shard after a successful handoff (source side)."""
    if store.cold is not None:
        # cold refs travel with the shard (the receiver faulted them in
        # via the export's fault_in_shard); local refs must not linger
        store.cold.drop_shard(shard)
    if store.merkle is not None:
        store.merkle.mark_all(shard)
    for t in store.tables.values():
        used = int(t.used_rows[shard])
        if used:
            for f in t.snap:
                t.snap[f] = t.snap[f].at[shard].set(0)
                t.head[f] = t.head[f].at[shard].set(0)
            t.snap_vc = t.snap_vc.at[shard].set(0)
            t.snap_seq = t.snap_seq.at[shard].set(0)
            t.ops_a = t.ops_a.at[shard].set(0)
            t.ops_b = t.ops_b.at[shard].set(0)
            t.ops_vc = t.ops_vc.at[shard].set(0)
            t.ops_origin = t.ops_origin.at[shard].set(0)
            t.head_vc = t.head_vc.at[shard].set(0)
            t.invalidate_epochs()
            t.n_ops[shard] = 0
            t.slots_ub[shard] = 0
        t.used_rows[shard] = 0
        t.free_rows.pop(shard, None)  # rows restart from 0
    # index-driven relinquish: drop exactly the shard's keys instead of
    # rebuilding the whole directory (ISSUE 10 satellite)
    for dk in list(store.directory.shard_keys(shard)):
        del store.directory[dk]
    store.applied_vc[shard] = 0
    if store.log is not None:
        # the moved records must not resurrect here on the next recover
        store.log.truncate_shard(shard)


def opaque(obj: Any) -> Dict[str, Any]:
    """Pre-pack a large plain-data value (no ndarrays inside) so
    :func:`pack`/:func:`unpack`'s recursive walk crosses it as ONE node:
    a million-entry directory list costs one C-speed msgpack pass
    instead of five million Python ``dec`` calls (the measured majority
    of checkpoint image decode time at 1M keys — ISSUE 8)."""
    return {"__mp": msgpack.packb(obj, use_bin_type=True)}


def pack(pkg: Dict[str, Any]) -> bytes:
    """Wire form of an exported shard (msgpack; arrays as raw bytes)."""

    def enc(x):
        if isinstance(x, np.ndarray):
            return {"__nd": True, "d": str(x.dtype), "s": list(x.shape),
                    "b": x.tobytes()}
        if isinstance(x, dict):
            return {k: enc(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [enc(v) for v in x]
        return x

    return msgpack.packb(enc(pkg), use_bin_type=True)


def unpack(data: bytes) -> Dict[str, Any]:
    def dec(x):
        if isinstance(x, dict):
            if x.get("__nd"):
                return np.frombuffer(x["b"], x["d"]).reshape(x["s"]).copy()
            if x.get("__mp") is not None:
                return msgpack.unpackb(x["__mp"], raw=False,
                                       strict_map_key=False)
            return {k: dec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [dec(v) for v in x]
        return x

    return dec(msgpack.unpackb(data, raw=False, strict_map_key=False))


def assert_replication_quiescent(store: KVStore, my_dc: int,
                                 replica=None) -> None:
    """Refuse to reshard a replica with replication in flight.

    Re-chaining splits per-(origin, shard) opid chains; a remote txn
    buffered/gated mid-flight when the chains renumber would be silently
    dropped as a duplicate afterwards (r1 advisor medium (b)).  Quiescence
    here means: no gated or pending remote txns (``replica``), and every
    remote origin's lane equal across all shard clocks — an unequal lane
    is a remote commit some shards have applied and others haven't (or a
    safe-time ping still in the fabric)."""
    if replica is not None:
        stuck = [
            k for k, q in replica.gate.items() if len(q) > 0
        ] + [
            k for k, buf in replica.pending.items() if len(buf) > 0
        ]
        if stuck:
            raise RuntimeError(
                f"reshard with replication in flight: gated/pending remote "
                f"txns on (origin, shard) chains {sorted(set(stuck))}; "
                "pump the fabric to quiescence first"
            )
    vc = store.applied_vc
    for lane in range(store.cfg.max_dcs):
        if lane == my_dc:
            continue  # local lane legitimately differs per shard
        if not (vc[:, lane] == vc[0, lane]).all():
            raise RuntimeError(
                f"reshard with replication in flight: origin lane {lane} "
                f"differs across shards ({vc[:, lane].tolist()}); drain "
                "replication (pump + heartbeats) to quiescence first"
            )


def reshard(store: KVStore, new_cfg, log=None, my_dc: int | None = None,
            replica=None) -> KVStore:
    """Rebuild a replica onto a different shard count (ring resize).

    ``new_cfg`` must differ from ``store.cfg`` only in ``n_shards``.  Every
    key re-routes via one ``shard_batch`` crossing; each table moves with
    one host gather + one device scatter per array.  Returns the new store
    (the old one is left untouched).

    CALLER CONTRACT: replication must be quiescent — pass ``replica``
    (and/or ``my_dc``) to have that asserted
    (:func:`assert_replication_quiescent`) and to hold the replica's
    ingress barrier for the duration: the reference takes the whole ring
    through riak_core ownership handoff, which blocks vnode commands —
    without the barrier a remote txn delivered on a fabric thread
    mid-copy would land in the old store and be silently lost.
    """
    old_cfg = store.cfg
    assert new_cfg.max_dcs == old_cfg.max_dcs
    assert new_cfg.ops_per_key == old_cfg.ops_per_key
    assert new_cfg.snap_versions == old_cfg.snap_versions
    if replica is not None and my_dc is None:
        my_dc = replica.dc_id
    barrier = (replica.ingress_barrier() if replica is not None
               else contextlib.nullcontext())
    with barrier:
        if my_dc is not None:
            assert_replication_quiescent(store, my_dc, replica)
        return _reshard_locked(store, new_cfg, log)


def _reshard_locked(store: KVStore, new_cfg, log) -> KVStore:
    old_cfg = store.cfg
    # keep the device placement: a mesh-sharded replica must come out of a
    # ring resize still laid out over its mesh (its axis size permitting).
    # jax.device_put over the shard mesh axis of size M requires
    # n_shards % M == 0; an incompatible resize (e.g. 8->4 on an 8-device
    # mesh) falls back to default placement rather than crashing mid-copy.
    sharding = store.sharding
    if sharding is not None:
        from antidote_tpu.parallel.spmd import SHARD_AXIS

        mesh_axis = dict(sharding.mesh.shape).get(SHARD_AXIS, 1)
        if new_cfg.n_shards % mesh_axis != 0:
            logging.getLogger(__name__).warning(
                "reshard to n_shards=%d is not divisible by the mesh "
                "'%s' axis (%d): new store falls back to default device "
                "placement", new_cfg.n_shards, SHARD_AXIS, mesh_axis)
            sharding = None
    new = KVStore(new_cfg, sharding=sharding, log=log)

    items = list(store.directory.items())
    keys = [dk[0] for dk, _ in items]
    buckets = [dk[1] for dk, _ in items]
    new_shards = shard_batch(keys, buckets, new_cfg.n_shards)

    by_type: Dict[str, List] = {}
    for i, (dk, (tname, s, row)) in enumerate(items):
        by_type.setdefault(tname, []).append((dk, s, row, int(new_shards[i])))

    for tname, ents in by_type.items():
        src = store.tables[tname]
        dst = new.table(tname)
        old_s = np.asarray([e[1] for e in ents], np.int64)
        old_r = np.asarray([e[2] for e in ents], np.int64)
        ns = np.asarray([e[3] for e in ents], np.int64)
        # allocate contiguous rows per new shard
        nr = np.empty(len(ents), np.int64)
        for p in range(new_cfg.n_shards):
            m = ns == p
            cnt = int(m.sum())
            if cnt == 0:
                continue
            base = int(dst.used_rows[p])
            while base + cnt > dst.n_rows:
                dst._grow()
            nr[m] = base + np.arange(cnt)
            dst.used_rows[p] = base + cnt
        for f in dst.snap:
            dst.snap[f] = dst.snap[f].at[ns, nr].set(
                np.asarray(src.snap[f])[old_s, old_r])
            dst.head[f] = dst.head[f].at[ns, nr].set(
                np.asarray(src.head[f])[old_s, old_r])
        dst.snap_vc = dst.snap_vc.at[ns, nr].set(
            np.asarray(src.snap_vc)[old_s, old_r])
        dst.snap_seq = dst.snap_seq.at[ns, nr].set(
            np.asarray(src.snap_seq)[old_s, old_r])
        dst.ops_a = dst.ops_a.at[ns, nr].set(np.asarray(src.ops_a)[old_s, old_r])
        dst.ops_b = dst.ops_b.at[ns, nr].set(np.asarray(src.ops_b)[old_s, old_r])
        dst.ops_vc = dst.ops_vc.at[ns, nr].set(
            np.asarray(src.ops_vc)[old_s, old_r])
        dst.ops_origin = dst.ops_origin.at[ns, nr].set(
            np.asarray(src.ops_origin)[old_s, old_r])
        dst.head_vc = dst.head_vc.at[ns, nr].set(
            np.asarray(src.head_vc)[old_s, old_r])
        dst.n_ops[ns, nr] = src.n_ops[old_s, old_r]
        dst.slots_ub[ns, nr] = src.slots_ub[old_s, old_r]
        dst.next_seq = max(dst.next_seq, src.next_seq)
        dst.max_abs_delta = max(dst.max_abs_delta, src.max_abs_delta)
        np.maximum(dst.max_commit_vc, src.max_commit_vc,
                   out=dst.max_commit_vc)
        for i, (dk, _, _, _) in enumerate(ents):
            new.directory[dk] = (tname, int(ns[i]), int(nr[i]))

    # every commit applied on the old ring is applied on the new one: seed
    # all new shards with the DC-wide applied merge so the stable snapshot
    # (min over shards) never regresses
    merged = store.applied_vc.max(axis=0)
    new.applied_vc[:] = merged
    new.blobs = store.blobs
    # re-chain the durable log onto the new ring
    if log is not None and store.log is not None:
        for s in range(old_cfg.n_shards):
            for rec in store.log.replay_shard(s):
                eff = effect_from_rec(rec)
                ent = new.directory.get((eff.key, eff.bucket))
                if ent is None:
                    continue
                log.log_effect(
                    ent[1], eff.key, eff.type_name, eff.bucket, eff.eff_a,
                    eff.eff_b, np.asarray(rec["vc"], np.int32),
                    int(rec["o"]), blob_refs=eff.blob_refs,
                )
        log.commit_barrier(range(new_cfg.n_shards))
    return new
