// router.cc — native key→shard router.
//
// The reference routes keys through the crypto NIF's consistent hash
// (chash_key → crypto:bytes_to_integer,
// /root/reference/src/log_utilities.erl:96-118).  Here the router is a
// XXH64-style 64-bit hash (implemented from the public spec) with a batch
// API: the client protocol and commit path hash thousands of keys per
// call, so the per-key FFI cost is amortized to one crossing.
//
// C ABI for ctypes; pure functions, no state.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm LE)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline uint64_t round_(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl(acc, 31);
  return acc * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round_(0, val);
  return acc * P1 + P4;
}

uint64_t xxh64(const uint8_t* data, uint64_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = round_(v1, read64(p)); p += 8;
      v2 = round_(v2, read64(p)); p += 8;
      v3 = round_(v3, read64(p)); p += 8;
      v4 = round_(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += len;
  while (p + 8 <= end) {
    h ^= round_(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // namespace

extern "C" {

uint64_t router_hash64(const uint8_t* data, uint64_t len, uint64_t seed) {
  return xxh64(data, len, seed);
}

// Batch: blob holds n concatenated keys; offsets[i]..offsets[i+1] bounds
// key i (offsets has n+1 entries).  out[i] = hash % n_shards.
void router_shard_batch(const uint8_t* blob, const uint64_t* offsets,
                        int64_t n, uint64_t seed, int64_t n_shards,
                        int64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = xxh64(blob + offsets[i], offsets[i + 1] - offsets[i], seed);
    out[i] = static_cast<int64_t>(h % static_cast<uint64_t>(n_shards));
  }
}

}  // extern "C"
