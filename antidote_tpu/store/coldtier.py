"""Cold tier — beyond-RAM survival behind the slot-tier ladder (ISSUE 13).

The reference survives datasets bigger than RAM with dets spill-to-disk
tables (SURVEY §2.3/§2.6).  This module is that idea rebuilt for the
device-table store: rows untouched since the newest FULL checkpoint
image are **evicted** — the device copy is forgotten (the row zeroed
through the guarded :meth:`TypedTable.evict_rows` and pushed onto the
per-shard free list for reuse), while the image keeps the state — and
**faulted back in** on the next read or write through the locked path.
Because the image already holds VC-stamped heads per table, eviction
needs no extra write: it is "forget the device copy, keep the floor".

The addressing side is the checkpoint **cold sidecar** (``cold.bin``
next to ``image.bin``): the same per-table head columns laid out as raw
fixed-stride binaries with a per-row CRC, so a fault-in is a handful of
``pread`` calls — never a whole-image decode.  :func:`write_sidecar` /
:class:`Sidecar` own the format; the checkpoint writer emits it on every
full stamp (carrying still-cold rows forward as an appendix, so
retention never strands cold data).

Failure contract (the "no silent wrong reads" leg of ISSUE 13):

  * a fault-in past the fault-rate cap, behind an injected/real I/O
    error (site ``coldtier.fault``), or over a row that fails its CRC is
    refused with a typed :class:`~antidote_tpu.overload.ColdMiss` carrying
    a retry hint — the read parks client-side and retries, it is never
    served bottom;
  * a row verifiably lost on every retained image (bit rot caught by the
    scrubber mid-rebase) is tombstoned: reads raise a *permanent*
    ColdMiss naming the repair (re-bootstrap from a peer/follower);
  * eviction only ever drops rows whose live ``head_vc`` is byte-equal
    to the sidecar's stored stamp — a row written since the image is
    simply not evictable until the next stamp covers it.

RSS bounding: ``budget`` caps the store's RESIDENT device rows (the
allocation high-water mark minus freed rows).  Past it, the coldest
eligible keys (write-LRU) are evicted in bounded batches from the commit
path; when nothing is eligible (no image yet, or everything dirty since
the stamp) the tier asks the checkpointer for a stamp instead of ever
refusing a write.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from antidote_tpu import faults
from antidote_tpu.overload import ColdMiss, retry_hint_ms

log = logging.getLogger(__name__)

#: sidecar file name inside a published checkpoint directory
COLD_BIN = "cold.bin"


# ---------------------------------------------------------------------------
# sidecar format: raw fixed-stride columns + per-row CRC
# ---------------------------------------------------------------------------
def _row_bytes(spec: dict) -> int:
    return int(np.dtype(spec["dtype"]).itemsize
               * int(np.prod(spec["shape"], dtype=np.int64)))


def write_sidecar(fh, tables: Dict[str, dict]) -> dict:
    """Stream the cold sidecar for one full image and return its
    manifest block.  ``tables`` maps tiered table names to
    ``{"head": {field: arr[P, R, ...]}, "head_vc": arr[P, R, D],
    "slots_ub": arr[P, R]}`` host arrays (R = resident extent + cold
    appendix).  Layout: each column contiguous C-order at a recorded
    offset; ``row_crc`` is crc32 over the row's concatenated column
    bytes (sorted field order, then head_vc, then slots_ub) — the
    fault-in's integrity check."""
    manifest: Dict[str, Any] = {"tables": {}}
    off = 0
    crc_total = 0

    def emit(arr: np.ndarray) -> dict:
        nonlocal off, crc_total
        arr = np.ascontiguousarray(arr)
        data = arr.tobytes()
        fh.write(data)
        crc_total = zlib.crc32(data, crc_total)
        spec = {"off": off, "dtype": str(arr.dtype),
                "shape": list(arr.shape[2:])}
        off += len(data)
        return spec

    for tname in sorted(tables):
        tb = tables[tname]
        p, r = tb["head_vc"].shape[:2]
        cols = []  # (per-row byte matrices for the CRC pass)
        tman: Dict[str, Any] = {"rows": int(r), "fields": {}}
        for f in sorted(tb["head"]):
            arr = np.ascontiguousarray(tb["head"][f])
            tman["fields"][f] = emit(arr)
            cols.append(arr.reshape(p * r, -1).view(np.uint8))
        hvc = np.ascontiguousarray(tb["head_vc"], np.int32)
        tman["head_vc"] = emit(hvc)
        cols.append(hvc.reshape(p * r, -1).view(np.uint8))
        sub = np.ascontiguousarray(tb["slots_ub"], np.int32)
        tman["slots_ub"] = emit(sub)
        cols.append(sub.reshape(p * r, -1).view(np.uint8))
        rowmat = np.concatenate(cols, axis=1)
        crc = np.empty(p * r, np.uint32)
        for i in range(p * r):
            crc[i] = zlib.crc32(rowmat[i].tobytes()) & 0xFFFFFFFF
        tman["row_crc"] = emit(crc.reshape(p, r))
        manifest["tables"][tname] = tman
    manifest["bytes"] = off
    manifest["crc32"] = crc_total & 0xFFFFFFFF
    return manifest


class Sidecar:
    """pread-style reader over one published cold sidecar."""

    def __init__(self, path: str, manifest: dict):
        self.path = path
        self.man = manifest
        self._fd: Optional[int] = None

    def _fileno(self) -> int:
        if self._fd is None:
            self._fd = os.open(self.path, os.O_RDONLY)
        return self._fd

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def _pread(self, off: int, n: int) -> bytes:
        data = os.pread(self._fileno(), n, off)
        if len(data) != n:
            raise OSError(f"short sidecar read at {off} ({len(data)}/{n})")
        return data

    def _col_row(self, tman: dict, spec: dict, shard: int,
                 row: int) -> np.ndarray:
        rb = _row_bytes(spec)
        off = spec["off"] + (shard * tman["rows"] + row) * rb
        return np.frombuffer(self._pread(off, rb),
                             np.dtype(spec["dtype"])).reshape(spec["shape"])

    def read_row(self, tname: str, shard: int, row: int) -> dict:
        """One row's (head fields, head_vc, slots_ub), CRC-verified.
        Raises ValueError on a CRC mismatch (the caller types it)."""
        tman = self.man["tables"][tname]
        if not (0 <= row < tman["rows"]):
            raise ValueError(f"sidecar row {row} out of range for {tname}")
        parts: List[bytes] = []
        head = {}
        for f in sorted(tman["fields"]):
            arr = self._col_row(tman, tman["fields"][f], shard, row)
            head[f] = arr
            parts.append(arr.tobytes())
        hvc = self._col_row(tman, tman["head_vc"], shard, row)
        parts.append(hvc.tobytes())
        sub = self._col_row(tman, tman["slots_ub"], shard, row)
        parts.append(sub.tobytes())
        want = int(self._col_row(tman, tman["row_crc"], shard, row))
        got = zlib.crc32(b"".join(parts)) & 0xFFFFFFFF
        if got != want:
            raise ValueError(
                f"sidecar row CRC mismatch for {tname}[{shard},{row}] "
                f"({got:#x} != {want:#x}): bit rot on disk")
        return {"head": head, "head_vc": hvc, "slots_ub": int(sub)}

    def read_head_vc(self, tname: str, shard: int, row: int) -> np.ndarray:
        """Just the stored head_vc stamp (the evictability probe)."""
        tman = self.man["tables"][tname]
        return self._col_row(tman, tman["head_vc"], shard, row)

    def read_column(self, tname: str, name: str) -> np.ndarray:
        """One whole column ``[P, rows, ...]`` in a single bulk read —
        the rebase carry-forward path (never per-row syscalls at scale).
        ``name`` is a head field, ``"head_vc"`` or ``"slots_ub"``."""
        tman = self.man["tables"][tname]
        spec = (tman["fields"][name] if name in tman["fields"]
                else tman[name])
        rb = _row_bytes(spec)
        p = int(self.man["n_shards"])
        data = self._pread(spec["off"], rb * tman["rows"] * p)
        return np.frombuffer(data, np.dtype(spec["dtype"])).reshape(
            [p, tman["rows"]] + list(spec["shape"]))


# ---------------------------------------------------------------------------
# the tier
# ---------------------------------------------------------------------------
class ColdRef:
    """Where a key's head state lives on disk: (tiered table, shard,
    sidecar row) inside one retained full image (``src`` = ckpt id, or a
    string token for a staged import — follower bootstrap)."""

    __slots__ = ("tname", "shard", "srow", "src")

    def __init__(self, tname: str, shard: int, srow: int, src):
        self.tname = tname
        self.shard = int(shard)
        self.srow = int(srow)
        self.src = src

    def __repr__(self):
        return f"ColdRef({self.tname}, {self.shard}, {self.srow}, {self.src})"


class ColdTier:
    """Per-store cold-tier manager (see module docstring)."""

    #: rows evicted per commit-path cycle at most (bounds the lock hold)
    EVICT_BATCH = 4096
    #: LRU entries probed per cycle at most (skips are re-queued)
    SCAN_CAP = 16384

    def __init__(self, store, budget: int = 0,
                 fault_rate_cap: float = 0.0, lock=None):
        self.store = store
        #: resident device-row budget; 0 = unbounded (fault-in only)
        self.budget = int(budget)
        #: admitted fault-ins per second past which reads are refused
        #: with a typed ColdMiss (0 = unlimited)
        self.fault_rate_cap = float(fault_rate_cap)
        self.lock = lock if lock is not None else threading.RLock()
        #: dk -> ColdRef for every key a retained full image covers (cold
        #: keys authoritative; resident keys keep theirs as evict hints)
        self.refs: Dict[Tuple[Any, str], ColdRef] = {}
        #: keys currently COLD (no device row, no directory entry)
        self.cold_set: set = set()
        #: shard -> set of cold dks (digest / handoff sweeps)
        self.by_shard: Dict[int, set] = {}
        #: write-LRU over RESIDENT keys (move_to_end on write/birth)
        self.lru: "OrderedDict[Tuple[Any, str], None]" = OrderedDict()
        #: keys whose sidecar rows are verifiably lost (typed-permanent)
        self.lost: set = set()
        #: the newest full image id refs were rebound to (evict anchor)
        self.anchor: Optional[int] = None
        #: extra sidecar sources: token -> Sidecar (staged imports)
        self._extra_sources: Dict[str, Sidecar] = {}
        self._sidecars: Dict[Any, Sidecar] = {}
        #: keys probed NOT-evictable against the current anchor (written
        #: since its stamp): within one anchor that can never change, so
        #: probe each at most once instead of re-preading every cycle
        self._probed_dirty: set = set()
        #: called when the budget cannot be met (checkpointer.request)
        self.on_pressure = None
        #: called when a fault-in caught on-disk corruption (scrub nudge)
        self.on_corrupt = None
        self.evictions = 0
        self.faults = 0
        self.refused = 0
        self._fault_window_t0 = time.monotonic()
        self._fault_window_n = 0
        self._fault_streak = 0
        #: resolved once (recovery's replay detaches store.log while it
        #: applies the tail — fault-ins must keep working through that)
        self._log_dir: Optional[str] = (store.log.dir
                                        if store.log is not None else None)

    # -- metrics helper -------------------------------------------------
    def _count(self, event: str, n: int = 1) -> None:
        m = getattr(self.store, "metrics", None)
        if m is not None:
            m.coldtier_events.inc(n, event=event)

    def _gauges(self) -> None:
        m = getattr(self.store, "metrics", None)
        if m is not None:
            m.coldtier_resident_rows.set(self.resident_rows())
            m.coldtier_cold_keys.set(len(self.cold_set))

    # -- sources --------------------------------------------------------
    def _sidecar(self, src) -> Sidecar:
        sc = self._sidecars.get(src)
        if sc is not None:
            return sc
        if isinstance(src, str):
            sc = self._extra_sources.get(src)
            if sc is None:
                raise ColdMiss(
                    f"cold source {src!r} is gone (staged import already "
                    "consumed); retry after the local rebase",
                    retry_after_ms=250)
        else:
            from antidote_tpu.log import checkpoint as _ckpt

            if self._log_dir is None:
                assert self.store.log is not None, \
                    "cold tier needs a durable log dir"
                self._log_dir = self.store.log.dir
            root = _ckpt.checkpoint_root(self._log_dir)
            path = os.path.join(root, f"ckpt_{int(src)}")
            man = _ckpt.load_manifest(path)
            if man is None or "cold" not in man:
                raise ColdMiss(
                    f"checkpoint image ckpt_{src} (the cold anchor) is "
                    "no longer published; retry after the next rebase",
                    retry_after_ms=250)
            cman = dict(man["cold"])
            cman.setdefault("n_shards", self.store.cfg.n_shards)
            sc = Sidecar(os.path.join(path, COLD_BIN), cman)
        self._sidecars[src] = sc
        return sc

    def add_source(self, token: str, path: str, manifest: dict) -> None:
        """Register a staged sidecar source (follower bootstrap: the
        fetched owner sidecar, consumed by the next local rebase)."""
        cman = dict(manifest)
        cman.setdefault("n_shards", self.store.cfg.n_shards)
        self._extra_sources[token] = Sidecar(path, cman)

    def drop_source(self, token: str) -> None:
        sc = self._extra_sources.pop(token, None)
        if sc is not None:
            sc.close()
        self._sidecars.pop(token, None)

    def _drop_sidecar_cache(self) -> None:
        for sc in self._sidecars.values():
            sc.close()
        self._sidecars = {}

    # -- bookkeeping hooks ---------------------------------------------
    def note_birth(self, dk) -> None:
        self.lru[dk] = None
        self.lru.move_to_end(dk)

    def note_writes(self, dks) -> None:
        lru = self.lru
        for dk in dks:
            lru[dk] = None
            lru.move_to_end(dk)

    def drop_shard(self, shard: int) -> None:
        """Forget a relinquished shard's cold refs (the rows now live at
        the handoff receiver)."""
        with self.lock:
            for dk in self.by_shard.pop(int(shard), set()):
                self.cold_set.discard(dk)
                self.refs.pop(dk, None)
            for dk in [d for d, r in self.refs.items()
                       if r.shard == int(shard)]:
                self.refs.pop(dk, None)
                self.lru.pop(dk, None)

    def resident_rows(self) -> int:
        return sum(t.resident_rows() for t in self.store.tables.values())

    def is_cold(self, dk) -> bool:
        # lost keys stay "cold" forever: their fault-in raises the
        # typed-permanent ColdMiss — a directory miss must NEVER decay
        # into a silent bottom read for a key that once held data
        return dk in self.cold_set or dk in self.lost

    def shard_cold_keys(self, shard: int):
        return self.by_shard.get(int(shard), frozenset())

    # -- rebind after a full publish ------------------------------------
    def rebind(self, ckpt_id: int, resident_map: Dict, cold_rebinds: Dict,
               lost: Optional[set] = None) -> None:
        """Re-anchor every ref onto the freshly-published full image:
        ``resident_map`` maps resident-at-stamp dks to their image
        coordinates (bounded by the resident budget), ``cold_rebinds``
        maps still-cold dks to their appendix coordinates.  Keys the new
        image could not carry (unreadable source rows) arrive in
        ``lost`` and are tombstoned — their reads go typed-permanent,
        never bottom."""
        with self.lock:
            for dk, (tname, shard, srow) in resident_map.items():
                self.refs[dk] = ColdRef(tname, shard, srow, ckpt_id)
            for dk, (tname, shard, srow) in cold_rebinds.items():
                self.refs[dk] = ColdRef(tname, shard, srow, ckpt_id)
            if lost:
                for dk in lost:
                    self.refs.pop(dk, None)
                    self.cold_set.discard(dk)
                    self.lost.add(dk)
                    for s in self.by_shard.values():
                        s.discard(dk)
                self._count("lost", len(lost))
                log.error(
                    "cold tier: %d key(s) LOST to sidecar bit rot during "
                    "the rebase; their reads now fail typed-permanent "
                    "(repair: re-bootstrap this store from a peer)",
                    len(lost))
            self.anchor = int(ckpt_id)
            self._probed_dirty.clear()  # fresh anchor: re-probe
            self._drop_sidecar_cache()
            self._gauges()

    def seed_hints(self, src) -> None:
        """After a full-image install (recovery): every resident key's
        directory entry IS its sidecar coordinate — register them as
        evict hints so the post-recovery budget pass (and later
        commit-path eviction) has candidates.  Rows later overlaid by
        chain links or the WAL tail fail the head_vc equality probe and
        simply stay resident."""
        with self.lock:
            for dk, ent in self.store.directory.items():
                self.refs[dk] = ColdRef(ent[0], ent[1], ent[2], src)
                self.lru[dk] = None
            if not isinstance(src, str):
                self.anchor = int(src)

    def seed(self, entries, src) -> None:
        """Register cold keys from a recovered/installed image's
        ``cold_directory`` (``entries``: [key, bucket, tname, shard,
        srow] rows; ``src``: the image id or staged-source token)."""
        from antidote_tpu.store.kv import freeze_key

        with self.lock:
            for key, bucket, tname, shard, srow in entries:
                dk = (freeze_key(key), bucket)
                self.refs[dk] = ColdRef(tname, int(shard), int(srow), src)
                self.cold_set.add(dk)
                self.by_shard.setdefault(int(shard), set()).add(dk)
            if not isinstance(src, str):
                self.anchor = int(src)
            self._gauges()

    def cold_manifest(self) -> Dict[str, Dict[int, list]]:
        """The rebase carry-forward worklist, captured under the lock:
        {tiered name: {shard: [(dk, srow, src), ...]}} for every
        currently-cold key."""
        out: Dict[str, Dict[int, list]] = {}
        for dk in self.cold_set:
            ref = self.refs[dk]
            out.setdefault(ref.tname, {}).setdefault(ref.shard, []).append(
                (dk, ref.srow, ref.src))
        return out

    # -- fault-in -------------------------------------------------------
    def _admit_fault(self) -> None:
        if self.fault_rate_cap <= 0:
            self._fault_streak = 0
            return
        now = time.monotonic()
        if now - self._fault_window_t0 >= 1.0:
            self._fault_window_t0 = now
            self._fault_window_n = 0
        if self._fault_window_n >= self.fault_rate_cap:
            self._fault_streak += 1
            self.refused += 1
            self._count("refused")
            raise ColdMiss(
                f"cold-tier fault rate cap ({self.fault_rate_cap}/s) "
                "exceeded; the key stays cold this round",
                retry_after_ms=retry_hint_ms(self._fault_streak))
        self._fault_window_n += 1
        self._fault_streak = 0

    def fault_in(self, dk, admit: bool = True):
        """Fault one cold key's device row back in; returns the fresh
        directory entry.  Caller must hold the store's commit lock (the
        tier's ``lock`` is re-entrant and re-taken here)."""
        with self.lock:
            ent = self.store.directory.get(dk)
            if ent is not None:
                return ent  # raced: someone else faulted it in
            if dk in self.lost:
                raise ColdMiss(
                    f"cold key {dk!r}: its sidecar row was lost to bit "
                    "rot on every retained image — restore this store "
                    "from a peer/follower", retry_after_ms=60000,
                    permanent=True)
            ref = self.refs.get(dk)
            if ref is None or dk not in self.cold_set:
                raise KeyError(f"{dk!r} is not a cold key")
            if admit:
                self._admit_fault()
            d = faults.hit("coldtier.fault", key=ref.tname)
            if d is not None:
                if d.action == "delay" and d.arg:
                    time.sleep(float(d.arg))
                elif d.action in ("error", "io_error", "enospc"):
                    self.refused += 1
                    self._count("refused")
                    raise ColdMiss(
                        f"injected fault: coldtier.fault {dk!r}",
                        retry_after_ms=50)
            try:
                rowdata = self._sidecar(ref.src).read_row(
                    ref.tname, ref.shard, ref.srow)
            except ValueError as e:
                # on-disk corruption caught by the per-row CRC: typed
                # refusal + nudge the scrubber (a forced rebase re-reads
                # every row and tombstones the truly lost ones)
                self._count("crc_fail")
                cb = self.on_corrupt
                if cb is not None:
                    cb()
                raise ColdMiss(
                    f"cold fault-in for {dk!r} failed verification "
                    f"({e}); a rebase was requested — retry after it",
                    retry_after_ms=500) from e
            except OSError as e:
                self.refused += 1
                self._count("refused")
                raise ColdMiss(
                    f"cold fault-in for {dk!r} hit an I/O error ({e})",
                    retry_after_ms=100) from e
            t = self.store.table(ref.tname)
            row = t.alloc_row(ref.shard)
            t.install_rows(
                np.asarray([ref.shard]), np.asarray([row]),
                {f: x[None] for f, x in rowdata["head"].items()},
                rowdata["head_vc"][None],
            )
            t.slots_ub[ref.shard, row] = rowdata["slots_ub"]
            ent = (ref.tname, ref.shard, row)
            self.store.directory[dk] = ent
            self.cold_set.discard(dk)
            s = self.by_shard.get(ref.shard)
            if s is not None:
                s.discard(dk)
            self.note_birth(dk)
            self.store._ckpt_evicted.pop(dk, None)  # resident again
            # the (possibly reused) row must not serve from any frozen
            # epoch buffer: same discipline as a tier promotion
            self.store.mark_epoch_fallback(dk)
            self.faults += 1
            self._count("fault")
            self._gauges()
            return ent

    def fault_in_shard(self, shard: int) -> int:
        """Fault in every cold key of one shard (handoff export /
        relinquish sweeps run on whole-shard state).  Bypasses the rate
        cap — these are operator-paced paths."""
        n = 0
        for dk in list(self.shard_cold_keys(shard)):
            self.fault_in(dk, admit=False)
            n += 1
        return n

    # -- eviction -------------------------------------------------------
    def maybe_evict(self) -> int:
        """Commit-path budget enforcement: when resident rows exceed the
        budget, evict the coldest ELIGIBLE keys (live head_vc byte-equal
        to the anchor sidecar's stamp) in one bounded batch.  Returns
        rows evicted.  No-op (cheap) under budget."""
        if self.budget <= 0:
            return 0
        over = self.resident_rows() - self.budget
        if over <= 0:
            return 0
        return self.evict_now(max_rows=min(over, self.EVICT_BATCH))

    def enforce_budget(self) -> int:
        """Evict in bounded batches until the budget holds or nothing
        more is eligible (recovery's post-install pass: a beyond-RAM
        restart must not serve with the whole image resident)."""
        total = 0
        while self.budget > 0:
            over = self.resident_rows() - self.budget
            if over <= 0:
                break
            n = self.evict_now(max_rows=min(over, self.EVICT_BATCH))
            total += n
            if n == 0:
                break  # everything left is dirty/uncovered
        return total

    def evict_now(self, max_rows: int = EVICT_BATCH) -> int:
        """Evict up to ``max_rows`` of the coldest eligible keys."""
        with self.lock:
            if self.anchor is None:
                cb = self.on_pressure
                if cb is not None:
                    cb()
                return 0
            try:
                sc = self._sidecar(self.anchor)
            except ColdMiss:
                return 0
            picked: Dict[str, list] = {}  # tname -> [(dk, shard, row)]
            n_picked = 0
            scanned = 0
            hvc_cache: Dict[str, np.ndarray] = {}
            for dk in list(self.lru):
                if n_picked >= max_rows or scanned >= self.SCAN_CAP:
                    break
                scanned += 1
                if dk in self._probed_dirty:
                    # already proved unevictable against THIS anchor (a
                    # row only gets dirtier within one anchor): no
                    # re-pread until the next stamp re-anchors
                    self.lru.move_to_end(dk)
                    continue
                ref = self.refs.get(dk)
                ent = self.store.directory.get(dk)
                if ent is None:
                    self.lru.pop(dk, None)  # already gone/cold
                    continue
                if (ref is None or ref.src != self.anchor
                        or ref.tname != ent[0] or ref.shard != ent[1]):
                    # not covered by the anchor image (born/promoted
                    # since the stamp): re-queue behind the hot end so
                    # the scan makes progress
                    self._probed_dirty.add(dk)
                    self.lru.move_to_end(dk)
                    continue
                tname, shard, row = ent
                hvc = hvc_cache.get(tname)
                if hvc is None:
                    t = self.store.table(tname)
                    hvc = np.asarray(t.head_vc)
                    hvc_cache[tname] = hvc
                try:
                    stored = sc.read_head_vc(tname, ref.shard, ref.srow)
                except (OSError, ValueError, KeyError):
                    self._probed_dirty.add(dk)
                    self.lru.move_to_end(dk)
                    continue
                if not np.array_equal(hvc[shard, row], stored):
                    # written since the stamp: not evictable yet
                    self._probed_dirty.add(dk)
                    self.lru.move_to_end(dk)
                    continue
                picked.setdefault(tname, []).append((dk, shard, row))
                n_picked += 1
            evicted = 0
            for tname, items in picked.items():
                t = self.store.table(tname)
                t.evict_rows(np.asarray([x[1] for x in items]),
                             np.asarray([x[2] for x in items]))
                for dk, shard, _row in items:
                    ref = self.refs[dk]
                    self.store.directory.pop(dk, None)
                    self.lru.pop(dk, None)
                    self.cold_set.add(dk)
                    self.by_shard.setdefault(shard, set()).add(dk)
                    self.store.mark_epoch_fallback(dk)
                    self.store.drop_cached_value(dk)
                    # record the transition for the incremental chain: a
                    # composed recovery must re-register the key cold
                    # instead of resurrecting the (now reusable) row
                    self.store._ckpt_evicted[dk] = (
                        ref.tname, ref.shard, ref.srow, ref.src)
                evicted += len(items)
            if evicted:
                self.evictions += evicted
                self._count("evict", evicted)
                self._gauges()
            if self.resident_rows() > self.budget and evicted < max_rows:
                # could not reach the budget (everything hot/dirty):
                # ask for a stamp so the next cycle has coverage
                cb = self.on_pressure
                if cb is not None:
                    cb()
            return evicted

    # -- observability --------------------------------------------------
    def status(self) -> dict:
        return {
            "budget": self.budget,
            "resident_rows": self.resident_rows(),
            "cold_keys": len(self.cold_set),
            "lost_keys": len(self.lost),
            "anchor_image": self.anchor,
            "evictions": self.evictions,
            "faults": self.faults,
            "refused": self.refused,
            "fault_rate_cap": self.fault_rate_cap,
        }


__all__ = ["ColdTier", "ColdRef", "Sidecar", "write_sidecar", "COLD_BIN"]
